"""Turns the master's task queue into one continuous record stream.

Role parity with the reference's worker-side task data service
(reference worker/task_data_service.py): the worker sees a single
iterable of records, while underneath this service pulls shard tasks
from the master on demand, remembers which tasks the consumed records
belong to, and acknowledges each task back to the master once the
worker has burned through its record range.  Control tasks are handled
inline: a WAIT ends the current stream so the worker re-polls later,
and a SAVE_MODEL is parked for the export path instead of being fed to
training.

The implementation is this repo's own: completion accounting lives in a
small in-flight ledger (`_drain_acknowledged`) keyed by a running
record cursor, rather than the reference's inline while-loop, and the
stream itself is a plain generator handed to the repo's tf-free
`Dataset` shim (data/dataset.py).

Pipelined input plane (docs/input_pipeline.md):

- ``task_prefetch=N`` runs a background fetcher thread that keeps up to
  N shard tasks fetched ahead of the one being consumed — the master
  RPC round trip and the cold first-record read of task N+1 overlap the
  consumption of task N. The fetcher is a full participant in the
  ``_round_id`` abandonment protocol: a spare park
  (``requeue_inflight``) hands every prefetched-but-unconsumed task
  back to the master exactly once.
- ``ack_queue_size=M`` moves task acknowledgment RPCs off the hot loop:
  completed tasks queue on a bounded ack queue drained at task/eval/
  checkpoint boundaries (``drain_acks``; same boundary discipline as
  the worker's ``_drain_ps_pushes``). Failure acks bypass the queue —
  the master must requeue a failed task promptly.
"""

import concurrent.futures
import itertools
import queue
import threading
import time
from collections import deque

from elasticdl_tpu.common.constants import TaskExecCounterKey, TaskType
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.data.data_reader import create_data_reader
from elasticdl_tpu.data.dataset import Dataset, create_dataset_from_tasks
from elasticdl_tpu.data.input_stats import InputPlaneStats
from elasticdl_tpu.utils import profiling

_ABANDON_MSG = "round abandoned (spare park)"
_SENTINEL = object()


def _task_span(task):
    """Number of records a shard task covers."""
    return task.end - task.start


class _TaskFetcher:
    """Background task prefetcher for ONE stream round.

    Keeps up to ``depth`` tasks fetched ahead of the consumer: a single
    fetch thread pulls tasks from the master in order and parks them on
    an in-order queue, while a small warm pool reads each fetched
    task's first ``prefetch_warm_records`` records CONCURRENTLY — the
    cold reads of tasks N+1..N+depth overlap the consumption of task N
    (and each other) instead of riding the consumer's critical path.
    The queue itself is unbounded — depth is enforced by a semaphore
    the consumer releases as it pops — so fetcher puts never block (no
    abandoned-consumer put leak by construction;
    scripts/greps_guard.py).

    Abandonment: ``shutdown`` (idempotent, called by both the consumer
    generator's close and ``requeue_inflight``) cancels the fetch loop
    and hands every queued-but-unconsumed shard task back to the master
    exactly once. A fetch mid-``get_task`` when the round is abandoned
    notices the ``_round_id`` bump on return and hands its task back
    itself — the same step-aside protocol the serial producer pins in
    tests.
    """

    def __init__(self, service, gen_id, depth):
        self._service = service
        self._gen_id = gen_id
        self._q = queue.Queue()
        self._slots = threading.Semaphore(max(1, depth))
        self._cancel = threading.Event()
        # serializes puts against shutdown's cancel+drain so no item can
        # land in the queue after the final drain (exactly-once hand-back)
        self._offer_lock = threading.Lock()
        # one warm per in-flight task plus the one being consumed
        self._warm_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max(1, depth) + 1,
            thread_name_prefix="edl-task-warm",
        )
        self._thread = threading.Thread(
            target=self._fetch_loop,
            daemon=True,
            name="edl-task-fetcher",
        )

    def start(self):
        self._thread.start()

    def _offer(self, item):
        """Enqueue ``item`` unless the round was already shut down."""
        with self._offer_lock:
            if self._cancel.is_set():
                return False
            self._q.put(item)
            return True

    def _fetch_loop(self):
        service = self._service
        try:
            while not self._cancel.is_set():
                if not self._slots.acquire(timeout=0.2):
                    continue  # consumer still working the window; re-check cancel
                with service._ledger_lock:
                    task = service._primed_task
                    service._primed_task = None
                if task is None:
                    task = service._worker.get_task()
                with service._ledger_lock:
                    stale = service._round_id != self._gen_id
                if stale or self._cancel.is_set():
                    # round abandoned while this fetch was in flight:
                    # hand the task straight back (appending it would
                    # leak it in the master's doing-set)
                    self._hand_back(task)
                    return
                records = None
                if task.shard_name and task.type != TaskType.SAVE_MODEL:
                    # warm asynchronously: the fetch loop moves straight
                    # on to the NEXT get_task while this task's head
                    # records are read in the pool
                    try:
                        records = self._warm_pool.submit(
                            service._warm_records, task
                        )
                    except RuntimeError:
                        # shutdown closed the pool between our stale
                        # check and here: the round is being abandoned —
                        # this task must go back like any other
                        self._hand_back(task)
                        return
                if not self._offer((task, records)):
                    self._hand_back(task)
                    return
                if not task.shard_name:
                    return  # WAIT/exhausted ends the round's fetching
        except BaseException as e:  # propagate into the consumer
            self._offer(e)

    def _hand_back(self, task):
        if task is not None and task.shard_name:
            self._service._worker.report_task_result(
                task.task_id, _ABANDON_MSG
            )

    def next_item(self):
        """The next fetched (task, records) in fetch order; None once the
        round is shut down. Re-raises a fetcher-side exception (a failed
        ``get_task`` or a failed warm read, in order)."""
        while True:
            try:
                item = self._q.get(timeout=0.2)
            except queue.Empty:
                if self._cancel.is_set():
                    return None
                continue
            if isinstance(item, BaseException):
                raise item
            self._slots.release()
            task, warm = item
            if warm is None:
                return task, None
            # resolve the warm future: usually already done (the pool
            # read it while earlier tasks were consumed); .result()
            # re-raises a reader error at the right task position
            try:
                records = warm.result()
            except concurrent.futures.CancelledError:
                # shutdown's cancel_futures beat this pop's resolution:
                # the round is being abandoned — hand the task back and
                # end the stream quietly (not a reader error)
                self._hand_back(task)
                return None
            except BaseException:
                # the task was popped but never reached the ledger, so
                # neither shutdown's drain nor requeue_inflight can see
                # it: hand it back HERE or it leaks in the master's
                # doing-set (another worker retries the read)
                self._service._worker.report_task_result(
                    task.task_id, "prefetch read failed"
                )
                raise
            return task, records

    def shutdown(self):
        """Cancel the fetch loop and hand back every queued task.

        Idempotent and shared by the consumer generator's close and
        ``requeue_inflight``: queue pops are atomic, so however many
        callers race here each task is reported back exactly once.
        """
        with self._offer_lock:
            self._cancel.set()
        # no new puts can land past this point; drain what's queued
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if isinstance(item, BaseException):
                continue
            task, _ = item
            self._hand_back(task)
        # in-flight warm reads finish and are dropped; nothing new starts
        self._warm_pool.shutdown(wait=False, cancel_futures=True)


class TaskDataService:
    """One worker's bridge between master tasks and its input stream.

    The worker object passed in must expose ``get_task()`` and
    ``report_task_result(task_id, err_msg, exec_counters=)`` — the same
    two calls every worker runtime in this repo already makes over the
    master channel.
    """

    def __init__(
        self,
        worker,
        training_with_evaluation,
        data_reader_params=None,
        task_prefetch=0,
        ack_queue_size=0,
        prefetch_warm_records=32,
        data_reader=None,
        stats=None,
    ):
        self._worker = worker
        self._training_with_evaluation = training_with_evaluation
        self._ledger_lock = threading.Lock()
        self._stream_open = True  # may get_dataset() hand out a new stream?
        self._parked_export_task = None
        self._clear_ledger()
        if data_reader is not None:
            # injected reader (tests/bench fault injection)
            self.data_reader = data_reader
        else:
            reader_kwargs = dict(data_reader_params or {})
            self.data_reader = create_data_reader(
                data_origin=reader_kwargs.pop("data_origin", None),
                **reader_kwargs,
            )
        # First task is peeked once to prime reader metadata, then replayed
        # into the stream so no records are lost.
        self._primed_task = None
        self._metadata_primed = False
        # bumped (under the ledger lock) whenever an open round is
        # abandoned wholesale; stale producers notice and step aside
        self._round_id = 0
        # pipelined input plane (docs/input_pipeline.md)
        self._task_prefetch = max(0, int(task_prefetch))
        # how many of a prefetched task's records the fetcher reads
        # ahead (bounds prefetch memory at task_prefetch * this many
        # records); the rest stream on the consumer as before
        self._prefetch_warm_records = max(0, int(prefetch_warm_records))
        self._fetcher = None  # the open round's _TaskFetcher, if any
        self._ack_queue_size = max(0, int(ack_queue_size))
        self._ack_queue = deque()
        self._ack_lock = threading.Lock()
        # set (under the ledger lock) when a failure ack was queued;
        # report_record_done flushes right after releasing that lock
        self._ack_flush_needed = False
        self.stats = stats if stats is not None else InputPlaneStats()

    # ------------------------------------------------------------------
    # in-flight ledger
    # ------------------------------------------------------------------

    def _clear_ledger(self):
        self._inflight = deque()  # tasks whose records are being consumed
        self._record_cursor = 0  # records consumed against head of ledger
        self._bad_records = 0  # failed records charged to the head task

    def get_current_task(self):
        return self._inflight[0] if self._inflight else None

    def remaining_records_in_head_task(self):
        """Unconsumed record count of the ledger's head task (0 if empty).

        A failed train step calls report_record_done with exactly this
        amount to finish + fail-report the task it was on, without
        spilling the charge into tasks queued behind it.
        """
        with self._ledger_lock:
            if not self._inflight:
                return 0
            return max(0, _task_span(self._inflight[0]) - self._record_cursor)

    def _acknowledge(self, task, err_msg, outbox):
        """Queue one finished task's acknowledgment (ledger lock held).

        Never sends from here: the caller holds the ledger lock, and a
        master RPC under it would stall the fetcher's round checks and
        any concurrent spare-park requeue for a full round trip (edlint
        R5 pinned exactly this chain). With ``ack_queue_size`` > 0 a
        SUCCESS ack joins the bounded queue drained at boundaries (or
        on overflow); otherwise it lands in the caller's ``outbox`` and
        is sent right after the lock is released — the same
        snapshot-then-release pattern ps/servicer.pull_variable uses.
        Failure acks still flush promptly: the master must requeue a
        failed task, and the flush preserves ack order.
        """
        counters = (
            {TaskExecCounterKey.FAIL_COUNT: self._bad_records}
            if self._bad_records
            else None
        )
        t0 = getattr(task, "_edl_consume_t0", None)
        if t0 is not None:
            # worker-side half of the task timeline: first-ledger-append
            # to ack wall time rides the exec counters so the master's
            # task_done event carries both clocks
            counters = dict(counters or {})
            counters["consume_s"] = round(time.perf_counter() - t0, 6)
        trace = (getattr(task, "extended_config", None) or {}).get(
            "trace_id"
        )
        if trace is not None:
            # master recovery plane (docs/master_recovery.md): the ack
            # names the dispatcher's trace so a RELAUNCHED master (task
            # ids re-minted, this ack replayed through the failover
            # channel) can resolve it to the journaled task and dedup a
            # completion the dead incarnation already counted
            counters = dict(counters or {})
            counters[TaskExecCounterKey.TRACE_ID] = trace
            counters[TaskExecCounterKey.ATTEMPT] = task.extended_config.get(
                "_attempt", 0
            )
        if err_msg:
            logger.warning(
                "task %d finished with %d/%d bad records; last error: %s",
                task.task_id,
                self._bad_records,
                _task_span(task),
                err_msg,
            )
        self._bad_records = 0
        if self._ack_queue_size:
            # append only — _acknowledge runs under the ledger lock, and
            # an inline drain here would hold that lock across up to
            # queue-size master RPCs, stalling the fetcher's round
            # checks and a concurrent spare-park requeue. The caller
            # (report_record_done) drains AFTER releasing the ledger
            # lock, on overflow or (immediately) behind a failure; FIFO
            # order keeps earlier successes landing before the failure.
            with self._ack_lock:
                self._ack_queue.append((task.task_id, err_msg, counters))
            if err_msg:
                self._ack_flush_needed = True
            return
        outbox.append((task.task_id, err_msg, counters))

    def drain_acks(self):
        """Send every queued task acknowledgment to the master.

        Called at task/eval/checkpoint boundaries (the worker's
        ``_drain_ps_pushes`` discipline), on ack-queue overflow, before
        a failure ack, and by ``requeue_inflight`` before it
        fail-reports the in-flight set. Pops are atomic, so concurrent
        drains send disjoint acks — each exactly once, in order.
        """
        while True:
            with self._ack_lock:
                if not self._ack_queue:
                    return
                task_id, err_msg, counters = self._ack_queue.popleft()
            with profiling.span(
                "task/ack",
                trace_id=(counters or {}).get(TaskExecCounterKey.TRACE_ID),
            ), self.stats.timed("ack_s"):
                self._worker.report_task_result(
                    task_id, err_msg, exec_counters=counters
                )

    def _drain_acknowledged(self, err_msg, outbox):
        """Pop every ledger task the cursor has moved past, queueing its
        ack (bounded ack queue or the caller's ``outbox``).

        One batch can straddle several small tasks, so a single cursor
        advance may complete more than one; any failure tally rides out
        with the first task drained.
        """
        while self._inflight and self._record_cursor >= _task_span(
            self._inflight[0]
        ):
            done = self._inflight.popleft()
            self._record_cursor -= _task_span(done)
            self._acknowledge(done, err_msg, outbox)

    def report_record_done(self, count, err_msg=""):
        """Advance the cursor by ``count`` consumed records."""
        outbox = []
        with self._ledger_lock:
            self._record_cursor += count
            if err_msg:
                self._bad_records += count
            self._drain_acknowledged(err_msg, outbox)
        # inline acks go out AFTER the ledger lock is released: the
        # tasks are already popped, so a racing requeue_inflight cannot
        # double-report them, and the RPC no longer serializes the
        # fetcher/requeue paths behind a master round trip
        for task_id, msg, counters in outbox:
            with profiling.span(
                "task/ack",
                trace_id=(counters or {}).get(TaskExecCounterKey.TRACE_ID),
            ), self.stats.timed("ack_s"):
                self._worker.report_task_result(
                    task_id, msg, exec_counters=counters
                )
        if self._ack_queue_size:
            # backpressure OUTSIDE the ledger lock: completed-but-unacked
            # tasks must not pile up in the master's doing-set past the
            # bound — and a failure ack flushes the queue right here,
            # still within the caller's report_record_done — but the
            # drain RPCs must not serialize the ledger
            flush = self._ack_flush_needed
            self._ack_flush_needed = False
            with self._ack_lock:
                overflow = len(self._ack_queue) > self._ack_queue_size
            if overflow or flush:
                self.drain_acks()

    def requeue_inflight(self, err_msg):
        """Fail-report every in-flight (and primed) task — the master
        requeues them for other workers — and abandon the open record
        stream so the next ``get_dataset`` starts a clean round.

        A worker parked as an elastic SPARE cannot rewind its stream:
        the round's generator is mid-``read_records`` and ``prefetch``
        still buffers records of the tasks being handed back, so
        advancing the old stream after a requeue would charge leftover
        records against the NEXT ledger task (acknowledging work that
        never trained, double-training the requeued task elsewhere).
        Dropping the whole round is the only consistent cut. Bumping
        ``_round_id`` under the lock tells a producer thread mid-
        ``get_task`` to hand its fresh task straight back instead of
        appending to the cleared ledger (see ``_record_stream``); the
        abandoned producer itself is cancelled by prefetch when the
        consumer generator is dropped. With task prefetch the round's
        fetcher is shut down here too: every prefetched-but-unconsumed
        task is handed back exactly once (fetcher ``shutdown``), and a
        fetch mid-``get_task`` steps aside via the round bump."""
        with self._ledger_lock:
            self._round_id += 1
            inflight = list(self._inflight)
            self._clear_ledger()
            if self._primed_task is not None:
                # pulled for metadata priming, never consumed: it is in
                # the master's "doing" set and must go back too
                inflight.append(self._primed_task)
                self._primed_task = None
            fetcher, self._fetcher = self._fetcher, None
        # queued success acks first: they are for OTHER (completed)
        # tasks and must not be lost behind the fail-reports
        self.drain_acks()
        if fetcher is not None:
            fetcher.shutdown()
        for task in inflight:
            self._worker.report_task_result(task.task_id, err_msg)
        self._stream_open = True

    # ------------------------------------------------------------------
    # dataset construction
    # ------------------------------------------------------------------

    def get_validation_dataset(self, eval_task):
        """(dataset, model_version, task_id) for one eval task, or None."""
        if not eval_task:
            return None
        return (
            create_dataset_from_tasks([eval_task], self.data_reader),
            eval_task.model_version,
            eval_task.task_id,
        )

    def get_save_model_task_and_dataset(self):
        task, self._parked_export_task = self._parked_export_task, None
        if task is None:
            return None, None
        return task, create_dataset_from_tasks([task], self.data_reader)

    def _prime_reader_metadata(self):
        """Peek the first task so the reader can expose its metadata.

        Only a single record is pulled (enough for the reader to learn
        schema/shape info); the task itself is replayed by the stream so
        its records still reach training.
        """
        if self._metadata_primed:
            return
        task = self._worker.get_task()
        if task.shard_name:
            with self._ledger_lock:
                self._primed_task = task
            for _ in self.data_reader.read_records(task):
                break
        self._metadata_primed = True

    def get_dataset(self):
        """A Dataset spanning every task the master will hand out, or None."""
        if not self._stream_open:
            return None
        # a new round starts with an empty ack queue: the master must
        # see the previous round's completions before new work is pulled
        self.drain_acks()
        with self._ledger_lock:
            if self._inflight:
                logger.error(
                    "refusing a new dataset: %d in-flight tasks are still "
                    "unacknowledged",
                    len(self._inflight),
                )
                return None
            self._clear_ledger()
        self._prime_reader_metadata()
        self._stream_open = False
        return Dataset.from_generator(self._record_stream, stats=self.stats)

    def _warm_records(self, task, warm=None):
        """A record iterator for ``task`` with the first ``warm`` records
        already read — the cold read (file open / first page) happens on
        the caller's (fetcher) thread, off the consumer's critical path.

        Readers in this repo are stateless per read (mmap-backed
        recordio; ODPS opens a slice per call), so warming task N+1
        while task N's records are being consumed is safe: each task
        owns its own iterator and only one thread at a time advances it.
        """
        if warm is None:
            warm = self._prefetch_warm_records
        it = iter(self.data_reader.read_records(task))
        head = []
        # the dispatcher's trace id labels the prefetch-warm span, so a
        # profiler timeline joins this read to the same task's train
        # span on the consumer thread (docs/observability.md)
        from elasticdl_tpu.utils.profiling import annotate

        trace_id = (getattr(task, "extended_config", None) or {}).get(
            "trace_id", "untraced"
        )
        with annotate("edl/task/%s/warm" % trace_id), profiling.span(
            "task/warm", trace_id=trace_id, records=warm
        ), self.stats.timed("read_s"):
            for _ in range(max(0, warm)):
                rec = next(it, _SENTINEL)
                if rec is _SENTINEL:
                    return iter(head)
                head.append(rec)
        return itertools.chain(head, it)

    def _append_to_ledger(self, task, gen_id):
        """Append ``task`` to the in-flight ledger; False if the round
        went stale under our feet (the task is handed back instead).

        The round re-check happens under the SAME hold as the append:
        requeue_inflight can bump ``_round_id`` and clear the ledger at
        any point, and an append after that would charge the next
        round's records against a task the master already requeued
        (double-train + wrong accounting).
        """
        with self._ledger_lock:
            stale = self._round_id != gen_id
            if not stale:
                task._edl_consume_t0 = time.perf_counter()
                self._inflight.append(task)
        if stale:
            self._worker.report_task_result(task.task_id, _ABANDON_MSG)
        return not stale

    def _yield_records(self, records):
        """Yield a task's records, charging reader time (not the
        downstream consumer's time) to the read_s counter.

        The per-record timings accumulate in locals and hit the (locked)
        stats object ONCE per task — per-record lock traffic would tax
        exactly the hot loop this plane exists to shrink."""
        stats = self.stats
        it = iter(records)
        read_s = 0.0
        n = 0
        perf = time.perf_counter
        try:
            while True:
                t0 = perf()
                record = next(it, _SENTINEL)
                read_s += perf() - t0
                if record is _SENTINEL:
                    return
                if record is not None:
                    n += 1
                    yield record
        finally:
            stats.add("read_s", read_s)
            stats.count("records", n)

    def _handle_control_task(self, task):
        """WAIT pauses the stream, exhaustion ends it (True = stream
        over); SAVE_MODEL parks for the export path (False = continue)."""
        if not task.shard_name:
            if task.type == TaskType.WAIT:
                # More data may show up (e.g. a lazy next epoch); let
                # the worker loop ask again.
                self._stream_open = True
                logger.info("record stream paused (WAIT); will re-poll")
            else:
                logger.info("task queue exhausted; record stream ends")
            return True
        return False

    def _record_stream(self):
        """Generator: pull tasks until the master says stop, yield records."""
        gen_id = self._round_id
        if self._task_prefetch > 0:
            yield from self._record_stream_prefetched(gen_id)
            return
        while True:
            with self._ledger_lock:
                task, self._primed_task = self._primed_task, None
            if task is None:
                with profiling.span("task/wait"), self.stats.timed(
                    "task_starved_s"
                ):
                    task = self._worker.get_task()
            if self._round_id != gen_id:
                # the round was abandoned (spare park) while this
                # producer was fetching: hand the task straight back —
                # appending it to the cleared ledger would leak it in
                # the master's doing-set forever
                if task.shard_name:
                    self._worker.report_task_result(
                        task.task_id, _ABANDON_MSG
                    )
                return
            if not task.shard_name:
                self._handle_control_task(task)
                return
            if task.type == TaskType.SAVE_MODEL:
                self._parked_export_task = task
                continue
            if not self._append_to_ledger(task, gen_id):
                return
            self.stats.count("tasks")
            yield from self._yield_records(
                self.data_reader.read_records(task)
            )

    def _record_stream_prefetched(self, gen_id):
        """The ``task_prefetch`` consumer: tasks (and their warm first
        records) arrive from the background fetcher in fetch order; this
        generator owns the ledger appends and the control-task handling,
        so the consuming semantics are identical to the serial path."""
        fetcher = _TaskFetcher(self, gen_id, self._task_prefetch)
        with self._ledger_lock:
            if self._round_id != gen_id:
                return  # abandoned before the first record
            self._fetcher = fetcher
        fetcher.start()
        try:
            while True:
                with profiling.span("task/wait"), self.stats.timed(
                    "task_starved_s"
                ):
                    item = fetcher.next_item()
                if item is None:
                    return  # round shut down under us
                task, records = item
                if not task.shard_name:
                    self._handle_control_task(task)
                    return
                if task.type == TaskType.SAVE_MODEL:
                    self._parked_export_task = task
                    continue
                if not self._append_to_ledger(task, gen_id):
                    return
                self.stats.count("tasks")
                yield from self._yield_records(records)
        finally:
            # normal exhaustion, an error, and GC/close of an abandoned
            # consumer all land here; requeue_inflight may already have
            # detached and shut the fetcher down (shutdown is idempotent
            # and hands queued tasks back exactly once either way)
            with self._ledger_lock:
                if self._fetcher is fetcher:
                    self._fetcher = None
            fetcher.shutdown()
