"""Turns the master's task queue into one continuous record stream.

Role parity with the reference's worker-side task data service
(reference worker/task_data_service.py): the worker sees a single
iterable of records, while underneath this service pulls shard tasks
from the master on demand, remembers which tasks the consumed records
belong to, and acknowledges each task back to the master once the
worker has burned through its record range.  Control tasks are handled
inline: a WAIT ends the current stream so the worker re-polls later,
and a SAVE_MODEL is parked for the export path instead of being fed to
training.

The implementation is this repo's own: completion accounting lives in a
small in-flight ledger (`_drain_acknowledged`) keyed by a running
record cursor, rather than the reference's inline while-loop, and the
stream itself is a plain generator handed to the repo's tf-free
`Dataset` shim (data/dataset.py).
"""

import threading
from collections import deque

from elasticdl_tpu.common.constants import TaskExecCounterKey, TaskType
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.data.data_reader import create_data_reader
from elasticdl_tpu.data.dataset import Dataset, create_dataset_from_tasks


def _task_span(task):
    """Number of records a shard task covers."""
    return task.end - task.start


class TaskDataService:
    """One worker's bridge between master tasks and its input stream.

    The worker object passed in must expose ``get_task()`` and
    ``report_task_result(task_id, err_msg, exec_counters=)`` — the same
    two calls every worker runtime in this repo already makes over the
    master channel.
    """

    def __init__(
        self, worker, training_with_evaluation, data_reader_params=None
    ):
        self._worker = worker
        self._training_with_evaluation = training_with_evaluation
        self._ledger_lock = threading.Lock()
        self._stream_open = True  # may get_dataset() hand out a new stream?
        self._parked_export_task = None
        self._clear_ledger()
        reader_kwargs = dict(data_reader_params or {})
        self.data_reader = create_data_reader(
            data_origin=reader_kwargs.pop("data_origin", None),
            **reader_kwargs,
        )
        # First task is peeked once to prime reader metadata, then replayed
        # into the stream so no records are lost.
        self._primed_task = None
        self._metadata_primed = False
        # bumped (under the ledger lock) whenever an open round is
        # abandoned wholesale; stale producers notice and step aside
        self._round_id = 0

    # ------------------------------------------------------------------
    # in-flight ledger
    # ------------------------------------------------------------------

    def _clear_ledger(self):
        self._inflight = deque()  # tasks whose records are being consumed
        self._record_cursor = 0  # records consumed against head of ledger
        self._bad_records = 0  # failed records charged to the head task

    def get_current_task(self):
        return self._inflight[0] if self._inflight else None

    def remaining_records_in_head_task(self):
        """Unconsumed record count of the ledger's head task (0 if empty).

        A failed train step calls report_record_done with exactly this
        amount to finish + fail-report the task it was on, without
        spilling the charge into tasks queued behind it.
        """
        with self._ledger_lock:
            if not self._inflight:
                return 0
            return max(0, _task_span(self._inflight[0]) - self._record_cursor)

    def _acknowledge(self, task, err_msg):
        """Report one finished task (and its failure tally) to the master."""
        counters = (
            {TaskExecCounterKey.FAIL_COUNT: self._bad_records}
            if self._bad_records
            else None
        )
        if err_msg:
            logger.warning(
                "task %d finished with %d/%d bad records; last error: %s",
                task.task_id,
                self._bad_records,
                _task_span(task),
                err_msg,
            )
        self._worker.report_task_result(
            task.task_id, err_msg, exec_counters=counters
        )
        self._bad_records = 0

    def _drain_acknowledged(self, err_msg):
        """Pop + report every ledger task the cursor has moved past.

        One batch can straddle several small tasks, so a single cursor
        advance may complete more than one; any failure tally rides out
        with the first task drained.
        """
        while self._inflight and self._record_cursor >= _task_span(
            self._inflight[0]
        ):
            done = self._inflight.popleft()
            self._record_cursor -= _task_span(done)
            self._acknowledge(done, err_msg)

    def report_record_done(self, count, err_msg=""):
        """Advance the cursor by ``count`` consumed records."""
        with self._ledger_lock:
            self._record_cursor += count
            if err_msg:
                self._bad_records += count
            self._drain_acknowledged(err_msg)

    def requeue_inflight(self, err_msg):
        """Fail-report every in-flight (and primed) task — the master
        requeues them for other workers — and abandon the open record
        stream so the next ``get_dataset`` starts a clean round.

        A worker parked as an elastic SPARE cannot rewind its stream:
        the round's generator is mid-``read_records`` and ``prefetch``
        still buffers records of the tasks being handed back, so
        advancing the old stream after a requeue would charge leftover
        records against the NEXT ledger task (acknowledging work that
        never trained, double-training the requeued task elsewhere).
        Dropping the whole round is the only consistent cut. Bumping
        ``_round_id`` under the lock tells a producer thread mid-
        ``get_task`` to hand its fresh task straight back instead of
        appending to the cleared ledger (see ``_record_stream``); the
        abandoned producer itself is cancelled by prefetch when the
        consumer generator is dropped."""
        with self._ledger_lock:
            self._round_id += 1
            inflight = list(self._inflight)
            self._clear_ledger()
            if self._primed_task is not None:
                # pulled for metadata priming, never consumed: it is in
                # the master's "doing" set and must go back too
                inflight.append(self._primed_task)
                self._primed_task = None
        for task in inflight:
            self._worker.report_task_result(task.task_id, err_msg)
        self._stream_open = True

    # ------------------------------------------------------------------
    # dataset construction
    # ------------------------------------------------------------------

    def get_validation_dataset(self, eval_task):
        """(dataset, model_version, task_id) for one eval task, or None."""
        if not eval_task:
            return None
        return (
            create_dataset_from_tasks([eval_task], self.data_reader),
            eval_task.model_version,
            eval_task.task_id,
        )

    def get_save_model_task_and_dataset(self):
        task, self._parked_export_task = self._parked_export_task, None
        if task is None:
            return None, None
        return task, create_dataset_from_tasks([task], self.data_reader)

    def _prime_reader_metadata(self):
        """Peek the first task so the reader can expose its metadata.

        Only a single record is pulled (enough for the reader to learn
        schema/shape info); the task itself is replayed by the stream so
        its records still reach training.
        """
        if self._metadata_primed:
            return
        task = self._worker.get_task()
        if task.shard_name:
            with self._ledger_lock:
                self._primed_task = task
            for _ in self.data_reader.read_records(task):
                break
        self._metadata_primed = True

    def get_dataset(self):
        """A Dataset spanning every task the master will hand out, or None."""
        if not self._stream_open:
            return None
        with self._ledger_lock:
            if self._inflight:
                logger.error(
                    "refusing a new dataset: %d in-flight tasks are still "
                    "unacknowledged",
                    len(self._inflight),
                )
                return None
            self._clear_ledger()
        self._prime_reader_metadata()
        self._stream_open = False
        return Dataset.from_generator(self._record_stream)

    def _record_stream(self):
        """Generator: pull tasks until the master says stop, yield records."""
        gen_id = self._round_id
        while True:
            with self._ledger_lock:
                task, self._primed_task = self._primed_task, None
            if task is None:
                task = self._worker.get_task()
            if self._round_id != gen_id:
                # the round was abandoned (spare park) while this
                # producer was fetching: hand the task straight back —
                # appending it to the cleared ledger would leak it in
                # the master's doing-set forever
                if task.shard_name:
                    self._worker.report_task_result(
                        task.task_id, "round abandoned (spare park)"
                    )
                return
            if not task.shard_name:
                if task.type == TaskType.WAIT:
                    # More data may show up (e.g. a lazy next epoch); let
                    # the worker loop ask again.
                    self._stream_open = True
                    logger.info("record stream paused (WAIT); will re-poll")
                else:
                    logger.info("task queue exhausted; record stream ends")
                return
            if task.type == TaskType.SAVE_MODEL:
                self._parked_export_task = task
                continue
            with self._ledger_lock:
                # re-check the round under the SAME hold as the append:
                # requeue_inflight can bump _round_id and clear the
                # ledger between the check above and here, and an
                # append after that would charge the next round's
                # records against a task the master already requeued
                # (double-train + wrong accounting)
                stale = self._round_id != gen_id
                if not stale:
                    self._inflight.append(task)
            if stale:
                self._worker.report_task_result(
                    task.task_id, "round abandoned (spare park)"
                )
                return
            for record in self.data_reader.read_records(task):
                if record is not None:
                    yield record
