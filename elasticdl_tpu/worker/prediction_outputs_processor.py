"""User hook for handling prediction outputs.

Parity: reference worker/prediction_outputs_processor.py:4-22.
"""

from abc import ABC, abstractmethod


class BasePredictionOutputsProcessor(ABC):
    """Base class for processing prediction outputs on workers."""

    @abstractmethod
    def process(self, predictions, worker_id):
        """Process one batch of predictions produced by ``worker_id``."""
