"""Elastic multi-process ALLREDUCE worker: one process per TPU host.

The reference's north-star behavior — a job that survives killing half its
workers (BASELINE.md config 3) — exists there only for the PS plane, where
workers never talk to each other. This worker realizes it for the
collective plane: each process pulls tasks from the master exactly like a
PS worker (same dispatcher, same recover_tasks elasticity), but trains via
the global-mesh weighted lockstep step (parallel/elastic.py), and on any
membership change re-forms the ``jax.distributed`` world under the
master's MembershipService epochs.

Run loop shape:

    prime (first local batch in hand)           # join only once shapes known
    loop:
        await world (master membership RPC)
        establish (join + broadcast state from rank 0)
        step until: out-of-data-globally | epoch bump | collective failure
    final SAVE_MODEL if assigned

Epoch bumps are observed at batch boundaries (a cheap get_comm_world call
per step — the PS worker pays a get_model RPC per step for the same
cadence, reference worker.py:630-637). A peer death mid-collective instead
surfaces as a step error; the pre-step state is still addressable
(elastic step does not donate), so the worker snapshots, waits for the
master to notice the death and bump the epoch, and re-forms. Evaluation
tasks run between steps on host-fetched params over local devices only —
never on the global mesh — so slow eval can't wedge the collective plane.

Serving-only jobs (JobType.EVALUATION_ONLY / PREDICTION_ONLY) skip the
whole collective machinery: no membership, no world, no trainer state —
tasks drain against host-twin forwards over checkpoint-loaded params
(_run_eval_only / _run_predict_only), matching the reference's
one-loop-serves-all-modes worker (reference worker/worker.py:866-876).
"""

import os
import socket
import time

import numpy as np

from elasticdl_tpu.common.constants import (
    JobType,
    MetricsDictKey,
    Mode,
    SaveModelConfig,
    TaskExecCounterKey,
)
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.common.model_utils import get_model_spec
from elasticdl_tpu.parallel.distributed import WorldSpec, WorldBroken
from elasticdl_tpu.parallel.elastic import ElasticDPTrainer
from elasticdl_tpu.worker.task_data_service import TaskDataService

# distinguishes "no batch peeked ahead" from "peeked the stream's None
# WAIT signal" in the H2D-overlap lookahead
_NO_PEEK = object()


class ElasticAllReduceWorker:
    def __init__(
        self,
        worker_id,
        job_type,
        minibatch_size,
        model_zoo,
        model_def,
        model_params=None,
        dataset_fn="dataset_fn",
        loss="loss",
        optimizer="optimizer",
        eval_metrics_fn="eval_metrics_fn",
        stub=None,
        data_reader_params=None,
        seed=0,
        comm_host=None,
        epoch_poll_secs=10.0,
        sync_every=8,
        checkpoint_dir="",
        checkpoint_steps=0,
        keep_checkpoint_max=0,
        precision=None,
        accum_steps=1,
        checkpoint_filename_for_init="",
        prediction_outputs_processor="PredictionOutputsProcessor",
        remat="",
        replica_refresh_steps=8,
        task_prefetch=0,
        speculative_compile=False,
        telemetry_report_secs=5.0,
    ):
        self._worker_id = worker_id
        self._job_type = job_type
        self._minibatch_size = minibatch_size
        self._stub = stub
        self._sync_every = max(1, sync_every)
        self._host = comm_host or os.environ.get("EDL_COMM_HOST", "")
        if not self._host:
            # advertise an address peers can dial: on k8s the bare pod
            # hostname is not resolvable from sibling pods, but the pod IP
            # (what the hostname resolves to locally) is routable
            hostname = socket.gethostname()
            try:
                self._host = socket.gethostbyname(hostname)
            except OSError:
                self._host = hostname
        self._epoch_poll_secs = epoch_poll_secs
        spec = get_model_spec(
            model_zoo=model_zoo,
            model_def=model_def,
            model_params=model_params,
            dataset_fn=dataset_fn,
            loss=loss,
            optimizer=optimizer,
            eval_metrics_fn=eval_metrics_fn,
            prediction_outputs_processor=prediction_outputs_processor,
        )
        self._dataset_fn = spec.dataset_fn
        self._model = spec.model
        self._eval_metrics_fn = spec.eval_metrics_fn
        from elasticdl_tpu.common.export import export_provenance

        self._export_meta = export_provenance(
            model_zoo, model_def, model_params
        )
        from elasticdl_tpu.common.model_utils import (
            get_module_file_path,
            load_module,
        )

        zoo_module = load_module(
            get_module_file_path(model_zoo, model_def)
        ).__dict__
        self._init_ckpt_file = checkpoint_filename_for_init
        self._prediction_outputs_processor = (
            spec.prediction_outputs_processor
        )
        # serving jobs (pure eval / pure predict) need no collective at
        # all: tasks drain against a host-twin forward over local
        # devices with params loaded from a sharded checkpoint dir (the
        # elastic plane's own format) or an exported model file — the
        # reference serves all three modes from one worker loop
        # (reference worker/worker.py:866-876)
        self._serving_only = self._job_type in (
            JobType.EVALUATION_ONLY,
            JobType.PREDICTION_ONLY,
        )
        from elasticdl_tpu.common.model_utils import (
            get_dict_from_params_str,
        )

        extra = get_dict_from_params_str(model_params) or {}
        # per-table plane guard (docs/embedding_planes.md): a PS-plane
        # table has no parameter — its rows live on the PS fleet and
        # are pulled per batch, which the collective lockstep step
        # cannot do. Fail HERE with the pointer, not deep inside
        # establish after the world already formed (where it would
        # crash-loop under relaunch). Resolved through the same
        # selector the zoo uses (an explicit per-table spec defaults
        # UNLISTED tables to ps, so string-sniffing the spec would
        # miss e.g. "embedding:hbm"); zoos that don't declare TABLES
        # get the conservative reading: only an all-tables "hbm" spec
        # is provably collective-servable.
        plane_spec = str(extra.get("embedding_plane", "") or "")
        if plane_spec:
            from elasticdl_tpu.nn.comm_plane import resolve_table_planes

            tables = zoo_module.get("TABLES")
            if tables:
                planes = resolve_table_planes(
                    plane_spec,
                    tables,
                    hybrid_default=zoo_module.get("HYBRID_SPLIT"),
                )
                has_ps_tables = "ps" in planes.values()
            else:
                has_ps_tables = plane_spec != "hbm"
            if has_ps_tables:
                raise NotImplementedError(
                    "model config embedding_plane=%r places tables on "
                    "the PS plane, which the elastic allreduce worker "
                    "cannot serve; run PS-resident tables on the "
                    "parameter-server worker (--embedding_plane=hybrid "
                    "keeps dense local while the PS fleet serves the "
                    "sparse tables)" % plane_spec
                )
        wants_sharded = self._zoo_wants_sharded_params(
            zoo_module, model_params
        )
        # host-twin zoos (build_host_model) serve sharded tables by
        # scoring a dense same-structure twin against checkpoint
        # shards; zoos without the twin serve with the degenerate
        # (mesh=None) distributed form, which consumes checkpoints AND
        # exported model files
        host_twin_serving = (
            self._serving_only and "build_host_model" in zoo_module
        )
        if self._serving_only:
            if not (checkpoint_dir or checkpoint_filename_for_init):
                raise ValueError(
                    "%s on the allreduce plane scores a saved model: "
                    "pass --checkpoint_dir (sharded checkpoints from a "
                    "previous elastic job) or "
                    "--checkpoint_filename_for_init (an exported model "
                    "file)" % self._job_type
                )
            if host_twin_serving and not checkpoint_dir:
                # the sharded host-twin path only reads checkpoint dirs
                raise ValueError(
                    "%s for sharded-parameter model %s needs "
                    "--checkpoint_dir (sharded checkpoints); an "
                    "exported model file cannot feed the host-twin "
                    "forward" % (self._job_type, model_def)
                )
        builder = None
        mesh_axes_fn = None
        layout_planner = None
        self._host_model_factory = None
        if (
            self._serving_only
            and not host_twin_serving
            and "build_distributed_model" in zoo_module
        ):
            # score with the degenerate (mesh=None) distributed form: it
            # has the same parameter STRUCTURE the distributed training
            # job checkpointed (e.g. the pipelined transformer's stacked
            # stage subtree) and runs sequentially on local devices —
            # pass the same --model_params the training job used
            self._model = zoo_module["build_distributed_model"](
                mesh=None, **extra
            )
        pjit_dense = wants_sharded and self._zoo_wants_pjit_dense(
            zoo_module, model_params
        )
        if pjit_dense and not self._serving_only:
            # pjit dense plane (docs/distributed.md): the specs shard
            # the PLAIN module over the 2D data x model mesh — no
            # collective zoo form exists or is needed, XLA partitions
            # the global-semantics model from the NamedShardings. The
            # trainer detects the model-axis specs and routes the step
            # through make_pjit_train_step. Serving-only jobs need
            # none of this: they fall through to the degenerate
            # (mesh=None) plain-module path below, whose scoring
            # assembles FULL host arrays from the training job's
            # sharded checkpoints via load_sharded_to_host — the TP
            # shard files carry their slice metadata.
            def builder(
                mesh, _module=self._model, _zoo=zoo_module, _extra=extra
            ):
                return (
                    _module,
                    _zoo["param_shardings"](mesh, **_extra),
                )

            if "mesh_axes" in zoo_module:
                mesh_axes_fn = (
                    lambda n, _zoo=zoo_module, _extra=extra: _zoo[
                        "mesh_axes"
                    ](n, **_extra)
                )
            # elastic layout re-solve (docs/distributed.md "Layout
            # re-solve"): resizes on the pjit dense plane re-plan
            # dp x tp x micro-batch per world size instead of
            # replaying the launch layout. The zoo's static mesh_axes
            # stays as the fallback until the first establish derives
            # the model profile; the per-device budget comes from
            # EDL_LAYOUT_MEM_BUDGET_MB (unset: every layout fits).
            from elasticdl_tpu.parallel.layout_solver import (
                LayoutPlanner,
            )

            layout_planner = LayoutPlanner(
                fallback_axes_fn=mesh_axes_fn
            )
        elif (
            "build_distributed_model" in zoo_module
            and "build_collective_model" not in zoo_module
            and not self._serving_only
            and wants_sharded
        ):
            # training the plain replicated model instead would either
            # OOM (the table was sharded because it doesn't fit) or
            # silently change the declared strategy
            raise NotImplementedError(
                "model %s declares sharded parameters for this config "
                "(param_shardings is non-empty) but no "
                "build_collective_model hook; the multi-process elastic "
                "plane needs the collective-lookup form — add "
                "build_collective_model (see "
                "model_zoo/deepfm_edl_embedding or "
                "model_zoo/transformer_lm) or run the "
                "single-process ALLREDUCE strategy" % model_def
            )
        if (
            "build_collective_model" in zoo_module
            and not pjit_dense
            and (
                host_twin_serving
                or (not self._serving_only and wants_sharded)
            )
        ):
            # sharded parameters on the elastic plane (HBM vocab tables,
            # stacked pipeline stages): the model uses raw collectives
            # inside the weighted step's shard_map, parameters shard per
            # param_shardings, and re-forms restore from the replica
            # plane / sharded checkpoints. The module is built EAGERLY
            # (a flax dataclass — no device work) so unsupported
            # configs fail here, at worker construction, not after
            # world formation
            collective_module = zoo_module["build_collective_model"](
                **extra
            )

            def builder(
                mesh, _module=collective_module, _zoo=zoo_module, _extra=extra
            ):
                return (
                    _module,
                    _zoo["param_shardings"](mesh, **_extra),
                )

            if "mesh_axes" in zoo_module:
                # the elastic world's mesh layout (e.g. data x pipe for
                # pipelined models); evaluated per world size at each
                # establish
                mesh_axes_fn = (
                    lambda n, _zoo=zoo_module, _extra=extra: _zoo[
                        "mesh_axes"
                    ](n, **_extra)
                )

            if "build_host_model" in zoo_module:
                # optional since r5: TRAINING_WITH_EVALUATION scores
                # IN-PLANE (collective lockstep eval at aligned sync
                # points — no checkpoint, no host twin, tables never
                # materialize in one host's RAM); the twin remains the
                # serving-only scoring path and the export trace
                self._host_model_factory = (
                    lambda _zoo=zoo_module, _extra=extra: _zoo[
                        "build_host_model"
                    ](**_extra)
                )
        from elasticdl_tpu.training.step import parse_remat

        self.trainer = ElasticDPTrainer(
            spec.model,
            spec.loss,
            spec.optimizer(),
            seed=seed,
            precision=precision,
            accum_steps=accum_steps,
            distributed_builder=builder,
            remat=parse_remat(remat),
            mesh_axes_fn=mesh_axes_fn,
            layout_planner=layout_planner,
        )
        # in-memory replica plane: bounded-staleness no-disk recovery
        # for the sharded leaves (parallel/elastic.py ShardMirror);
        # 0 disables. The flag reaches every rank identically via the
        # arg relay, which the collective refresh relies on.
        self.trainer.mirror_steps = max(0, int(replica_refresh_steps))
        # compile-plane fast path (docs/compile_plane.md): the fixed
        # minibatch lets speculative AOT compiles derive the exact batch
        # shapes a future establish will step with; the persistent
        # compile cache (EDL_COMPILE_CACHE_DIR) makes relaunched
        # processes and re-formed worlds skip XLA compiles they have
        # paid before
        self.trainer.default_minibatch_size = minibatch_size
        self.trainer.speculative_compile = bool(speculative_compile)
        from elasticdl_tpu.parallel.compile_plane import (
            enable_persistent_cache,
        )

        enable_persistent_cache()
        self._last_size_hint = 0
        # escapable sync waits: a peer death can wedge this rank's fetch
        # forever (gloo listener-side hang); the trainer polls this hook
        # while waiting so a wedged rank notices the master has moved
        # the world on and takes the failed-step recovery path instead
        # of getting fenced (state intact for the replica plane)
        self.trainer.abort_check = self._world_moved_on
        # task prefetch composes with the spare-park protocol: the
        # fetcher participates in requeue_inflight's round abandonment,
        # so every prefetched-but-unconsumed task goes back to the
        # master (docs/input_pipeline.md). Acks stay synchronous on this
        # plane — the validate/flush window already defers them.
        self._task_data_service = TaskDataService(
            self,
            self._job_type == JobType.TRAINING_WITH_EVALUATION,
            data_reader_params=data_reader_params,
            task_prefetch=task_prefetch,
        )
        # job telemetry: step/examples rates + resize / compile-plane
        # events ride the task-report channel (docs/observability.md)
        from elasticdl_tpu.worker.telemetry import WorkerTelemetry

        self._telemetry = WorkerTelemetry(
            worker_id,
            stats=self._task_data_service.stats,
            interval_s=telemetry_report_secs,
        )
        self._ckpt = None
        if checkpoint_dir and checkpoint_steps:
            from elasticdl_tpu.common.sharded_checkpoint import (
                ShardedCheckpointManager,
            )

            # async_io: saves block only for the HBM->host snapshot;
            # file writes overlap the next training window
            self._ckpt = ShardedCheckpointManager(
                checkpoint_dir,
                checkpoint_steps,
                keep_checkpoint_max,
                async_io=True,
            )
            self.trainer.restore_provider = self._ckpt_dirs_newest_first
        elif checkpoint_dir and self._serving_only:
            from elasticdl_tpu.common.sharded_checkpoint import (
                ShardedCheckpointManager,
            )

            # read-only: serving jobs load checkpoints, never write
            self._ckpt = ShardedCheckpointManager(checkpoint_dir)
        elif builder is not None:
            logger.warning(
                "sharded-parameter elastic job without --checkpoint_steps:"
                " any membership change RE-INITIALIZES the model"
            )
        self._restore_attempted = False
        self._last_ckpt_version = 0
        self._batch_gen = None
        self._retry_batch = None
        # one-batch lookahead for the H2D overlap: _NO_PEEK means
        # nothing peeked (a peeked None is the stream's WAIT signal and
        # must be delivered, not re-pulled)
        self._staged_peek = _NO_PEEK
        self._unreported = []  # counts of consumed-but-unvalidated steps
        self._drained = False
        self._forward_fn = None
        self._eval_params_version = None
        self._eval_params = None
        self._eval_scored_version = None  # version params actually carry
        self._overflow_alarmed = 0
        self._preempted = False
        self._drain_announced = False
        self._drain_deadline = 0.0

    # -- graceful preemption ------------------------------------------------

    # distinct from 0 ("done, don't replace me") and from crash codes:
    # the instance manager relaunches a replacement for this exit
    PREEMPTED_EXIT_CODE = 75  # EX_TEMPFAIL

    def request_drain(self, *_signal_args):
        """SIGTERM handler hook: drain gracefully at the next batch
        boundary instead of dying mid-collective.

        Cloud preemptions deliver SIGTERM with notice (k8s
        terminationGracePeriod, TPU-VM maintenance events). A drained
        worker flushes its sync window, checkpoints (sharded plane),
        reports its records, and LEAVES the world cleanly — so the
        survivors observe an ordinary membership epoch at a batch
        boundary rather than a broken collective + failed-step recovery,
        and no work is lost at all."""
        self._preempted = True
        logger.info(
            "preemption notice received; draining at the next batch "
            "boundary"
        )

    def enable_drain_on_sigterm(self):
        """Install the SIGTERM -> request_drain handler, and keep it
        installed: ``jax.distributed.initialize`` registers XLA's own
        C++ preemption notifier for SIGTERM (preemption_notifier.cc),
        silently REPLACING any Python handler registered before it — so
        the worker re-installs after every establish (see _run)."""
        self._drain_signal_enabled = True
        self._install_drain_handler()

    def _install_drain_handler(self):
        if not getattr(self, "_drain_signal_enabled", False):
            return
        import signal
        import threading

        if threading.current_thread() is not threading.main_thread():
            return  # in-process test workers: signals stay with the host
        signal.signal(signal.SIGTERM, self.request_drain)

    @staticmethod
    def _zoo_wants_pjit_dense(zoo_module, model_params):
        """Does this config shard the DENSE model over the ``model``
        axis (the pjit/GSPMD path, plain module), rather than declaring
        collective-form sharded parameters? Probed with mesh=None like
        :meth:`_zoo_wants_sharded_params`."""
        ps = zoo_module.get("param_shardings")
        if ps is None:
            return False
        from elasticdl_tpu.common.model_utils import (
            get_dict_from_params_str,
        )
        from elasticdl_tpu.parallel.elastic import (
            collect_sharded_paths,
            specs_use_axis,
        )

        try:
            specs = ps(
                None, **(get_dict_from_params_str(model_params) or {})
            )
            return specs_use_axis(collect_sharded_paths(specs), "model")
        except Exception:
            logger.debug(
                "model ps() pjit probe failed; assuming collective "
                "form",
                exc_info=True,
            )
            return False

    @staticmethod
    def _zoo_wants_sharded_params(zoo_module, model_params):
        """Does this zoo + model_params combination actually shard
        parameters? Keying the collective-hook requirement on
        build_distributed_model's mere PRESENCE would wrongly reject
        configs whose distributed form is optional (e.g. transformer_lm
        without pipeline_stages trains replicated). param_shardings is
        probed with mesh=None — zoo hooks accept that and answer from
        the params alone; no mesh (= no JAX backend init) may happen
        before the world forms."""
        ps = zoo_module.get("param_shardings")
        if ps is None:
            return True  # conservative: hook declared, intent unknown
        from elasticdl_tpu.common.model_utils import (
            get_dict_from_params_str,
        )

        try:
            return bool(
                ps(None, **(get_dict_from_params_str(model_params) or {}))
            )
        except Exception:
            logger.debug(
                "model ps() probe failed; assuming PS mode",
                exc_info=True,
            )
            return True

    def _ckpt_dirs_newest_first(self):
        """Candidate checkpoint dirs, newest first; drains in-flight
        async saves so an establish/restore never reads a half-written
        one. More than one candidate matters: a killed rank can leave
        the newest version torn (its manifest missing) while an older
        complete one sits behind it."""
        if self._ckpt is None:
            return []
        try:
            self._ckpt.wait()
        except Exception:
            logger.warning(
                "async checkpoint write failed; restoring from the "
                "previous complete checkpoint",
                exc_info=True,
            )
        return self._ckpt.dirs_newest_first()

    def _latest_ckpt_dir(self):
        dirs = self._ckpt_dirs_newest_first()
        return dirs[0] if dirs else None

    # master surface used by TaskDataService
    def get_task(self, task_type=None):
        return self._stub.get_task(self._worker_id, task_type)

    def report_task_result(self, task_id, err_msg="", exec_counters=None):
        from elasticdl_tpu.worker.reporting import with_model_version

        result = self._stub.report_task_result(
            task_id, err_msg, with_model_version(self.trainer, exec_counters)
        )
        # piggyback the (rate-limited) telemetry snapshot — resize and
        # speculative-compile events reach the master's event log here
        self._telemetry.ship(self._stub)
        return result

    # -- data ---------------------------------------------------------------

    def _batches(self):
        """Continuous (features, labels) stream over all task rounds.

        Yields None on a WAIT round (no data *now*, job not finished) so
        the caller can keep the collective plane ticking; StopIteration
        means the master has no more training work for this process.
        """
        while True:
            if self._unreported:
                # settle the sync window before the round rolls over:
                # held-back reports keep the finished round's tasks
                # "pending", which would wedge the next get_dataset
                ok = self.trainer.validate()
                self._flush_unreported(
                    "" if ok else "collective failed before validation"
                )
            dataset = self._task_data_service.get_dataset()
            if not dataset:
                return
            dataset = self._dataset_fn(
                dataset,
                Mode.TRAINING,
                self._task_data_service.data_reader.metadata,
            )
            dataset = dataset.batch(self._minibatch_size).prefetch(1)
            got = False
            for batch in dataset:
                got = True
                yield batch
            self._process_save_model_task_if_needed()
            if not got:
                yield None

    def _next_batch(self):
        if self._retry_batch is not None:
            batch, self._retry_batch = self._retry_batch, None
            return batch
        if self._staged_peek is not _NO_PEEK:
            # the H2D-overlap lookahead already pulled this item (and
            # its placement may be staging on the feeder thread)
            batch, self._staged_peek = self._staged_peek, _NO_PEEK
            return batch
        if self._drained:
            return None
        try:
            batch = next(self._batch_gen)
        except StopIteration:
            self._drained = True
            return None
        return batch

    def _peek_and_stage_next(self):
        """Pull batch N+1 and hand it to the trainer's feeder thread so
        its H2D placement overlaps the sync-point cadence work
        (checkpoint save, eval rounds, mirror refresh) and the next
        step's dispatch. Called ONLY after _flush_unreported has settled
        the ledger: a round boundary crossed here then sees every
        consumed record reported — the same state the unpeeked loop's
        next _next_batch call would cross it with. The peeked item (a
        None WAIT signal included) is delivered by the next _next_batch
        call, so the stream's semantics are byte-identical."""
        if (
            self._staged_peek is not _NO_PEEK
            or self._retry_batch is not None
            or self._drained
        ):
            return
        try:
            peek = next(self._batch_gen)
        except StopIteration:
            self._drained = True
            return
        self._staged_peek = peek
        if peek is not None:
            self.trainer.stage_next(
                peek[0], peek[1], self._minibatch_size
            )

    # -- membership ---------------------------------------------------------

    def _await_world(self):
        """Poll the master until a world including us is ready.

        Returns a WorldSpec, or None if the job finished while waiting
        (every process drained and the master stopped handing out work).

        A ``spare`` reply means a ``world_size_multiple`` round-down
        left this live worker out of the current world (e.g. 3
        survivors of a 2-stage pipelined job form a world of 2): it
        idles here WITHOUT a mesh slot, so any pulled-but-untrained
        work goes back to the master immediately — a spare holding
        tasks would stall job completion for everyone.
        """
        spare_flushed = False
        while True:
            if self._preempted:
                return None  # drain notice while between worlds
            w = self._stub.get_comm_world(
                self._worker_id, self._host, awaiting=True
            )
            if w.get("ready"):
                # member ids of this world: the wedge-escape probe needs
                # them to tell "one of MY peers died" from growth/drain
                self._world_members = list(w.get("members", ()))
                return WorldSpec(
                    coordinator=w["coordinator"],
                    num_processes=w["num_processes"],
                    process_id=w["process_id"],
                    epoch=w["epoch"],
                )
            if w.get("spare") and not spare_flushed:
                spare_flushed = True
                self._requeue_as_spare()
            if self._drained and self._retry_batch is None:
                return None
            time.sleep(0.2)

    def _requeue_as_spare(self):
        """Hand every in-flight task back to the master (fail-report +
        requeue), drop the primed batch, and abandon the current data
        round: a spare trains nothing, world members can finish the
        work it was holding, and the round's buffered stream cannot be
        rewound past the requeued tasks (TaskDataService
        ``requeue_inflight``). On rejoin the run loop re-primes from a
        fresh round."""
        tds = self._task_data_service
        # no early-out on an "empty" ledger: the round may still be OPEN
        # with a producer thread about to pull a fresh task — the round
        # bump below is what tells it to step aside
        logger.info(
            "parked as spare (world-size rounding); requeueing "
            "in-flight work and abandoning the open round"
        )
        msg = "parked as spare (world size rounding)"
        self._retry_batch = None
        # a peeked batch belongs to a task being requeued wholesale
        self._staged_peek = _NO_PEEK
        # settle any stepped-but-unreported window first (normally empty
        # — the reform pause flushed it); its cursor advance must land
        # before the ledger is requeued wholesale
        self._flush_unreported(msg)
        tds.requeue_inflight(msg)
        # restart the batch stream: the abandoned round's generator and
        # its prefetch buffer die with the old handle
        self._batch_gen = self._batches()

    def _await_epoch_bump(self, stale_epoch):
        """After a collective failure: wait for the master to re-form.

        Returns True once the epoch bumps; False if it never does within
        the poll window (the failure wasn't a membership event and should
        propagate as a real bug, not be retried forever).
        """
        deadline = time.time() + self._epoch_poll_secs
        while time.time() < deadline:
            w = self._stub.get_comm_world(
                self._worker_id, self._host, awaiting=False
            )
            if w["epoch"] != stale_epoch:
                return True
            time.sleep(0.3)
        return False

    # -- the run loop --------------------------------------------------------

    def run(self):
        from elasticdl_tpu.utils.profiling import maybe_stop_trace

        try:
            return self._run()
        finally:
            # final telemetry flush (PS-mode Worker.run does the same):
            # a job shorter than the report interval, and any events
            # emitted after the last interval-gated ack, still land one
            # snapshot. Best-effort — the master may already be gone.
            self._telemetry.ship(self._stub, force=True)
            # flush any open trace even on the exception path — the run
            # that crashed is the one whose profile matters most
            maybe_stop_trace()
            # a crash path skips _finalize; queued async checkpoint
            # writes must still land (save() already returned and
            # advanced the cadence — dropping them here would lose up
            # to checkpoint_steps of durable progress)
            self._drain_ckpt()
            # compile-plane helper threads (speculative compiler, H2D
            # feeder) must not outlive the worker
            self.trainer.close()

    def _run(self):
        if self._job_type == JobType.EVALUATION_ONLY:
            return self._run_eval_only()
        if self._job_type == JobType.PREDICTION_ONLY:
            return self._run_predict_only()
        losses = []
        self._batch_gen = self._batches()
        # register with the membership BEFORE priming: a promoted
        # standby's death-bump is DEFERRED waiting for exactly this
        # registration, so announcing first lets the survivors pause
        # and settle in parallel with our dataset/reader priming
        # (measured ~5.7 s serial before this, BASELINE.md r5). The
        # awaiting=False poll registers without confirming a formation
        # we are not yet ready to join.
        try:
            self._stub.get_comm_world(
                self._worker_id, self._host, awaiting=False
            )
        except Exception:
            # registration happens via the await loop anyway
            logger.debug("pre-registration poll failed", exc_info=True)
        first = self._prime()
        if first is None:
            # no training data ever assigned; still serve eval/save
            # tasks. We pre-registered above, so announce the leave —
            # an unconfirmed member would hold every peer's formation
            # for the confirm window and then get fenced mid-eval
            try:
                self._stub.leave_comm_world(self._worker_id)
            except Exception:
                logger.debug(
                    "leave announcement missed; the confirm-timeout "
                    "fencer clears this member",
                    exc_info=True,
                )
            self._finalize()
            return losses
        self._retry_batch = first

        while True:
            world = self._await_world()
            if world is None:
                break
            try:
                example = self._retry_batch or self.trainer._last_local
                if example is None:
                    # rejoining after a spare park requeued everything:
                    # prime a fresh batch (shapes gate the mesh slot)
                    first = self._prime()
                    if first is None:
                        # drained/preempted while parked: leave so the
                        # members' formation doesn't wait out the
                        # confirm window on us
                        try:
                            self._stub.leave_comm_world(self._worker_id)
                        except Exception:
                            logger.debug(
                                "leave announcement missed; the "
                                "confirm-timeout fencer clears this "
                                "member",
                                exc_info=True,
                            )
                        break
                    self._retry_batch = example = first
                self.trainer.establish(world, example_batch=example)
                if self._ckpt is not None:
                    # ring eviction must know what "complete" means in
                    # this world: every rank writes sharded versions,
                    # rank 0 alone writes replicated ones
                    self._ckpt.set_expected_writers(
                        world.num_processes
                        if self.trainer.is_sharded
                        else 1
                    )
                    # PadDim0 leaves the new world padded: manifests
                    # record the logical rows so host-side restores
                    # (export, twin scoring) clip the padding off
                    self._ckpt.set_logical_dim0(
                        self.trainer.logical_dim0_by_path()
                    )
                if (
                    self._ckpt is not None
                    and not self._restore_attempted
                    and not self.trainer.is_sharded
                ):
                    self._restore_attempted = True
                    # resume only when the WHOLE world is virgin (the
                    # broadcast state carries version 0). A fresh process
                    # joining a live job receives the survivors' state in
                    # the broadcast; restoring a stale checkpoint over
                    # just this replica would silently de-synchronize the
                    # replicated parameters. (Sharded-parameter jobs
                    # restore inside establish() instead, every epoch.)
                    if self.trainer.version == 0:
                        self._restore_latest_checkpoint()
                if self.trainer.is_sharded:
                    self._last_ckpt_version = max(0, self.trainer.version)
            except WorldBroken:
                logger.warning(
                    "world %d broke during formation; re-polling", world.epoch
                )
                # the failed initialize may still have displaced the
                # drain handler — take it back before re-polling
                self._install_drain_handler()
                continue
            # jax.distributed.initialize (inside establish) installs
            # XLA's own SIGTERM notifier, displacing the drain handler —
            # take it back so preemption notices reach request_drain
            self._install_drain_handler()
            from elasticdl_tpu.utils.profiling import maybe_start_trace

            maybe_start_trace()  # safe only now: the backend is world-aware
            outcome = self._train_epoch(world, losses)
            if outcome in ("done", "preempted"):
                break
            if self._preempted:
                # the announced drain exits through the ordinary reform
                # pause ("reform"); a drained worker must not re-join
                break
        self._finalize()
        return losses

    def _restore_latest_checkpoint(self):
        """Resume from the newest restorable checkpoint; a partial or
        corrupt directory falls back to the next-older one instead of
        crash-looping the worker."""
        self._ckpt.wait()  # an in-flight async save must land first
        for directory in self._ckpt.dirs_newest_first():
            try:
                self.trainer.restore_sharded(directory)
                self._last_ckpt_version = self.trainer.version
                return True
            except Exception:
                logger.warning(
                    "checkpoint %s unrestorable; trying older",
                    directory,
                    exc_info=True,
                )
        return False

    def _prime(self):
        """Block until the first local batch is in hand (its shapes gate
        world membership — a shapeless process can't hold a mesh slot).

        Heartbeats the membership (from a side thread — the slow part
        is INSIDE the batch generator: reader setup, shuffle-buffer
        fill) while blocked: this worker may already be REGISTERED
        (register-before-prime), and a registered member whose last
        poll goes stale looks dead to the confirm-timeout fencer — a
        cold reader that primes slowly would get the fresh process
        killed mid-prime. The awaiting=False poll refreshes liveness
        without confirming a formation we can't join yet (the master
        waits on a responsive-but-slow member instead of fencing it)."""
        import threading

        done = threading.Event()
        # bounded: a beat that never stops would keep a truly WEDGED
        # primer (reader stuck on a dead filesystem) looking alive
        # forever, holding every peer's formation — past the deadline
        # the beats stop and the confirm-timeout fencer regains
        # authority over this process
        deadline = time.time() + 120.0

        def beat():
            while time.time() < deadline and not done.wait(1.0):
                try:
                    self._stub.get_comm_world(
                        self._worker_id, self._host, awaiting=False
                    )
                except Exception:
                    logger.debug(
                        "liveness beat missed (master busy/unreachable)",
                        exc_info=True,
                    )

        beater = None
        if self._stub is not None:
            beater = threading.Thread(target=beat, daemon=True)
            beater.start()
        try:
            while True:
                if self._preempted:
                    return None
                batch = self._next_batch()
                if batch is not None:
                    return batch
                if self._drained:
                    return None
                time.sleep(0.2)
        finally:
            done.set()
            if beater is not None:
                # a beat mid-RPC must land before the caller announces a
                # leave (register-after-leave is additionally blocked by
                # the membership's departing blacklist; joining removes
                # the race entirely)
                beater.join(timeout=5.0)

    def _world_moved_on(self):
        """The trainer's escapable-wait abort probe: True when one of
        this world's members actually DIED (watch/fence removal) — its
        collectives are unrecoverable and a bump is coming (possibly
        deferred for a standby promotion, so the epoch alone is NOT the
        gate: waiting for it would hold a wedged survivor through the
        whole deferral). A growth bump or a graceful drain advances the
        epoch while every member is still stepping — those must never
        abort a healthy (merely slow, e.g. compiling) dispatch, which
        is why the probe keys on deaths, not epochs."""
        from elasticdl_tpu.parallel import distributed

        spec = distributed.current_spec()
        if spec is None:
            return False
        try:
            w = self._stub.get_comm_world(
                self._worker_id, self._host, awaiting=False
            )
        except Exception:
            logger.debug(
                "world probe failed; not treating as moved-on",
                exc_info=True,
            )
            return False
        dead = set(w.get("dead", ()))
        members = getattr(self, "_world_members", None) or ()
        return any(
            m in dead for m in members if m != self._worker_id
        )

    def _flush_unreported(self, err_msg=""):
        """Report record counts held back while their steps were
        unvalidated. With an err_msg the consumed-but-unapplied records
        count as failures (per-task failure counters), and a task that
        drains on the failing flush fail-reports + requeues — the
        reference's failed-minibatch accounting semantics."""
        pending, self._unreported = self._unreported, []
        for count in pending:
            self._task_data_service.report_record_done(count, err_msg)

    def _settle_and_leave(self, verdict, validate=True, losses=None):
        """The leave epilogue every pause path shares: settle the sync
        window (validated steps report done, a failed window
        fail-reports + requeues), checkpoint the sharded plane, close
        any open trace, and leave the world. A validated window's
        deferred (collect-later) losses drain into ``losses`` — leave()
        drops the pending scalars, so without this the pause paths
        would silently lose up to sync_every-1 recorded steps."""
        ok = self.trainer.validate() if validate else False
        if ok and losses is not None:
            losses.extend(self.trainer.drain_metrics())
        self._flush_unreported(
            "" if ok else "collective failed before validation"
        )
        if ok and self.trainer.is_sharded:
            # a checkpoint written at the pause point makes the
            # re-form's restore lossless (all members pause at the same
            # version, so no rank's manifest is torn)
            self._save_ckpt_if_newer()
        from elasticdl_tpu.utils.profiling import maybe_stop_trace

        maybe_stop_trace()  # the trace must not outlive its world
        self.trainer.leave()
        return verdict

    def _train_epoch(self, world, losses):
        step_i = 0
        while True:
            if self._preempted and not self._drain_announced:
                # graceful drain rides the ORDINARY reform protocol:
                # announce the departure so the master bumps the epoch
                # now, then KEEP STEPPING — every member (this one
                # included) observes the bump at the same lockstep
                # iteration and pauses at the batch boundary, so no
                # collective is ever left hanging on a vanished rank.
                # Leaving immediately instead would strand survivors'
                # in-flight steps and send them down the failed-step
                # recovery path this drain exists to avoid.
                self._drain_announced = True
                self._drain_deadline = (
                    time.time() + self._epoch_poll_secs
                )
                try:
                    self._stub.leave_comm_world(self._worker_id)
                    logger.info(
                        "drain announced; stepping until the world "
                        "pauses"
                    )
                except Exception:
                    logger.warning(
                        "drain announcement failed; will hard-leave",
                        exc_info=True,
                    )
            if (
                self._drain_announced
                and self._drain_deadline
                and time.time() > self._drain_deadline
            ):
                # the announcement never landed (master unreachable?):
                # settle what we can and leave anyway — survivors take
                # the failure-recovery path, same as a hard kill
                return self._settle_and_leave("preempted", losses=losses)
            if (
                self._job_type == JobType.TRAINING_WITH_EVALUATION
                and not self.trainer.is_sharded
            ):
                # sharded jobs evaluate IN-PLANE at aligned sync points
                # below (the collective rounds must line up across
                # ranks); the replicated plane scores a local snapshot
                # and can drain whenever
                self._evaluate_only()
            w = self._stub.get_comm_world(
                self._worker_id, self._host, awaiting=False
            )
            # membership-service size hint: the live+lobby head count is
            # the world the next growth bump would form — feed it to the
            # speculative compiler so that establish finds its
            # executable already built (docs/compile_plane.md)
            hint = int(w.get("live", 0) or 0)
            if hint and hint != self._last_size_hint:
                self._last_size_hint = hint
                per_proc = self.trainer.mesh.devices.size // max(
                    1, world.num_processes
                )
                self.trainer.hint_world_sizes([hint * per_proc])
            if self._drain_announced and w["epoch"] != world.epoch:
                # the drain bump IS visible: the consensus pause will
                # land within one sync window — disarm the hard-leave
                # fallback so a slow eval round or a long first-step
                # compile cannot turn a clean drain into a broken
                # collective
                self._drain_deadline = 0.0
            # NOTE: a polled epoch bump does NOT pause here. With
            # deferred sync the hosts run ahead of the device unevenly,
            # so members OBSERVE a bump at different host iterations; a
            # member pausing at its observation point strands peers'
            # already-dispatched steps on a vanished rank. Instead the
            # polled epoch rides INTO the step (epoch_hint) and the
            # in-step pmax consensus — read back at aligned sync indices,
            # which are the same step for every member — triggers the
            # pause below.
            batch = self._next_batch()
            step_i += 1
            # syncing (a device->host round trip) every step stalls the
            # dispatch pipeline; data steps sync every sync_every steps,
            # drain steps always (their n_active drives the exit).
            # Records consumed by unsynced steps are reported only once
            # their window validates.
            # aligned_sync points land at the same step INDEX on every
            # rank (loop iterations are lockstep — one collective per
            # iteration), so version reads there agree globally; a
            # drain-forced sync is local to the draining rank
            aligned_sync = step_i % self._sync_every == 0
            sync = batch is None or aligned_sync
            try:
                if batch is None:
                    loss, n_active, count = self.trainer.train_step(
                        None,
                        None,
                        self._minibatch_size,
                        sync=True,
                        epoch_hint=w["epoch"],
                    )
                    losses.extend(self.trainer.drain_metrics())
                else:
                    features, labels = batch
                    loss, n_active, count = self.trainer.train_step(
                        features,
                        labels,
                        self._minibatch_size,
                        sync=sync,
                        epoch_hint=w["epoch"],
                    )
                    if loss is not None:
                        # collect-later losses of the unsynced window
                        # land first, keeping the list chronological
                        losses.extend(self.trainer.drain_metrics())
                        losses.append(loss)
            except Exception:
                logger.exception("collective step failed")
                # the whole unvalidated window (including this batch)
                # fail-reports: its task drains + requeues, and the
                # records are re-read by whichever worker picks it up —
                # retrying the batch here would double-charge the
                # requeued task's accounting
                if batch is not None:
                    leaf = batch[1]
                    self._unreported.append(int(np.asarray(leaf).shape[0]))
                self._settle_and_leave("reform", validate=False)
                if not self._await_epoch_bump(world.epoch):
                    raise
                return "reform"
            if batch is not None:
                self._unreported.append(count)
                self._telemetry.on_batch(count)
            if sync:
              # a peer death can surface here as WorldBroken from the
              # escapable waits inside the cadence fetches / the pause
              # refresh (trainer._await_ready): take the same reform
              # path as a failed step — the just-synced window already
              # validated and flushed, so no accounting is lost
              try:
                self._flush_unreported()
                if batch is not None:
                    # step overlap: pull batch N+1 now — its H2D
                    # placement runs on the feeder thread while the
                    # cadence work below (checkpoint save, eval rounds,
                    # mirror refresh) runs here. Strictly AFTER the
                    # flush: every consumed record is reported, so a
                    # round boundary this peek crosses sees the same
                    # settled ledger the unpeeked loop's next
                    # _next_batch would — get_dataset never refuses
                    # over records this very iteration consumed.
                    self._peek_and_stage_next()
                self._alarm_on_embedding_overflow()
                consensus = self.trainer.epoch_consensus
                if (
                    aligned_sync
                    and consensus is not None
                    and consensus > world.epoch
                ):
                    # every member reads this SAME consensus value at
                    # this SAME step index — the whole world pauses in
                    # unison, no collective left hanging
                    logger.info(
                        "epoch bump %d -> %d; pausing at aligned sync",
                        world.epoch,
                        consensus,
                    )
                    if self.trainer.mirror_enabled():
                        # the pause is the one point where EVERY member
                        # (a draining victim included) sits at the same
                        # step: a refresh here makes the upcoming
                        # reform's replica-plane assembly LOSSLESS — the
                        # victim's shards ride the ppermute to its
                        # neighbor at the pause version, no disk needed
                        try:
                            self.trainer.refresh_mirror()
                        except Exception:
                            logger.warning(
                                "pause-point replica refresh failed; "
                                "reform falls back to the last refresh "
                                "or checkpoints",
                                exc_info=True,
                            )
                    return self._settle_and_leave("reform", losses=losses)
                if (
                    self._ckpt is not None
                    and (
                        world.process_id == 0 or self.trainer.is_sharded
                    )
                    and self._ckpt.is_enabled()
                    # sharded checkpoints are only restorable when EVERY
                    # rank wrote the same version, so the cadence must
                    # trigger at rank-aligned sync points alone
                    and (aligned_sync or not self.trainer.is_sharded)
                ):
                    # checkpoints land at sync points, so the cadence is
                    # "at least checkpoint_steps versions since the last
                    # save" rather than an exact modulo (which would
                    # silently degrade to lcm(sync_every, steps)). Rank 0
                    # alone suffices on the replicated plane (it holds
                    # replica 0 of every leaf); with sharded parameters
                    # EVERY rank writes — each owns distinct table rows,
                    # and the per-process manifests only assemble into a
                    # restorable checkpoint when all ranks contributed.
                    # Versions agree across ranks (lockstep collective
                    # steps), so all ranks pick the same cadence points.
                    version = self.trainer.version
                    if (
                        version - self._last_ckpt_version
                        >= self._ckpt.steps
                    ):
                        self._ckpt.save(self.trainer._ts, version)
                        self._last_ckpt_version = version
                if (
                    aligned_sync
                    and self.trainer.is_sharded
                    and self._job_type
                    == JobType.TRAINING_WITH_EVALUATION
                ):
                    # in-plane eval: a lockstep protocol (consensus
                    # gather + collective forwards), so it must run at
                    # the same aligned index on every rank — exactly
                    # here, after the pause check agreed nobody is
                    # re-forming this round
                    self._collective_evaluate()
                if aligned_sync and self.trainer.mirror_enabled():
                    # replica-plane cadence: same aligned-sync trigger
                    # discipline as the checkpoint cadence (the refresh
                    # is a collective — every rank must take it at the
                    # same step, which the version-based predicate
                    # guarantees)
                    self.trainer.maybe_refresh_mirror(
                        self.trainer.version
                    )
              except Exception as cadence_err:
                # the reform path is only for WORLD failures — a peer
                # loss surfacing as WorldBroken (escaped wedge) or as a
                # raw collective/runtime error from the cadence
                # refresh/fetches. Local errors (e.g. a checkpoint-save
                # disk failure) must propagate untouched: tearing down
                # a healthy world for them would break peers' in-flight
                # collectives for nothing.
                from jax.errors import JaxRuntimeError

                if not isinstance(
                    cadence_err, (WorldBroken, JaxRuntimeError)
                ):
                    raise
                logger.exception(
                    "world broke during the sync cadence; re-forming"
                )
                self._settle_and_leave("reform", validate=False)
                if not self._await_epoch_bump(world.epoch):
                    raise
                return "reform"
            if n_active == 0:
                # global quiescence: every rank observes it in the same
                # collective round with the same (final) version. Sharded
                # ranks land their shards NOW — the export task (one
                # rank, in _finalize) needs every OTHER rank's manifest,
                # and those ranks may legitimately still be here waiting
                # for the job (incl. that very export task) to finish.
                if self.trainer.is_sharded:
                    self._save_ckpt_if_newer()
                if self._drained:
                    return "done"
                time.sleep(0.2)

    def _alarm_on_embedding_overflow(self):
        """Surface a2a capacity overflow (ids silently trained on zero
        rows) at sync points. The counter is a replicated scalar in the
        model state, so the read costs one scalar fetch per sync."""
        ts = self.trainer._ts
        if ts is None:
            return
        from elasticdl_tpu.nn.hbm_embedding import a2a_overflow_total

        try:
            total = a2a_overflow_total(ts.state)
        except Exception:
            # mid-failure state; the step error path owns it
            logger.debug("overflow counter fetch failed", exc_info=True)
            return
        if total and total > self._overflow_alarmed:
            logger.warning(
                "embedding a2a capacity overflow: %d ids have read zero "
                "rows since job start (+%d since last sync) — increase "
                "HbmEmbedding capacity (or leave it None for the exact "
                "worst case)",
                total,
                total - self._overflow_alarmed,
            )
            self._overflow_alarmed = total

    # -- evaluation (local devices only, host-fetched params) ---------------

    def _run_eval_only(self):
        """Pure evaluation: drain the eval queue against saved params.

        No collective, no world membership, no training loop — the
        reference serves eval-only from the same worker loop
        (reference worker/worker.py:866-876); here the loop shrinks to
        the eval-task drain the interleaved path already uses. Params
        come from the newest complete sharded checkpoint (sharded zoos
        score through their host twin via _sharded_forward) or an
        exported model file."""
        drained_rounds = 0
        while True:
            executed = self._evaluate_only()
            task = self.get_task()  # non-eval queue: detects job end
            if task.shard_name:
                # unexpected non-eval work (mixed job?): report it back
                # untouched as failed so the master re-routes it
                self.report_task_result(
                    task.task_id,
                    err_msg="eval-only worker cannot run task type %s"
                    % task.type,
                )
            if not executed and not task.shard_name:
                drained_rounds += 1
                if drained_rounds >= 3:
                    break
                time.sleep(0.5)
            else:
                drained_rounds = 0
        # giving up: a drained eval queue is normal completion, but a
        # task that is STILL there means every attempt deferred (e.g. the
        # checkpoint dir is empty and no trainer will ever fill it) —
        # fail loudly instead of letting the master wait on requeues
        # forever
        from elasticdl_tpu.common.constants import TaskType

        leftover = self.get_task(TaskType.EVALUATION)
        if leftover.shard_name:
            self.report_task_result(
                leftover.task_id,
                err_msg="eval-only worker giving up: no scoreable params",
            )
            raise RuntimeError(
                "evaluation-only job cannot make progress: eval tasks "
                "keep deferring (is --checkpoint_dir empty / "
                "--checkpoint_filename_for_init unreadable, or does the "
                "checkpoint's parameter structure mismatch the model "
                "built from --model_params?)"
            )
        return []

    def _run_predict_only(self):
        """Pure prediction: stream prediction tasks through the dataset
        machinery, forward with saved params, hand outputs to the zoo's
        processor — the PS worker's _predict_only shape (reference
        worker.py:879-899), with record accounting via
        report_record_done so a failed batch fail-reports its task."""
        import jax

        if self._prediction_outputs_processor is None:
            # reference contract (worker.py:230-240): warn, don't fail —
            # outputs are simply not processed
            logger.warning(
                "prediction_outputs_processor is not defined in the "
                "model definition. Prediction outputs are not processed."
            )
        while True:
            dataset = self._task_data_service.get_dataset()
            if not dataset:
                break
            dataset = self._dataset_fn(
                dataset,
                Mode.PREDICTION,
                self._task_data_service.data_reader.metadata,
            )
            dataset = dataset.batch(self._minibatch_size).prefetch(1)
            for features in dataset:
                count = int(
                    np.asarray(
                        jax.tree_util.tree_leaves(features)[0]
                    ).shape[0]
                )
                err_msg = ""
                outputs = None
                # bounded retry before giving up (parity with the
                # eval-only drain's 3 rounds): a transiently missing or
                # torn checkpoint — e.g. a trainer still flushing async
                # writes into a shared dir — resolves in seconds and must
                # not fail the whole predict job. Only the FORWARD
                # retries; the user's outputs processor runs once (a
                # replay would duplicate records already written to its
                # sink)
                for attempt in range(3):
                    err_msg = ""
                    try:
                        outputs = self._serving_forward(features)
                        break
                    except RuntimeError as e:
                        # e.g. no restorable checkpoint yet: retry, then
                        # fail-report so the task requeues; the give-up
                        # below keeps a dead checkpoint source from
                        # spinning forever
                        logger.warning(
                            "prediction batch deferred (attempt %d): %s",
                            attempt + 1,
                            e,
                        )
                        err_msg = str(e)
                        if attempt < 2:
                            time.sleep(0.5)
                if (
                    not err_msg
                    and self._prediction_outputs_processor is not None
                ):
                    try:
                        self._prediction_outputs_processor.process(
                            outputs, self._worker_id
                        )
                    except RuntimeError as e:
                        # processor failures are terminal (no replay —
                        # it may have partially written its sink) but
                        # must still fail-report below so the master
                        # requeues immediately instead of waiting for
                        # worker-death detection
                        logger.warning(
                            "prediction outputs processor failed: %s", e
                        )
                        err_msg = str(e)
                self._task_data_service.report_record_done(
                    count, err_msg
                )
                if err_msg:
                    raise RuntimeError(
                        "prediction-only job cannot make progress: %s"
                        % err_msg
                    )
        return []

    def _serving_forward(self, features):
        """Forward for serving jobs: sharded zoos go through the host
        twin, everything else through the checkpoint-loaded params."""
        if self.trainer.is_sharded:
            return self._sharded_forward(features)
        return self._eval_only_forward(features)

    def _eval_only_forward(self, features):
        if self._eval_params is None:
            self._load_eval_only_params(features)
        if self._forward_fn is None:
            from elasticdl_tpu.training.step import make_forward_fn

            self._forward_fn = make_forward_fn(self._model)
        params, state = self._eval_params
        return self._forward_fn(params, state, features)

    def _load_eval_only_params(self, features):
        """Newest complete sharded checkpoint, else the exported model
        file (params only — exported models carry no mutable state, so
        stateful models evaluate with init-fresh state)."""
        if self._ckpt is not None:
            from elasticdl_tpu.common.sharded_checkpoint import (
                load_sharded_to_host,
            )

            for directory in self._ckpt_dirs_newest_first():
                try:
                    loaded_version, tree = load_sharded_to_host(directory)
                except Exception:
                    logger.debug(
                        "eval restore skipped torn checkpoint %s",
                        directory,
                        exc_info=True,
                    )
                    continue
                self._eval_params = (
                    tree["params"],
                    tree.get("state") or {},
                )
                self._eval_scored_version = loaded_version
                return
        if self._init_ckpt_file:
            import jax

            from elasticdl_tpu.common.model_utils import (
                load_from_checkpoint_file,
            )
            from elasticdl_tpu.common.tensor import named_arrays_to_pytree
            from elasticdl_tpu.nn.model_api import (
                init_variables,
                split_variables,
            )

            # accepts a .chkpt file or an export-artifact directory
            # (load_from_checkpoint_file resolves both)
            version, named = load_from_checkpoint_file(
                self._init_ckpt_file
            )
            one = jax.tree_util.tree_map(
                lambda x: np.asarray(x)[:1], features
            )
            template, state = split_variables(
                init_variables(self._model, jax.random.PRNGKey(0), one)
            )
            params = named_arrays_to_pytree(named, template)
            logger.info(
                "eval-only: scoring exported model v%d from %s",
                version,
                self._init_ckpt_file,
            )
            self._eval_params = (params, state)
            self._eval_scored_version = version
            return
        raise RuntimeError(
            "no restorable checkpoint in %r for evaluation"
            % (self._ckpt._base if self._ckpt is not None else "")
        )

    def _local_forward(self, features, pinned_version=None):
        if self.trainer.is_sharded:
            return self._sharded_forward(features)
        if self._job_type == JobType.EVALUATION_ONLY:
            return self._eval_only_forward(features)
        if self._forward_fn is None:
            from elasticdl_tpu.training.step import make_forward_fn

            self._forward_fn = make_forward_fn(self._model)
        if (
            pinned_version is None
            or self._eval_params_version != pinned_version
        ):
            # eval rounds pin the version a sync-point report carried,
            # and the run loop polls the eval queue at the NEXT iteration
            # (before any further step), so the common case snapshots at
            # exactly the pinned version — the cached snapshot then
            # serves every task of the round even after training moves
            # on (the reference's pinned-checkpoint semantics,
            # reference master/evaluation_service.py:186-203). A late
            # grab (re-form raced the round) scores current params and
            # reports the true version alongside.
            version = self.trainer.version
            if self._eval_params_version != version:
                host_ts = self.trainer.snapshot()
                if host_ts is None:
                    # never trained (peers drained the queue before this
                    # process got a task): no params to evaluate with
                    raise RuntimeError(
                        "no local train state for evaluation"
                    )
                self._eval_params = (host_ts.params, host_ts.state)
                self._eval_params_version = version
        self._eval_scored_version = self._eval_params_version
        params, state = self._eval_params
        return self._forward_fn(params, state, features)

    def _sharded_forward(self, features):
        """Eval forward for sharded-parameter jobs: the host-twin model
        over full tables assembled from the newest complete checkpoint.

        Evaluation therefore scores the checkpoint version (lagged by at
        most the cadence) — the same approximation the replicated plane
        makes in the other direction (it scores current params whatever
        version the eval task pinned)."""
        from elasticdl_tpu.common.sharded_checkpoint import (
            load_sharded_to_host,
        )

        candidates = self._ckpt_dirs_newest_first()
        if not candidates:
            raise RuntimeError(
                "no sharded checkpoint yet; eval params unavailable"
            )
        # re-assemble only when a checkpoint newer than the last ATTEMPT
        # appears — keyed on the attempt, not the loaded dir, so a torn
        # newest (killed peer) doesn't trigger a full-model disk reload
        # on every eval minibatch
        if candidates[0] != self._eval_params_version:
            self._eval_params_version = candidates[0]
            tree = None
            for directory in candidates:
                try:
                    loaded_version, tree = load_sharded_to_host(directory)
                    # reported alongside the pinned round version so the
                    # published summary shows the cadence lag honestly
                    self._eval_scored_version = loaded_version
                    break
                except Exception:
                    # newest may be mid-write by a peer; older complete
                    # versions are fine for a lagged eval
                    logger.debug(
                        "lagged-eval restore skipped %s",
                        directory,
                        exc_info=True,
                    )
                    continue
            if tree is not None:
                if self._forward_fn is None:
                    from elasticdl_tpu.training.step import (
                        make_forward_fn,
                    )

                    self._forward_fn = make_forward_fn(
                        self._host_model_factory()
                    )
                self._eval_params = (
                    tree["params"],
                    tree.get("state") or {},
                )
            elif self._eval_params is None:
                self._eval_params_version = None  # retry next call
                raise RuntimeError(
                    "no complete sharded checkpoint for evaluation"
                )
            # else: every candidate torn right now; score the previous
            # assembly rather than thrashing the disk
        params, state = self._eval_params
        return self._forward_fn(params, state, features)

    def _evaluate_only(self, final=False):
        """Drain pending eval tasks. ``final=True`` (the _finalize call,
        where no later training iteration will retry) waits out transient
        deferrals — e.g. a peer's final checkpoint still landing — so a
        requeued eval task is never abandoned with the job unfinished."""
        from elasticdl_tpu.common.constants import TaskType

        eval_only = self._job_type == JobType.EVALUATION_ONLY
        if not eval_only and not self.trainer.has_state:
            # no params to evaluate with (never trained): leave the eval
            # tasks for peers that have state — grabbing one here would
            # fail-requeue-regrab in a tight livelock. Eval-only workers
            # instead score saved checkpoints, so they always proceed.
            return False
        executed = False
        retries = 30 if final else 0
        while True:
            task = self.get_task(TaskType.EVALUATION)
            if not task.shard_name:
                break
            if not self._process_eval_task(task):
                # deferred (e.g. no sharded checkpoint yet): the task
                # requeued. Mid-training, stop regrabbing in a tight
                # loop — the next training iteration retries.
                if retries <= 0:
                    break
                retries -= 1
                time.sleep(1.0)
                continue
            executed = True
        return executed

    def _collective_evaluate(self, final=False):
        """In-plane lockstep eval for sharded-parameter jobs: every
        rank participates in every collective forward (the model's
        lookups/ring ARE collectives), ranks without eval work feed
        dummy rows until the all-gathered pending count reaches zero.

        Called at ALIGNED points only — the same step index on every
        rank (the aligned-sync block mid-training; the quiescence-
        aligned _finalize) — so the consensus gathers and forwards
        line up. Scores CURRENT parameters on the training plane
        itself: no checkpoint in the path, no host twin, and the
        sharded tables never materialize in one host's RAM (the
        reference's evaluate-on-the-training-plane semantics,
        reference worker/worker.py:659-693).

        ``final=True`` (the _finalize call, no later iteration will
        retry): waits out transient empty consensus rounds — a task
        fail-requeued by one rank can land back on the master just
        after every rank polled empty, and abandoning it would hang
        the job. The re-check loop is itself consensus-driven, so all
        ranks count the same empty rounds and exit together."""
        from elasticdl_tpu.common.constants import TaskType

        pending = None  # (task_id, model_version, batches, outs, labels)
        empty_rounds = 0
        while True:
            if pending is None:
                task = self.get_task(TaskType.EVALUATION)
                if task.shard_name:
                    pending = self._start_eval_task(task)
            have = pending is not None
            if self.trainer.eval_have_consensus(have) == 0:
                empty_rounds += 1
                if not final or empty_rounds >= 3:
                    break
                time.sleep(0.5)
                continue
            empty_rounds = 0
            feats, labels, count = (None, None, 0)
            if pending is not None:
                feats, labels, count = pending[2].pop(0)
            outputs = self.trainer.eval_step(
                feats, self._minibatch_size
            )
            if pending is None:
                continue  # dummy participation for a busy peer
            if not isinstance(outputs, dict):
                outputs = {MetricsDictKey.MODEL_OUTPUT: outputs}
            for k, v in outputs.items():
                pending[3].setdefault(k, []).append(
                    np.asarray(v)[:count]
                )
            pending[4].append(np.asarray(labels))
            if not pending[2]:
                self._eval_scored_version = self.trainer.version
                self._report_eval_outputs(
                    pending[0], pending[1], pending[3], pending[4]
                )
                pending = None

    def _report_eval_outputs(
        self, task_id, model_version, out_chunks, label_chunks
    ):
        """Publish one eval task's accumulated outputs and complete it;
        a reporting failure fail-reports the task for retry instead of
        propagating (shared by the local and in-plane eval paths)."""
        try:
            if out_chunks:
                self._stub.report_evaluation_metrics(
                    model_version,
                    {
                        k: np.concatenate(v)
                        for k, v in out_chunks.items()
                    },
                    np.concatenate(label_chunks),
                    scored_version=self._eval_scored_version,
                )
            self.report_task_result(task_id)
        except Exception as e:
            logger.warning(
                "eval task %d report failed: %s", task_id, e
            )
            try:
                self.report_task_result(task_id, err_msg=str(e))
            except Exception:
                # master unreachable: its death detection requeues
                logger.debug(
                    "fail-report for eval task %d also failed",
                    task_id,
                    exc_info=True,
                )

    def _start_eval_task(self, task):
        """Materialize one eval task's batches for the lockstep rounds.
        Returns [task_id, model_version, [(features, labels, count)],
        out_chunks, label_chunks] or None (task fail-reported)."""
        eval_info = self._task_data_service.get_validation_dataset(task)
        if not eval_info:
            return None
        dataset, model_version, task_id = eval_info
        dataset = self._dataset_fn(
            dataset,
            Mode.EVALUATION,
            self._task_data_service.data_reader.metadata,
        )
        dataset = dataset.batch(self._minibatch_size)
        import jax

        batches = []
        try:
            for features, labels in dataset:
                count = int(
                    np.asarray(
                        jax.tree_util.tree_leaves(features)[0]
                    ).shape[0]
                )
                batches.append((features, labels, count))
        except Exception as e:
            logger.warning("eval task %d unreadable: %s", task_id, e)
            self.report_task_result(task_id, err_msg=str(e))
            return None
        if not batches:
            self.report_task_result(task_id)
            return None
        return [task_id, model_version, batches, {}, []]

    def _process_eval_task(self, task):
        """Returns True when the task completed (success or reported
        failure another worker should retry); False when deferred — the
        caller stops regrabbing until the next training iteration."""
        eval_info = self._task_data_service.get_validation_dataset(task)
        if not eval_info:
            return False
        dataset, model_version, task_id = eval_info
        dataset = self._dataset_fn(
            dataset,
            Mode.EVALUATION,
            self._task_data_service.data_reader.metadata,
        )
        dataset = dataset.batch(self._minibatch_size)
        if (
            self._job_type != JobType.EVALUATION_ONLY
            and not self.trainer.has_state
        ):
            # fail the task so a worker that has trained state redoes it
            self.report_task_result(
                task_id, err_msg="no local train state for evaluation"
            )
            return False
        out_chunks, label_chunks = {}, []
        try:
            for features, labels in dataset:
                outputs = self._local_forward(
                    features, pinned_version=model_version
                )
                if not isinstance(outputs, dict):
                    outputs = {MetricsDictKey.MODEL_OUTPUT: outputs}
                for k, v in outputs.items():
                    out_chunks.setdefault(k, []).append(np.asarray(v))
                label_chunks.append(np.asarray(labels))
        except RuntimeError as e:
            # e.g. a sharded job's first eval task arriving before any
            # checkpoint exists — fail-report so the task requeues and a
            # later round (with a checkpoint) redoes it, instead of
            # crash-looping the worker
            logger.warning("eval task %d deferred: %s", task_id, e)
            self.report_task_result(task_id, err_msg=str(e))
            return False
        self._report_eval_outputs(
            task_id, model_version, out_chunks, label_chunks
        )
        return True

    # -- export -------------------------------------------------------------

    def _process_save_model_task_if_needed(self):
        (
            task,
            dataset,
        ) = self._task_data_service.get_save_model_task_and_dataset()
        if task is None:
            return
        saved_model_path = task.extended_config.get(
            SaveModelConfig.SAVED_MODEL_PATH, "/tmp/edl_saved_model"
        )
        if self.trainer.is_sharded:
            params, state, version = self._assemble_sharded_export()
            if params is None:
                self.report_task_result(
                    task.task_id,
                    err_msg="no complete sharded checkpoint to export",
                )
                return
            # serving plane traces the host twin (dense lookups, same
            # param structure the sharded checkpoint assembles to)
            model = (
                self._host_model_factory()
                if self._host_model_factory is not None
                else None
            )
        else:
            host_ts = self.trainer.snapshot()
            if host_ts is None:
                # never trained (no data ever assigned); let another
                # worker with state pick the task up
                self.report_task_result(
                    task.task_id, err_msg="no local train state to export"
                )
                return
            params = host_ts.params
            state = host_ts.state
            version = max(0, int(np.asarray(host_ts.version)))
            model = self._model
        saved_model_path = os.path.join(
            saved_model_path, str(int(time.time()))
        )
        from elasticdl_tpu.common.export import (
            example_batch_for_export,
            export_model,
            make_serving_fn,
        )

        example = example_batch_for_export(
            dataset,
            self._dataset_fn,
            self._task_data_service.data_reader.metadata,
            self._minibatch_size,
            Mode.PREDICTION,
        )
        export_model(
            saved_model_path,
            params,
            version,
            metadata=self._export_meta,
            serving_fn=(
                make_serving_fn(model, state)
                if model is not None and example is not None
                else None
            ),
            example_features=example,
        )
        logger.info("Exported model to %s", saved_model_path)
        self.report_task_result(task_id=task.task_id, err_msg="")

    def _assemble_sharded_export(self):
        """Full host model from the newest complete sharded checkpoint.

        Every rank wrote a final checkpoint entering _finalize, but the
        export-task rank may get here before its peers' manifests land —
        retry on incomplete coverage before falling back to the previous
        complete version."""
        from elasticdl_tpu.common.sharded_checkpoint import (
            load_sharded_to_host,
        )

        directory = self._latest_ckpt_dir()
        if directory is None:
            return None, None, 0
        last_err = None
        for attempt in range(10):
            try:
                version, tree = load_sharded_to_host(directory)
                return tree["params"], tree.get("state") or {}, version
            except Exception as e:  # noqa: BLE001 - retried, then logged
                last_err = e
                time.sleep(1.0)
        logger.warning(
            "newest checkpoint %s never completed (%s); exporting the "
            "previous one",
            directory,
            last_err,
        )
        for older in self._ckpt.dirs_newest_first()[1:]:
            try:
                v, tree = load_sharded_to_host(older)
                return tree["params"], tree.get("state") or {}, v
            except Exception:
                logger.debug(
                    "restore skipped torn checkpoint %s",
                    older,
                    exc_info=True,
                )
                continue
        return None, None, 0

    def _save_ckpt_if_newer(self):
        """Checkpoint the current state if its version advanced past the
        last save (all three call sites: graceful epoch bump, global
        quiescence, finalize)."""
        if self._ckpt is None or not self._ckpt.is_enabled():
            return
        version = self.trainer.version
        if version > self._last_ckpt_version:
            self._ckpt.save(self.trainer._ts, version)
            self._last_ckpt_version = version

    def _drain_ckpt(self):
        """Land queued async checkpoint writes; surface IO errors as a
        warning (teardown must not mask the original failure)."""
        if self._ckpt is None:
            return
        try:
            self._ckpt.close()
        except Exception:
            logger.warning(
                "async checkpoint writes failed at teardown",
                exc_info=True,
            )

    def _finalize(self):
        if self._preempted:
            # drained under a preemption notice: land queued checkpoint
            # writes and get out — taking MORE work (final eval rounds,
            # the SAVE_MODEL task) on a dying node would strand it
            self._drain_ckpt()
            from elasticdl_tpu.parallel import distributed

            if distributed.current_spec() is not None:
                distributed.leave_world()
            return
        if self.trainer.is_sharded and self.trainer._ts is not None:
            # every rank lands a final checkpoint so the export task (one
            # rank) and any resume see the finished state, not the last
            # cadence point
            self._save_ckpt_if_newer()
        self._drain_ckpt()
        if self._job_type == JobType.TRAINING_WITH_EVALUATION:
            try:
                if (
                    self.trainer.is_sharded
                    and self.trainer._ts is not None
                ):
                    # the world is still formed (ranks leave below) and
                    # every rank enters _finalize from the SAME
                    # quiescence round, so the lockstep eval stays
                    # aligned; it also drains the queue collectively —
                    # each round every idle rank re-polls for tasks
                    self._collective_evaluate(final=True)
                else:
                    self._evaluate_only(final=True)
            except Exception:
                logger.warning("final eval round failed", exc_info=True)
        self._process_save_model_task_if_needed()
        from elasticdl_tpu.utils.profiling import maybe_stop_trace

        maybe_stop_trace()
        from elasticdl_tpu.parallel import distributed

        if distributed.current_spec() is not None:
            distributed.leave_world()
