"""Worker process entry.

Parity: reference worker/main.py — builds the master channel (256 MB caps
live in rpc.core), optional PS channels from ``--ps_addrs``, then runs the
task loop to completion.
"""

import os
import sys

from elasticdl_tpu.common.args import (
    parse_worker_args,
    warn_accum_unsupported,
)
from elasticdl_tpu.common.log_utils import default_logger as logger
from elasticdl_tpu.master.rpc_service import MasterClient
from elasticdl_tpu.worker.worker import Worker


def main():
    from elasticdl_tpu.common.jax_platform import honor_jax_platforms_env

    honor_jax_platforms_env()
    args = parse_worker_args()
    if args.distribution_strategy == "AllreduceStrategy":
        # the elastic worker must not touch the JAX backend before its
        # jax.distributed world forms; it starts the env-selected trace
        # itself after the first establish
        return _run(args)
    from elasticdl_tpu.utils.profiling import maybe_profile

    with maybe_profile():
        return _run(args)


def _run(args):
    from elasticdl_tpu.utils import profiling

    # tracing identity: every span id / postmortem header from this
    # process names the worker; the flight recorder arms only from the
    # env (worker pods own no durable directory — the operator points
    # EDL_FLIGHT_RECORDER_DIR at one) (docs/observability.md)
    profiling.spans.set_process("worker-%d" % args.worker_id)
    profiling.maybe_arm_flight_recorder()
    wire_dtype = getattr(args, "wire_dtype", "")
    stub = (
        MasterClient(
            args.master_addr,
            wire_dtype=wire_dtype,
            # co-located master pods serve get_model replies through a
            # negotiated shm ring; cross-host (or any attach failure)
            # silently keeps the bytes path (docs/wire.md)
            shm=getattr(args, "master_shm", "auto"),
            # ride out a master SIGKILL/relaunch instead of dying with
            # it: UNAVAILABLE retries through the outage window and
            # acks dedup on the new incarnation's journal
            # (docs/master_recovery.md)
            failover_s=getattr(args, "master_failover_s", 120.0),
        )
        if args.master_addr
        else None
    )
    ps_client = None
    bound_ps = []
    if args.ps_addrs:
        from elasticdl_tpu.worker.ps_client import BoundPS, PSClient

        addrs = [a for a in args.ps_addrs.split(",") if a]
        window = getattr(args, "hot_row_staleness_window", 0)
        if window <= 0:
            # default staleness bound: the SSP window the worker already
            # trains under between model pulls
            window = getattr(args, "get_model_steps", 1)
        deadline_s = getattr(args, "rpc_deadline_s", 60.0)
        bound_ps = [
            BoundPS(
                a,
                deadline_s=deadline_s if deadline_s > 0 else None,
                retries=getattr(args, "rpc_retries", 2),
                # co-located pods negotiate the shared-memory payload
                # path at first call; cross-host (or any attach
                # failure) silently keeps the bytes path (docs/wire.md)
                shm=getattr(args, "ps_shm", "auto"),
                shm_slots=getattr(args, "ps_shm_slots", 4),
                shm_slot_mb=getattr(args, "ps_shm_slot_mb", 8),
            )
            for a in addrs
        ]
        ps_client = PSClient(
            bound_ps,
            wire_dtype=wire_dtype,
            hot_row_cache_rows=getattr(args, "hot_row_cache_rows", 0),
            staleness_window=window,
            fanout=getattr(args, "ps_fanout", True),
            push_inflight=getattr(args, "ps_push_inflight", 0),
        )
    from elasticdl_tpu.common.model_utils import get_dict_from_params_str

    if args.distribution_strategy == "AllreduceStrategy":
        # a worker process under a master always runs the elastic
        # multi-process plane (a world of one process is the degenerate
        # case); the single-process AllReduceWorker remains the in-process
        # form used by the local API mode
        from elasticdl_tpu.worker.elastic_allreduce_worker import (
            ElasticAllReduceWorker,
        )

        worker = ElasticAllReduceWorker(
            worker_id=args.worker_id,
            job_type=args.job_type,
            minibatch_size=args.minibatch_size,
            model_zoo=args.model_zoo,
            model_def=args.model_def,
            model_params=args.model_params,
            dataset_fn=args.dataset_fn,
            loss=args.loss,
            optimizer=args.optimizer,
            eval_metrics_fn=args.eval_metrics_fn,
            stub=stub,
            data_reader_params=get_dict_from_params_str(
                args.data_reader_params
            ),
            comm_host=args.comm_host or None,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_steps=args.checkpoint_steps,
            keep_checkpoint_max=args.keep_checkpoint_max,
            checkpoint_filename_for_init=args.checkpoint_filename_for_init,
            prediction_outputs_processor=args.prediction_outputs_processor,
            precision=args.precision_policy or None,
            accum_steps=args.grad_accum_steps,
            remat=args.remat,
            replica_refresh_steps=args.replica_refresh_steps,
            task_prefetch=getattr(args, "task_prefetch", 1),
            speculative_compile=getattr(
                args, "speculative_compile", False
            ),
            telemetry_report_secs=getattr(
                args, "telemetry_report_secs", 5.0
            ),
        )
        if getattr(args, "standby", False):
            # pre-warmed spare: the cold start (jax/flax import chain
            # plus worker construction — ~all of a relaunch's 45-50 s,
            # BASELINE.md r3) was just paid ABOVE; park until the master
            # promotes this process, then adopt the assigned id. No
            # device is touched while parked (that would pin the
            # backend and break the world formation after promotion).
            import time as _time

            from elasticdl_tpu.common.log_utils import (
                default_logger as logger,
            )

            token = args.worker_id
            logger.info("standby %d warmed; parking", token)
            failures = 0
            while True:
                try:
                    wid = stub.standby_poll(token)
                    failures = 0
                except Exception:
                    # a transient RPC blip (master busy mid-formation)
                    # must not kill the spare that just paid its cold
                    # start — but a master that stays unreachable for
                    # ~2 min is gone, and an orphaned standby must not
                    # spin (and log) forever
                    failures += 1
                    if failures >= 60:
                        logger.error(
                            "standby %d: master unreachable for %d "
                            "consecutive polls; exiting",
                            token,
                            failures,
                        )
                        return 1
                    logger.warning(
                        "standby poll failed (%d); retrying", failures
                    )
                    wid = None
                if wid is not None:
                    logger.info(
                        "standby %d promoted to worker %d", token, wid
                    )
                    worker._worker_id = int(wid)
                    break
                _time.sleep(0.5 if failures == 0 else 2.0)
        # graceful preemption: cloud preemptions / pod evictions send
        # SIGTERM with notice — drain at the next batch boundary
        # (checkpoint + clean world leave) instead of dying
        # mid-collective
        worker.enable_drain_on_sigterm()
        worker.run()
        if not worker._preempted:
            # announce the clean completion BEFORE exiting: membership
            # exempts this process's coming rc-0 exit from the
            # survivors' wedge-escape dead list only for announced
            # leaves (an unannounced exit 0 — user code calling
            # sys.exit(0) mid-step — must still read as a death there).
            # All device/collective work is done (global quiescence +
            # _finalize), so nobody can be wedged on this rank.
            # Best-effort: if the RPC misses, the watch dead-lists the
            # exit and teardown-window survivors recover via one
            # (spurious but safe) reform.
            try:
                if stub is not None:
                    stub.leave_comm_world(worker._worker_id)
            except Exception:
                logger.debug(
                    "leave announcement missed; the watch dead-lists "
                    "this exit and survivors reform",
                    exc_info=True,
                )
        if worker._preempted:
            # distinct exit code: the instance manager relaunches a
            # replacement (exit 0 would read as "job done for me").
            # Hard exit, skipping atexit teardown: the drained world is
            # being torn down by every member at once, and a
            # jax.distributed.shutdown whose coordinator (rank 0's
            # process) already left FATALs in C++ — turning a clean
            # drain into a crash exit. Checkpoint writes were drained
            # in _finalize; there is nothing left worth tearing down.
            import sys as _sys

            _sys.stderr.flush()
            _sys.stdout.flush()
            os._exit(ElasticAllReduceWorker.PREEMPTED_EXIT_CODE)
        return 0

    warn_accum_unsupported(args, "the parameter-server worker")
    worker = Worker(
        worker_id=args.worker_id,
        job_type=args.job_type,
        minibatch_size=args.minibatch_size,
        model_zoo=args.model_zoo,
        model_def=args.model_def,
        model_params=args.model_params,
        dataset_fn=args.dataset_fn,
        loss=args.loss,
        optimizer=args.optimizer,
        eval_metrics_fn=args.eval_metrics_fn,
        prediction_outputs_processor=args.prediction_outputs_processor,
        stub=stub,
        ps_client=ps_client,
        get_model_steps=args.get_model_steps,
        data_reader_params=get_dict_from_params_str(
            args.data_reader_params
        ),
        precision=args.precision_policy or None,
        task_prefetch=getattr(args, "task_prefetch", 1),
        task_ack_queue=getattr(args, "task_ack_queue", 8),
        loss_log_steps=getattr(args, "loss_log_steps", 20),
        telemetry_report_secs=getattr(
            args, "telemetry_report_secs", 5.0
        ),
        embedding_plane=getattr(args, "embedding_plane", "ps"),
        # streaming serving exports (docs/serving.md): relayed from
        # the master's flags like every other train param
        export_dir=getattr(args, "export_dir", "") or None,
        export_every_versions=getattr(
            args, "export_every_versions", 0
        ),
        export_keep=getattr(args, "export_keep", 4),
    )
    try:
        worker.run()
    finally:
        if ps_client is not None:
            # settles any still-pending async pushes and releases the
            # fan-out threads
            ps_client.close()
        for bound in bound_ps:
            # unlink negotiated shm rings + close the channels (the
            # atexit hook is only the crash floor)
            bound.close()
        if stub is not None:
            # same discipline for the master channel's negotiated ring
            stub.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
