"""Worker-side sharded-PS client.

Parity: the multi-PS paths inside reference worker/worker.py — variables
partitioned to PS shards by name hash (:279-291), embedding rows by
``id % N`` (:229-252), per-shard gradient pushes (:383-450), and the
pull-merge of dense params. Partition placement uses common/hash_utils so
row/variable placement is stable across restarts and matches the
checkpoint layout.

Overlap (docs/dense_overlap.md): every logical data-plane call fans its
per-shard RPCs out concurrently over a small thread pool, so an N-shard
fleet costs one round trip instead of N; ``push_inflight > 0`` makes
``push_gradient`` non-blocking behind a bounded in-flight window that
drains at every ``pull_dense`` and at worker task boundaries. The caller
contract is single-threaded: one worker thread drives the client; the
internal pools only ever run the per-shard legs and the queued pushes.
"""

import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from elasticdl_tpu.common.hash_utils import (
    scatter_embedding_vector,
    string_to_id,
)
from elasticdl_tpu.common.tensor import Tensor, release_message


# HotRowCache moved behind the comm-plane interface (nn/comm_plane.py)
# so one version-tagged cache instance can serve every plane a table
# rides; imported here for the historical call sites.
from elasticdl_tpu.nn.comm_plane import HotRowCache  # noqa: E402,F401


class PSClient:
    def __init__(
        self,
        ps_stubs,
        wire_dtype="",
        combine_push=True,
        hot_row_cache_rows=0,
        staleness_window=1,
        fanout=True,
        push_inflight=0,
        cache=None,
        on_shard_reset=None,
    ):
        """``ps_stubs``: list of objects exposing the Pserver dict-RPC
        methods — rpc.core Clients bound with ``BoundPS`` below, or
        in-process PserverServicer instances (the reference test rung 2
        uses both). ``wire_dtype="bfloat16"`` compresses pushed
        gradients (see rpc/wire_compression.py); pulled params
        decompress by the response's own field.

        Sparse fast path knobs (docs/sparse_fast_path.md):
        ``combine_push`` (default on) segment-sums duplicate sparse rows
        before the wire so each push carries one row per unique id;
        ``hot_row_cache_rows`` > 0 enables a :class:`HotRowCache` of
        that many rows whose entries stay valid for
        ``staleness_window`` PS versions (wire it to the worker's SSP
        window, ``get_model_steps``).

        Overlap knobs (docs/dense_overlap.md): ``fanout`` (default on)
        issues the per-shard RPCs of one logical call concurrently;
        ``push_inflight`` > 0 makes ``push_gradient`` non-blocking with
        at most that many logical pushes on the wire (1 = classic
        double buffering: compute batch k+1 while batch k's gradients
        travel). The window drains at every ``pull_dense`` and via
        :meth:`drain`."""
        self._ps = ps_stubs
        self._wire_dtype = wire_dtype
        self._combine_push = combine_push
        # ``cache``: an externally-owned (plane-shared) HotRowCache —
        # the comm-plane refactor lets one version-tagged cache back
        # every PS-resident table, whichever client pulls them
        # (docs/embedding_planes.md); hot_row_cache_rows > 0 keeps the
        # historical per-client construction.
        self._cache = cache if cache is not None else (
            HotRowCache(hot_row_cache_rows, staleness_window)
            if hot_row_cache_rows > 0
            else None
        )
        self._fanout_enabled = bool(fanout)
        self._fanout_pool = None
        self._push_inflight = max(0, int(push_inflight))
        self._push_pool = None
        # lazy pool creation happens on whichever thread first needs a
        # pool (the minibatch path or a push-window driver) and close()
        # tears them down from the worker's finally — pool handles ride
        # one lock so a racing pair can't double-create and leak the
        # loser's threads, and _closed keeps a late caller (a prefetch
        # warm pull racing teardown) from resurrecting a pool nothing
        # would ever shut down (edlint R8)
        self._pool_lock = threading.Lock()
        self._closed = False
        self._pending_pushes = deque()
        # combined outcome of async pushes reaped since the last drain
        self._reaped_accepted = True
        self._last_push_version = -1
        # -- reconnect protocol state (docs/ps_recovery.md) --
        # Every PS reply carries the serving incarnation's shard_epoch
        # (a boot id). A changed epoch means the shard died and came
        # back — possibly restored to an OLDER version — and this
        # client runs the reconnect protocol: invalidate that shard's
        # cache entries, abandon the in-flight push window (the
        # non-idempotent pushes raced the dead incarnation; they are
        # dropped, never resent), and re-push model/embedding infos if
        # the shard reports uninitialized. Detection can happen on
        # fan-out/push threads, so the state rides its own lock.
        self._epoch_mu = threading.Lock()
        self._shard_epochs = {}  # shard -> last seen shard_epoch
        self._seen_versions = {}  # shard -> newest version seen
        self._reset_gen = 0  # bumps at every detected epoch change
        self._shard_fail_t = {}  # shard -> first-failure monotonic time
        self._last_probe_t = {}  # shard -> last ps_status probe time
        self._needs_reinit = set()  # shards reporting uninitialized
        self._on_shard_reset = on_shard_reset

    @property
    def hot_row_cache(self):
        """The HotRowCache (None when disabled) — stats live on it."""
        return self._cache

    # -- the reconnect protocol (docs/ps_recovery.md) -----------------------

    def set_on_shard_reset(self, callback):
        """``callback(shards)`` runs on the next data-plane call after a
        relaunched shard reported UNINITIALIZED state (relaunch with no
        snapshot to restore): the worker re-pushes its model + embedding
        infos (first-write-wins, so live shards ignore the re-push)."""
        self._on_shard_reset = callback

    @property
    def shard_epochs(self):
        """{shard: last seen shard_epoch} (diagnostics/tests)."""
        with self._epoch_mu:
            return dict(self._shard_epochs)

    def _note_shard_reply(self, shard, resp):
        """Track the replying incarnation; run the reset protocol on an
        epoch change. Called from whichever thread processed the reply
        (worker, fan-out, or push driver) — state rides _epoch_mu, and
        the cache invalidation happens outside it (the cache has its
        own lock; nesting would add a lock-order edge for nothing)."""
        if not isinstance(resp, dict):
            return
        epoch = resp.get("shard_epoch")
        if epoch is None:
            return
        version = resp.get("version")
        with self._epoch_mu:
            prev = self._shard_epochs.get(shard)
            if prev is not None and epoch < prev:
                # a DELAYED reply from the dead incarnation (its fan-out
                # leg resolved after the relaunch was already detected):
                # epochs are monotonic per shard, so this is stale —
                # recording it would regress the epoch and spuriously
                # re-run the reset against the live incarnation
                return
            self._shard_epochs[shard] = epoch
            changed = prev is not None and epoch > prev
            seen = self._seen_versions.get(shard, -1)
            if changed:
                self._reset_gen += 1
                # re-anchor the version clock at the restored value:
                # the dead incarnation's high-water mark is void
                self._seen_versions[shard] = (
                    int(version) if version is not None else -1
                )
                if (
                    resp.get("initialized") is False
                    or resp.get("model_init_status") is False
                ):
                    self._needs_reinit.add(shard)
                fail_t = self._shard_fail_t.pop(shard, None)
            else:
                if version is not None and int(version) > seen:
                    self._seen_versions[shard] = int(version)
                # a healthy reply clears any stale failure stamp
                self._shard_fail_t.pop(shard, None)
        if not changed:
            return
        rollback = max(
            0, seen - (int(version) if version is not None else seen)
        )
        dropped = 0
        if self._cache is not None:
            dropped = self._cache.invalidate_shard(shard, version=version)
        from elasticdl_tpu.utils import profiling

        profiling.events.emit(
            "ps_shard_restore",
            shard=shard,
            old_epoch=prev,
            new_epoch=epoch,
            version=version,
            rollback_depth=rollback,
            cache_rows_invalidated=dropped,
            restore_latency_s=(
                round(time.monotonic() - fail_t, 3)
                if fail_t is not None
                else None
            ),
        )
        from elasticdl_tpu.common.log_utils import default_logger

        default_logger.warning(
            "PS shard %s relaunched (epoch %s -> %s): version rolled "
            "back %d to %s; %d cached rows invalidated, in-flight push "
            "window abandoned",
            shard,
            prev,
            epoch,
            rollback,
            version,
            dropped,
        )

    def _note_shard_failures(self, shard_keys):
        """Stamp first-failure times and probe the failing shards'
        status (idempotent ``ps_status``): a shard that already came
        back as a new incarnation is detected HERE — before the retry
        machinery re-runs the batch — so the cache/window reset happens
        ahead of the next pull, and an uninitialized relaunch gets
        flagged for the model re-push instead of erroring forever on
        its empty store."""
        shards = set()
        for key in shard_keys:
            shard = key[1] if isinstance(key, tuple) else key
            if isinstance(shard, (int, np.integer)):
                shards.add(int(shard))
        now = time.monotonic()
        with self._epoch_mu:
            for shard in shards:
                self._shard_fail_t.setdefault(shard, now)
            # throttle: the probe pays the data-plane deadline/retry
            # budget against a possibly-dead endpoint, and failures can
            # arrive once per minibatch — probing each shard at most
            # once per second bounds the added failure-path latency
            # without delaying relaunch detection meaningfully
            shards = {
                s
                for s in shards
                if now - self._last_probe_t.get(s, -10.0) >= 1.0
            }
            for shard in shards:
                self._last_probe_t[shard] = now
        for shard in shards:
            try:
                status = self._ps[shard].ps_status({})
            except Exception:  # noqa: BLE001 — still down
                from elasticdl_tpu.common.log_utils import default_logger

                default_logger.debug(
                    "ps_status probe of shard %s failed (still down); "
                    "the next data-plane failure re-probes",
                    shard,
                    exc_info=True,
                )
                continue
            self._note_shard_reply(shard, status)
            if isinstance(status, dict):
                release_message(status)

    def _gen_snapshot(self):
        with self._epoch_mu:
            return self._reset_gen

    def _service_reinit(self):
        """Run the worker's re-push callback for shards that came back
        empty. Runs on the thread entering a data-plane call (the
        worker thread, or the prefetch pipeline's pull thread — both
        only READ the model pytree, and push_model is first-write-wins
        on every shard, so a racing re-push is harmless)."""
        with self._epoch_mu:
            if not self._needs_reinit:
                return
            shards = sorted(self._needs_reinit)
            self._needs_reinit.clear()
        cb = self._on_shard_reset
        if cb is None:
            return
        try:
            cb(shards)
        except Exception:
            # a transient re-push failure (the shard still flapping)
            # must not LOSE the flag — nothing re-adds it until another
            # epoch change, and the empty store would wedge every later
            # pull. Re-arm and let the failure surface normally (the
            # task retry re-enters here).
            with self._epoch_mu:
                self._needs_reinit.update(shards)
            raise

    # -- serving-plane reads (docs/serving.md) ------------------------------

    def serving_status(self, shard):
        """One shard's per-table freshness advertisement
        (ps/servicer.serving_status): {version, shard_epoch, tables,
        floors, initialized}. Rides the reconnect protocol — a changed
        ``shard_epoch`` in the reply triggers the shard-selective cache
        invalidation right here, so a scorer's poll loop detects a PS
        relaunch without waiting for a data-plane pull to fail
        (docs/ps_recovery.md)."""
        resp = self._ps[shard].serving_status({})
        self._note_shard_reply(shard, resp)
        try:
            return {
                "version": int(resp.get("version", -1)),
                "shard_epoch": resp.get("shard_epoch"),
                "initialized": bool(resp.get("initialized", False)),
                "tables": dict(resp.get("tables") or {}),
                "floors": dict(resp.get("floors") or {}),
            }
        finally:
            release_message(resp)

    def pull_embedding_delta(self, shard, name, since_version):
        """Ids of ``name``'s rows shard ``shard`` updated after
        ``since_version`` -> (ids int64, covered_version, complete).
        Idempotent read (edlint R9) — safe under the retriable
        data-plane channel."""
        resp = self._ps[shard].pull_embedding_delta(
            {"name": name, "since_version": int(since_version)}
        )
        self._note_shard_reply(shard, resp)
        try:
            # materialize: the decoded ids are a zero-copy view into
            # the reply buffer (possibly a recycling shm slot)
            ids = np.array(resp["ids"], dtype=np.int64, copy=True)
            return (
                ids,
                int(resp.get("version", since_version)),
                bool(resp.get("complete", False)),
            )
        finally:
            release_message(resp)

    @property
    def num_ps(self):
        return len(self._ps)

    @property
    def push_inflight_window(self):
        return self._push_inflight

    def _ps_of_var(self, name):
        return self._ps[string_to_id(name, self.num_ps)]

    # -- concurrent shard fan-out -------------------------------------------

    def _get_fanout_pool(self):
        with self._pool_lock:
            if self._closed:
                raise RuntimeError("PSClient is closed")
            if self._fanout_pool is None:
                # wider than num_ps: one multi-table pull produces
                # (tables x shards) legs that should all fly in one
                # round
                self._fanout_pool = ThreadPoolExecutor(
                    max_workers=min(16, max(self.num_ps, 8)),
                    thread_name_prefix="edl-ps-fanout",
                )
            return self._fanout_pool

    def _run_sharded(self, calls):
        """Run ``[(shard, thunk), ...]`` and return ``{shard: result}``.

        With fan-out on, every thunk is submitted to the pool at once
        and the per-shard round trips overlap, so one logical call costs
        the slowest shard, not the sum of shards. Completion handling is
        deterministic either way: results are consumed in ascending
        shard order, and on failure the lowest-numbered failing shard's
        exception is raised only after EVERY call finished — no RPC is
        left in flight mutating caller-visible state after the raise.
        """
        if not calls:
            return {}
        if not self._fanout_enabled or len(calls) == 1:
            try:
                return {shard: thunk() for shard, thunk in calls}
            except Exception:  # noqa: BLE001 — probe, then re-raise
                # serial legs run in-line, so the failing shard is not
                # attributable here — probe every shard of the call
                # (ps_status is an idempotent read; a healthy shard's
                # probe just refreshes its epoch record)
                self._note_shard_failures([shard for shard, _ in calls])
                raise
        pool = self._get_fanout_pool()
        futs = [(shard, pool.submit(thunk)) for shard, thunk in calls]
        results, errors = {}, []
        for shard, fut in futs:
            try:
                results[shard] = fut.result()
            except Exception as err:  # noqa: BLE001 — re-raised below
                errors.append((shard, err))
        if errors:
            errors.sort(key=lambda pair: pair[0])
            # reconnect protocol: stamp + probe the failing shards so a
            # relaunched incarnation is detected before the retry runs
            self._note_shard_failures([shard for shard, _ in errors])
            raise errors[0][1]
        return results

    def close(self):
        """Drain pending pushes and release the fan-out/push threads.

        Best-effort on the drain: close() runs from teardown paths
        (worker main's finally), where a dead-shard error has already
        surfaced through drain()/pull_dense and must not mask the
        original failure — it is logged, not re-raised."""
        try:
            self.drain()
        except Exception as err:  # noqa: BLE001 — teardown best-effort
            from elasticdl_tpu.common.log_utils import default_logger

            default_logger.warning(
                "async push window failed to drain at close: %s", err
            )
        finally:
            # detach under the lock, shut down outside it (shutdown
            # waits on worker threads; holding the lock across that
            # would stall a concurrent _get_fanout_pool for the
            # duration)
            with self._pool_lock:
                self._closed = True
                pools = (self._push_pool, self._fanout_pool)
                self._push_pool = None
                self._fanout_pool = None
            for pool in pools:
                if pool is not None:
                    pool.shutdown(wait=True)

    # -- model lifecycle ----------------------------------------------------

    def push_model(self, named_params, embedding_infos=None, version=0):
        """Partition dense vars by name hash; infos go to every shard.

        All shard pushes go out concurrently; the call returns only
        once every shard has acked its partition."""
        partitions = [{} for _ in range(self.num_ps)]
        for name, arr in named_params.items():
            partitions[string_to_id(name, self.num_ps)][name] = arr
        infos = [
            {"name": i.name, "dim": i.dim, "initializer": i.initializer}
            for i in embedding_infos or ()
        ]
        calls = []
        for shard, (ps, part) in enumerate(zip(self._ps, partitions)):
            req = {
                "version": version,
                "params": [Tensor(n, v) for n, v in part.items()],
                "embedding_infos": infos,
            }
            calls.append(
                (shard, lambda ps=ps, req=req: ps.push_model(req))
            )
        for shard, resp in self._run_sharded(calls).items():
            # the earliest epoch baseline: a later reply with a
            # DIFFERENT epoch is then a detectable relaunch
            self._note_shard_reply(shard, resp)
            release_message(resp)

    def push_embedding_info(self, embedding_infos):
        infos = [
            {"name": i.name, "dim": i.dim, "initializer": i.initializer}
            for i in embedding_infos
        ]
        resps = self._run_sharded(
            [
                (
                    shard,
                    lambda ps=ps: ps.push_embedding_info(
                        {"embedding_infos": infos}
                    ),
                )
                for shard, ps in enumerate(self._ps)
            ]
        )
        for shard, resp in resps.items():
            self._note_shard_reply(shard, resp)
            release_message(resp)

    def pull_dense(self):
        """Merge every shard's params; returns (all_initialized, version,
        {name: ndarray}).

        Drains the async-push window first, so the pulled model always
        reflects this worker's own completed pushes (the in-flight
        window never widens the SSP staleness bound). All shard pulls
        are issued concurrently; responses merge in ascending shard
        order (names are hash-partitioned, so order cannot change the
        result — the fixed order keeps failure handling deterministic).
        """
        from elasticdl_tpu.rpc.wire_compression import decompress_tensors

        self._service_reinit()
        self.drain()
        resps = self._run_sharded(
            [
                (shard, lambda ps=ps: ps.pull_variable({}))
                for shard, ps in enumerate(self._ps)
            ]
        )
        named = {}
        versions = []
        try:
            for shard in range(self.num_ps):
                resp = resps[shard]
                self._note_shard_reply(shard, resp)
                if not resp.get("model_init_status"):
                    return False, -1, {}
                versions.append(resp["version"])
                if self._cache is not None:
                    self._cache.note_version(shard, resp["version"])
                for t in decompress_tensors(
                    resp.get("params", []), resp.get("compressed_f32")
                ):
                    # AUDITED retention site (docs/wire.md): the worker
                    # keeps these params across steps, so zero-copy
                    # decoded views must materialize here — the single
                    # decode copy of the dense pull. Owned arrays
                    # (in-process stubs, already-upcast bf16) pass
                    # through untouched.
                    named[t.name] = t.materialize().values
        finally:
            for resp in resps.values():
                release_message(resp)
        return True, min(versions), named

    # -- gradients ----------------------------------------------------------

    def push_gradient(self, dense_named, sparse_tensors, version):
        """Per-shard push: dense by var hash, sparse rows by id shard.

        Returns the COMBINED result across shards: ``accepted`` only
        when EVERY shard accepted, ``version`` the minimum shard
        version. This deliberately departs from the reference's
        TODO-choose-last tail (worker.py:444-450), which reported only
        the final shard's response and silently masked an earlier
        shard's stale-gradient rejection.

        With ``push_inflight`` > 0 the call is non-blocking: the whole
        fan-out (compression included) runs on a push thread while the
        worker computes the next batch, bounded to ``push_inflight``
        logical pushes in flight (submitting past the window first
        reaps the oldest). The immediate return is optimistic —
        ``(True, last reconciled version)`` — and the true combined
        outcome is reconciled at the next ``pull_dense``/:meth:`drain`,
        where a shard failure also re-raises. The default window of 1
        keeps pushes strictly ordered per shard.
        """
        reqs = [[] for _ in range(self.num_ps)]
        for name, arr in (dense_named or {}).items():
            reqs[string_to_id(name, self.num_ps)].append(Tensor(name, arr))
        for t in sparse_tensors or ():
            if self._combine_push:
                # one row per unique id on the wire; the PS applies the
                # sum either way (optimizer_wrapper combines at apply),
                # so this only shrinks the payload
                t = t.combined()
            for shard, (values, ids) in scatter_embedding_vector(
                t.values, t.indices, self.num_ps
            ).items():
                reqs[shard].append(Tensor(t.name, values, indices=ids))
        self._service_reinit()
        if self._push_inflight <= 0:
            return self._push_shards(reqs, version)
        while len(self._pending_pushes) >= self._push_inflight:
            self._reap_push(self._pending_pushes.popleft())
        with self._pool_lock:
            if self._closed:
                raise RuntimeError("PSClient is closed")
            if self._push_pool is None:
                # one driver thread per window slot, separate from the
                # fan-out pool (a driver waits on fan-out futures;
                # sharing the pool could starve its own legs)
                self._push_pool = ThreadPoolExecutor(
                    max_workers=self._push_inflight,
                    thread_name_prefix="edl-ps-push",
                )
            push_pool = self._push_pool
        # each queued push remembers the reset generation it was
        # submitted under: an epoch change detected before the reap
        # ABANDONS it (outcome dropped, failure swallowed, never
        # resent) — the window raced a dead incarnation and resolving
        # it against the restored one would double-count or wedge
        self._pending_pushes.append(
            (push_pool.submit(self._push_shards, reqs, version),
             self._gen_snapshot())
        )
        return True, self._last_push_version

    def _push_shards(self, reqs, version):
        """One logical push: compress + send every shard leg, combine."""

        def push_one(shard):
            from elasticdl_tpu.rpc.wire_compression import compress_tensors

            tensors, compressed = compress_tensors(
                reqs[shard], self._wire_dtype
            )
            return self._ps[shard].push_gradient(
                {
                    "model_version": version,
                    "gradients": tensors,
                    "compressed_f32": compressed,
                }
            )

        resps = self._run_sharded(
            [
                (shard, lambda shard=shard: push_one(shard))
                for shard in range(self.num_ps)
            ]
        )
        accepted, out_version = True, None
        for shard in range(self.num_ps):
            resp = resps[shard]
            self._note_shard_reply(shard, resp)
            accepted = accepted and bool(resp["accepted"])
            out_version = (
                resp["version"]
                if out_version is None
                else min(out_version, resp["version"])
            )
            if self._cache is not None:
                # the apply this push triggered advanced the shard's
                # version: noting it here ages our cached copies of the
                # rows it just rewrote
                self._cache.note_version(shard, resp["version"])
            release_message(resp)  # scalar reply: its shm slot recycles
        return accepted, (-1 if out_version is None else out_version)

    def _reap_push(self, entry):
        fut, gen = entry
        try:
            accepted, version = fut.result()
        except Exception as err:  # noqa: BLE001 — re-raise unless abandoned
            if self._gen_snapshot() != gen:
                # epoch-abandonment: this push was in flight across a
                # shard relaunch. Its gradient is part of the bounded
                # rollback the restore already priced in; resending a
                # non-idempotent push could double-apply on shards
                # whose leg DID land, so the whole push is dropped.
                from elasticdl_tpu.common.log_utils import default_logger
                from elasticdl_tpu.utils import profiling

                profiling.events.emit(
                    "ps_push_window_dropped", reason=str(err)[:200]
                )
                default_logger.warning(
                    "in-flight gradient push abandoned across a PS "
                    "shard relaunch (dropped, not resent): %s",
                    err,
                )
                return True, -1
            raise
        if self._gen_snapshot() != gen:
            # the push resolved, but against a mix of incarnations: its
            # combined accepted/version verdict is void — ignore it
            return True, -1
        self._reaped_accepted = self._reaped_accepted and accepted
        if version >= 0:
            self._last_push_version = max(
                self._last_push_version, version
            )
        return accepted, version

    def drain(self):
        """Complete every in-flight async push synchronously.

        Returns ``(accepted, version)`` combined over all pushes reaped
        since the previous drain — ``accepted`` is False if ANY shard
        of any push rejected, ``version`` is the newest version any
        push response reported (-1 when nothing completed). A shard
        failure (e.g. deadline expiry on a dead pod) re-raises here —
        UNLESS the failing push was abandoned by the reconnect protocol
        (submitted before a detected shard relaunch): abandoned pushes
        are dropped silently, never resent, and never wedge the drain
        (docs/ps_recovery.md). Called automatically by ``pull_dense``;
        the worker also calls it at task boundaries, before eval, and
        before checkpoints.
        """
        while self._pending_pushes:
            self._reap_push(self._pending_pushes.popleft())
        accepted = self._reaped_accepted
        self._reaped_accepted = True
        return accepted, self._last_push_version

    @property
    def pending_push_count(self):
        return len(self._pending_pushes)

    # -- embeddings ---------------------------------------------------------

    def pull_embedding_vectors(self, name, ids):
        """Scatter ids to shards by id%N, gather, restore original order.

        With the hot-row cache enabled, cached fresh rows are served
        locally and only the misses cross the wire (a shard whose ids
        all hit is skipped entirely); pulled rows enter the cache tagged
        with the response's model version. The cache is probed once per
        DISTINCT id (duplicates fan out from that single probe via
        numpy mask ops — hit/miss stats count probes), and per-shard
        miss filtering is a mask select, not a per-id Python loop.
        Shard pulls fan out concurrently; responses land in disjoint
        row ranges and merge in ascending shard order."""
        return self.pull_embedding_vectors_multi({name: ids})[name]

    def pull_embedding_vectors_multi(self, ids_by_name):
        """Pull several tables' rows in ONE fan-out round.

        ``{table_name: ids} -> {table_name: rows}``: every
        (table, shard) leg flies concurrently, so a model with T
        embedding layers pays one round trip per batch instead of T
        (the worker's batch prepare pulls all layers through here).
        Semantics per table are exactly :meth:`pull_embedding_vectors`;
        responses merge in sorted (table, shard) order."""
        self._service_reinit()
        state = {}
        calls = []
        for name in ids_by_name:
            ids = np.asarray(ids_by_name[name], dtype=np.int64)
            st = {"ids": ids, "out": None, "positions": {}}
            state[name] = st
            if ids.size == 0:
                st["out"] = np.zeros((0, 0), np.float32)
                continue
            shard_ids = ids % self.num_ps
            hit_mask = np.zeros(ids.shape, dtype=bool)
            if self._cache is not None:
                uniq, inverse = np.unique(ids, return_inverse=True)
                uniq_rows = self._cache.get_rows(name, uniq)
                uniq_hit = np.fromiter(
                    (r is not None for r in uniq_rows),
                    dtype=bool,
                    count=len(uniq_rows),
                )
                hit_mask = uniq_hit[inverse]
                if uniq_hit.any():
                    hit_rows = np.stack(
                        [r for r in uniq_rows if r is not None]
                    ).astype(np.float32, copy=False)
                    out = np.empty(
                        (len(ids), hit_rows.shape[1]), np.float32
                    )
                    # row index into hit_rows for every hitting unique
                    uniq_to_hit = np.cumsum(uniq_hit) - 1
                    out[hit_mask] = hit_rows[
                        uniq_to_hit[inverse[hit_mask]]
                    ]
                    st["out"] = out
            for shard in np.unique(shard_ids[~hit_mask]):
                shard = int(shard)
                positions = np.nonzero(
                    (shard_ids == shard) & ~hit_mask
                )[0]
                st["positions"][shard] = positions
                req = {"name": name, "ids": ids[positions]}
                calls.append(
                    (
                        (name, shard),
                        lambda shard=shard, req=req: self._ps[
                            shard
                        ].pull_embedding_vector(req),
                    )
                )
        resps = self._run_sharded(calls)
        for name, shard in sorted(resps):
            resp = resps[(name, shard)]
            self._note_shard_reply(shard, resp)
            st = state[name]
            positions = st["positions"][shard]
            got = np.asarray(resp["rows"], dtype=np.float32)
            if got.shape[0] != len(positions):
                raise ValueError(
                    "PS shard %d returned %d rows for %d ids of %r"
                    % (shard, got.shape[0], len(positions), name)
                )
            if st["out"] is None:
                st["out"] = np.empty(
                    (len(st["ids"]), got.shape[1]), np.float32
                )
            # the scatter into the caller-owned output (and the cache's
            # own row copies below) IS this path's one decode copy, so
            # the zero-copy view ``got`` never outlives its message
            st["out"][positions] = got
            if self._cache is not None:
                version = resp.get("version")
                self._cache.note_version(shard, version)
                self._cache.put_rows(
                    name, st["ids"][positions], shard, version, got
                )
            release_message(resp)
        return {name: st["out"] for name, st in state.items()}


class PSRpcError(RuntimeError):
    """A PS data-plane RPC failed terminally (deadline expiry, dead
    pod past retries). RuntimeError on purpose: the worker's minibatch
    machinery converts RuntimeError into a failed-task report (the
    task requeues and the worker lives), whereas a raw grpc.RpcError
    would propagate out of the task loop and kill the worker process.
    ``code`` carries the gRPC status for callers that branch on it."""

    def __init__(self, addr, method, cause):
        super().__init__(
            "PS %s %s failed: %s" % (addr, method, cause)
        )
        self.addr = addr
        self.method = method
        self.cause = cause
        code = getattr(cause, "code", None)
        self.code = code() if callable(code) else None


class BoundPS:
    """Adapts an rpc.core Client to the dict-method PS interface.

    ``deadline_s`` bounds every data-plane RPC (rpc/core.Client), so a
    dead PS pod fails the call in ~``deadline_s`` seconds instead of
    hanging a fan-out forever; ``retries``/``backoff_s`` retry
    UNAVAILABLE transients (a restarting pod) — except on
    ``push_gradient``, which is NOT idempotent (an async PS applies on
    receipt; resending after a post-apply connection drop would apply
    the gradient twice). ``None`` keeps the historical blocking
    channel. Terminal transport failures surface as :class:`PSRpcError`
    (a RuntimeError), feeding the worker's minibatch retry loop.

    ``shm`` (docs/wire.md): ``"auto"`` negotiates the co-located
    shared-memory payload path at first call (``transport_hello``) and
    silently keeps the bytes path cross-host or on any attach/setup
    failure; ``"off"`` (default — the conservative choice for direct
    constructions in tests/benches) never negotiates. Slot geometry
    rides ``shm_slots`` x ``shm_slot_mb``.
    """

    def __init__(
        self,
        addr,
        deadline_s=None,
        retries=0,
        backoff_s=0.2,
        shm="off",
        shm_slots=4,
        shm_slot_mb=8,
    ):
        from elasticdl_tpu.rpc.core import Client

        self._addr = addr
        self._client = Client(
            addr,
            deadline_s=deadline_s,
            retries=retries,
            backoff_s=backoff_s,
        )
        self._shm = None
        if shm in ("auto", "on"):
            from elasticdl_tpu.rpc.shm_transport import ShmChannel

            self._shm = ShmChannel(
                self._client, n_slots=shm_slots, slot_mb=shm_slot_mb
            )
        elif shm not in ("off", "", None, False):
            raise ValueError("shm must be 'auto', 'on' or 'off'")

    @property
    def shm_channel(self):
        """The ShmChannel (None when disabled) — state/stats live on it."""
        return self._shm

    def close(self):
        """Release the channel: unlink the shm ring (if negotiated) and
        close the gRPC channel. Safe to call repeatedly."""
        if self._shm is not None:
            self._shm.close()
        self._client.close()

    def __getattr__(self, method):
        def call(req):
            import grpc

            from elasticdl_tpu.utils import profiling

            try:
                if self._shm is not None:
                    # ShmChannel applies the same retry guard
                    # internally (push_gradient never resends)
                    return self._shm.call(method, **req)
                return self._client.call(
                    method,
                    _retriable=(method != "push_gradient"),
                    **req
                )
            except grpc.RpcError as err:
                wrapped = PSRpcError(self._addr, method, err)
                # fleet-visible event: rides the worker's next telemetry
                # snapshot into the master's job log
                profiling.events.emit(
                    "ps_shard_failure",
                    addr=self._addr,
                    method=method,
                    code=getattr(wrapped.code, "name", None),
                )
                raise wrapped from err

        return call
