"""Worker-side sharded-PS client.

Parity: the multi-PS paths inside reference worker/worker.py — variables
partitioned to PS shards by name hash (:279-291), embedding rows by
``id % N`` (:229-252), per-shard gradient pushes (:383-450), and the
pull-merge of dense params. Partition placement uses common/hash_utils so
row/variable placement is stable across restarts and matches the
checkpoint layout.
"""

from collections import OrderedDict

import numpy as np

from elasticdl_tpu.common.hash_utils import (
    scatter_embedding_vector,
    string_to_id,
)
from elasticdl_tpu.common.tensor import Tensor


class HotRowCache:
    """Worker-side LRU of recently pulled embedding rows, with
    version-tagged invalidation.

    Power-law id distributions re-pull the same head rows every batch;
    this cache serves those repeats locally instead of over gRPC. Every
    entry is tagged with the owning PS shard's model version at pull
    time; the client notes the newest version it has SEEN per shard
    (from pull AND push responses — the same version counter
    ps/servicer.py's staleness machinery modulates the LR by), and an
    entry older than ``window`` versions behind that is a miss. The
    served rows are therefore stale by at most ``window`` optimizer
    steps of that shard — the same bounded-staleness contract SSP local
    updates already run under (``get_model_steps``, with the async LR
    discounted by 1/staleness via master/learning_rate_modulator.py) —
    so the cache never adds a staleness mode the training loop doesn't
    already tolerate.
    """

    def __init__(self, max_rows, window=1):
        if max_rows <= 0:
            raise ValueError("max_rows must be positive")
        if window < 0:
            raise ValueError("window must be >= 0")
        self._max_rows = max_rows
        self._window = window
        self._rows = OrderedDict()  # (name, id) -> (shard, version, row)
        self._latest = {}  # shard -> newest version seen in any response
        self.hits = 0
        self.misses = 0

    def note_version(self, shard, version):
        """Record a version observed in shard ``shard``'s response."""
        if version is None or version < 0:
            return
        if version > self._latest.get(shard, -1):
            self._latest[shard] = version

    def get(self, name, row_id):
        """The cached row, or None on miss/stale (stale entries drop)."""
        key = (name, int(row_id))
        entry = self._rows.get(key)
        if entry is None:
            self.misses += 1
            return None
        shard, version, row = entry
        if version < self._latest.get(shard, -1) - self._window:
            del self._rows[key]
            self.misses += 1
            return None
        self._rows.move_to_end(key)
        self.hits += 1
        return row

    def put(self, name, row_id, shard, version, row):
        if version is None:
            return  # unversioned response: nothing safe to tag with
        key = (name, int(row_id))
        # copy: ``row`` is usually a view into the pull's full response
        # array, and storing the view would pin that whole buffer for
        # as long as any one of its rows stays hot
        self._rows[key] = (shard, version, np.array(row, np.float32))
        self._rows.move_to_end(key)
        while len(self._rows) > self._max_rows:
            self._rows.popitem(last=False)

    def __len__(self):
        return len(self._rows)


class PSClient:
    def __init__(
        self,
        ps_stubs,
        wire_dtype="",
        combine_push=True,
        hot_row_cache_rows=0,
        staleness_window=1,
    ):
        """``ps_stubs``: list of objects exposing the Pserver dict-RPC
        methods — rpc.core Clients bound with ``BoundPS`` below, or
        in-process PserverServicer instances (the reference test rung 2
        uses both). ``wire_dtype="bfloat16"`` compresses pushed
        gradients (see rpc/wire_compression.py); pulled params
        decompress by the response's own field.

        Sparse fast path knobs (docs/sparse_fast_path.md):
        ``combine_push`` (default on) segment-sums duplicate sparse rows
        before the wire so each push carries one row per unique id;
        ``hot_row_cache_rows`` > 0 enables a :class:`HotRowCache` of
        that many rows whose entries stay valid for
        ``staleness_window`` PS versions (wire it to the worker's SSP
        window, ``get_model_steps``)."""
        self._ps = ps_stubs
        self._wire_dtype = wire_dtype
        self._combine_push = combine_push
        self._cache = (
            HotRowCache(hot_row_cache_rows, staleness_window)
            if hot_row_cache_rows > 0
            else None
        )

    @property
    def hot_row_cache(self):
        """The HotRowCache (None when disabled) — stats live on it."""
        return self._cache

    @property
    def num_ps(self):
        return len(self._ps)

    def _ps_of_var(self, name):
        return self._ps[string_to_id(name, self.num_ps)]

    # -- model lifecycle ----------------------------------------------------

    def push_model(self, named_params, embedding_infos=None, version=0):
        """Partition dense vars by name hash; infos go to every shard."""
        partitions = [{} for _ in range(self.num_ps)]
        for name, arr in named_params.items():
            partitions[string_to_id(name, self.num_ps)][name] = arr
        infos = [
            {"name": i.name, "dim": i.dim, "initializer": i.initializer}
            for i in embedding_infos or ()
        ]
        for ps, part in zip(self._ps, partitions):
            ps.push_model(
                {
                    "version": version,
                    "params": [Tensor(n, v) for n, v in part.items()],
                    "embedding_infos": infos,
                }
            )

    def push_embedding_info(self, embedding_infos):
        infos = [
            {"name": i.name, "dim": i.dim, "initializer": i.initializer}
            for i in embedding_infos
        ]
        for ps in self._ps:
            ps.push_embedding_info({"embedding_infos": infos})

    def pull_dense(self):
        """Merge every shard's params; returns (all_initialized, version,
        {name: ndarray})."""
        from elasticdl_tpu.rpc.wire_compression import decompress_tensors

        named = {}
        versions = []
        for shard, ps in enumerate(self._ps):
            resp = ps.pull_variable({})
            if not resp.get("model_init_status"):
                return False, -1, {}
            versions.append(resp["version"])
            if self._cache is not None:
                self._cache.note_version(shard, resp["version"])
            for t in decompress_tensors(
                resp.get("params", []), resp.get("compressed_f32")
            ):
                named[t.name] = t.values
        return True, min(versions), named

    # -- gradients ----------------------------------------------------------

    def push_gradient(self, dense_named, sparse_tensors, version):
        """Per-shard push: dense by var hash, sparse rows by id shard.

        Returns (accepted, version) of the last response, matching the
        reference's TODO-choose-last behavior (worker.py:444-450).
        """
        reqs = [[] for _ in range(self.num_ps)]
        for name, arr in (dense_named or {}).items():
            reqs[string_to_id(name, self.num_ps)].append(Tensor(name, arr))
        for t in sparse_tensors or ():
            if self._combine_push:
                # one row per unique id on the wire; the PS applies the
                # sum either way (optimizer_wrapper combines at apply),
                # so this only shrinks the payload
                t = t.combined()
            for shard, (values, ids) in scatter_embedding_vector(
                t.values, t.indices, self.num_ps
            ).items():
                reqs[shard].append(Tensor(t.name, values, indices=ids))
        from elasticdl_tpu.rpc.wire_compression import compress_tensors

        accepted, out_version = True, -1
        for shard, (ps, tensors) in enumerate(zip(self._ps, reqs)):
            tensors, compressed = compress_tensors(
                tensors, self._wire_dtype
            )
            resp = ps.push_gradient(
                {
                    "model_version": version,
                    "gradients": tensors,
                    "compressed_f32": compressed,
                }
            )
            accepted = resp["accepted"]
            out_version = resp["version"]
            if self._cache is not None:
                # the apply this push triggered advanced the shard's
                # version: noting it here ages our cached copies of the
                # rows it just rewrote
                self._cache.note_version(shard, resp["version"])
        return accepted, out_version

    # -- embeddings ---------------------------------------------------------

    def pull_embedding_vectors(self, name, ids):
        """Scatter ids to shards by id%N, gather, restore original order.

        With the hot-row cache enabled, cached fresh rows are served
        locally and only the misses cross the wire (a shard whose ids
        all hit is skipped entirely); pulled rows enter the cache tagged
        with the response's model version."""
        ids = np.asarray(ids, dtype=np.int64)
        if ids.size == 0:
            return np.zeros((0, 0), np.float32)
        shard_ids = ids % self.num_ps
        out = None
        hit_rows = {}  # position -> cached row
        if self._cache is not None:
            for pos in range(len(ids)):
                row = self._cache.get(name, ids[pos])
                if row is not None:
                    hit_rows[pos] = row
        for shard in np.unique(shard_ids):
            positions = np.nonzero(shard_ids == shard)[0]
            positions = [p for p in positions if p not in hit_rows]
            if not positions:
                continue
            resp = self._ps[int(shard)].pull_embedding_vector(
                {"name": name, "ids": ids[positions]}
            )
            got = np.asarray(resp["rows"], dtype=np.float32)
            if got.shape[0] != len(positions):
                raise ValueError(
                    "PS shard %d returned %d rows for %d ids of %r"
                    % (shard, got.shape[0], len(positions), name)
                )
            if out is None:
                out = np.empty((len(ids), got.shape[1]), np.float32)
            out[positions] = got
            if self._cache is not None:
                version = resp.get("version")
                self._cache.note_version(int(shard), version)
                for p, row in zip(positions, got):
                    self._cache.put(
                        name, ids[p], int(shard), version, row
                    )
        if hit_rows:
            if out is None:
                dim = next(iter(hit_rows.values())).shape[0]
                out = np.empty((len(ids), dim), np.float32)
            for pos, row in hit_rows.items():
                out[pos] = row
        return out


class BoundPS:
    """Adapts an rpc.core Client to the dict-method PS interface."""

    def __init__(self, addr):
        from elasticdl_tpu.rpc.core import Client

        self._client = Client(addr)

    def __getattr__(self, method):
        def call(req):
            return self._client.call(method, **req)

        return call
