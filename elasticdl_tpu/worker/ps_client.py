"""Worker-side sharded-PS client.

Parity: the multi-PS paths inside reference worker/worker.py — variables
partitioned to PS shards by name hash (:279-291), embedding rows by
``id % N`` (:229-252), per-shard gradient pushes (:383-450), and the
pull-merge of dense params. Partition placement uses common/hash_utils so
row/variable placement is stable across restarts and matches the
checkpoint layout.
"""

import numpy as np

from elasticdl_tpu.common.hash_utils import (
    scatter_embedding_vector,
    string_to_id,
)
from elasticdl_tpu.common.tensor import Tensor


class PSClient:
    def __init__(self, ps_stubs, wire_dtype=""):
        """``ps_stubs``: list of objects exposing the Pserver dict-RPC
        methods — rpc.core Clients bound with ``BoundPS`` below, or
        in-process PserverServicer instances (the reference test rung 2
        uses both). ``wire_dtype="bfloat16"`` compresses pushed
        gradients (see rpc/wire_compression.py); pulled params
        decompress by the response's own field."""
        self._ps = ps_stubs
        self._wire_dtype = wire_dtype

    @property
    def num_ps(self):
        return len(self._ps)

    def _ps_of_var(self, name):
        return self._ps[string_to_id(name, self.num_ps)]

    # -- model lifecycle ----------------------------------------------------

    def push_model(self, named_params, embedding_infos=None, version=0):
        """Partition dense vars by name hash; infos go to every shard."""
        partitions = [{} for _ in range(self.num_ps)]
        for name, arr in named_params.items():
            partitions[string_to_id(name, self.num_ps)][name] = arr
        infos = [
            {"name": i.name, "dim": i.dim, "initializer": i.initializer}
            for i in embedding_infos or ()
        ]
        for ps, part in zip(self._ps, partitions):
            ps.push_model(
                {
                    "version": version,
                    "params": [Tensor(n, v) for n, v in part.items()],
                    "embedding_infos": infos,
                }
            )

    def push_embedding_info(self, embedding_infos):
        infos = [
            {"name": i.name, "dim": i.dim, "initializer": i.initializer}
            for i in embedding_infos
        ]
        for ps in self._ps:
            ps.push_embedding_info({"embedding_infos": infos})

    def pull_dense(self):
        """Merge every shard's params; returns (all_initialized, version,
        {name: ndarray})."""
        from elasticdl_tpu.rpc.wire_compression import decompress_tensors

        named = {}
        versions = []
        for ps in self._ps:
            resp = ps.pull_variable({})
            if not resp.get("model_init_status"):
                return False, -1, {}
            versions.append(resp["version"])
            for t in decompress_tensors(
                resp.get("params", []), resp.get("compressed_f32")
            ):
                named[t.name] = t.values
        return True, min(versions), named

    # -- gradients ----------------------------------------------------------

    def push_gradient(self, dense_named, sparse_tensors, version):
        """Per-shard push: dense by var hash, sparse rows by id shard.

        Returns (accepted, version) of the last response, matching the
        reference's TODO-choose-last behavior (worker.py:444-450).
        """
        reqs = [[] for _ in range(self.num_ps)]
        for name, arr in (dense_named or {}).items():
            reqs[string_to_id(name, self.num_ps)].append(Tensor(name, arr))
        for t in sparse_tensors or ():
            for shard, (values, ids) in scatter_embedding_vector(
                t.values, t.indices, self.num_ps
            ).items():
                reqs[shard].append(Tensor(t.name, values, indices=ids))
        from elasticdl_tpu.rpc.wire_compression import compress_tensors

        accepted, out_version = True, -1
        for ps, tensors in zip(self._ps, reqs):
            tensors, compressed = compress_tensors(
                tensors, self._wire_dtype
            )
            resp = ps.push_gradient(
                {
                    "model_version": version,
                    "gradients": tensors,
                    "compressed_f32": compressed,
                }
            )
            accepted = resp["accepted"]
            out_version = resp["version"]
        return accepted, out_version

    # -- embeddings ---------------------------------------------------------

    def pull_embedding_vectors(self, name, ids):
        """Scatter ids to shards by id%N, gather, restore original order."""
        ids = np.asarray(ids, dtype=np.int64)
        if ids.size == 0:
            return np.zeros((0, 0), np.float32)
        shard_ids = ids % self.num_ps
        out = None
        for shard in np.unique(shard_ids):
            positions = np.nonzero(shard_ids == shard)[0]
            resp = self._ps[int(shard)].pull_embedding_vector(
                {"name": name, "ids": ids[positions]}
            )
            got = np.asarray(resp["rows"], dtype=np.float32)
            if got.shape[0] != len(positions):
                raise ValueError(
                    "PS shard %d returned %d rows for %d ids of %r"
                    % (shard, got.shape[0], len(positions), name)
                )
            if out is None:
                out = np.empty((len(ids), got.shape[1]), np.float32)
            out[positions] = got
        return out


class BoundPS:
    """Adapts an rpc.core Client to the dict-method PS interface."""

    def __init__(self, addr):
        from elasticdl_tpu.rpc.core import Client

        self._client = Client(addr)

    def __getattr__(self, method):
        def call(req):
            return self._client.call(method, **req)

        return call
