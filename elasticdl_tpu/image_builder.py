"""Job-image builder.

Parity: reference elasticdl/image_builder.py — generate a Dockerfile that
embeds the framework + the user's model zoo (+ optional cluster spec),
build it with the docker SDK and push to the job repository. The docker
SDK is imported lazily; local-mode jobs (api.py) never need it.
"""

import os
import shutil
import tempfile
import uuid

from elasticdl_tpu.common.log_utils import default_logger as logger

_DOCKERFILE_TEMPLATE = """\
FROM {base_image}
WORKDIR /
COPY framework /elasticdl_tpu_pkg
RUN pip install --no-cache-dir /elasticdl_tpu_pkg {extra_index}
COPY model_zoo /model_zoo
{cluster_spec_copy}
ENV PYTHONUNBUFFERED=1
"""


def _generate_dockerfile(base_image, extra_pypi_index="", cluster_spec=""):
    return _DOCKERFILE_TEMPLATE.format(
        base_image=base_image or "python:3.11",
        extra_index=(
            "--extra-index-url " + extra_pypi_index
            if extra_pypi_index
            else ""
        ),
        cluster_spec_copy=(
            "COPY cluster_spec /cluster_spec" if cluster_spec else ""
        ),
    )


def build_and_push_docker_image(
    model_zoo,
    docker_image_repository,
    base_image="",
    extra_pypi="",
    cluster_spec="",
    docker_base_url="unix://var/run/docker.sock",
    docker_tlscert="",
    docker_tlskey="",
):
    """Build + push the job image; returns the pushed image name."""
    import docker

    with tempfile.TemporaryDirectory() as ctx:
        # framework sources
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        shutil.copytree(
            os.path.join(pkg_root, "elasticdl_tpu"),
            os.path.join(ctx, "framework", "elasticdl_tpu"),
        )
        shutil.copy(
            os.path.join(pkg_root, "pyproject.toml"),
            os.path.join(ctx, "framework", "pyproject.toml"),
        )
        shutil.copytree(model_zoo, os.path.join(ctx, "model_zoo"))
        if cluster_spec:
            os.makedirs(os.path.join(ctx, "cluster_spec"))
            shutil.copy(cluster_spec, os.path.join(ctx, "cluster_spec"))
        with open(os.path.join(ctx, "Dockerfile"), "w") as f:
            f.write(
                _generate_dockerfile(base_image, extra_pypi, cluster_spec)
            )

        image_name = "%s:%s" % (
            docker_image_repository.rstrip("/") + "/elasticdl",
            uuid.uuid4().hex[:12],
        )
        if docker_tlscert and docker_tlskey:
            tls_config = docker.tls.TLSConfig(
                client_cert=(docker_tlscert, docker_tlskey)
            )
            client = docker.APIClient(
                base_url=docker_base_url, tls=tls_config
            )
        else:
            client = docker.APIClient(base_url=docker_base_url)
        logger.info("Building image %s", image_name)
        for line in client.build(
            path=ctx, tag=image_name, decode=True, rm=True
        ):
            if "stream" in line:
                logger.info(line["stream"].rstrip())
            if "error" in line:
                raise RuntimeError("docker build failed: %s" % line["error"])
        logger.info("Pushing image %s", image_name)
        for line in client.push(image_name, stream=True, decode=True):
            if "error" in line:
                raise RuntimeError("docker push failed: %s" % line["error"])
        return image_name


def remove_images(docker_image_repository="", all_images=False, **docker_kw):
    """Remove job images (reference image_builder.remove_images)."""
    import docker

    client = docker.APIClient(
        base_url=docker_kw.get(
            "docker_base_url", "unix://var/run/docker.sock"
        )
    )
    prefix = (
        docker_image_repository.rstrip("/") + "/elasticdl"
        if docker_image_repository
        else "elasticdl"
    )
    removed = []
    for image in client.images():
        for tag in image.get("RepoTags") or ():
            if all_images or tag.startswith(prefix):
                client.remove_image(tag, force=True)
                removed.append(tag)
    return removed
