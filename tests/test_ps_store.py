"""PS store + sparse optimizer tests.

Parity: reference tests/embedding_table_test.py, parameters_test.py, and
the correctness core of optimizer_wrapper_test.py (sparse row updates must
match dense training when every row is touched; SGD partial updates match
the closed form).
"""

import numpy as np
import optax
import pytest

from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.ps.embedding_table import (
    EmbeddingTable,
    create_embedding_table,
    get_slot_table_name,
)
from elasticdl_tpu.ps.optimizer_wrapper import OptimizerWrapper
from elasticdl_tpu.ps.parameters import EmbeddingTableInfo, Parameters


def test_embedding_table_lazy_init():
    t = create_embedding_table("emb", 4, "uniform")
    rows = t.get([3, 7, 3])
    assert rows.shape == (3, 4)
    np.testing.assert_array_equal(rows[0], rows[2])  # same id same row
    assert len(t) == 2
    again = t.get([7])
    np.testing.assert_array_equal(again[0], rows[1])  # stable rows


def test_lazy_init_is_order_independent():
    """Fresh rows are a pure function of (id, initializer, seed) — NOT
    of materialization order. Two tables pulling the same ids in
    opposite orders (and interleaved with other ids) mint bitwise-equal
    rows. This pins the id-seeded initializer contract the device
    arena's vectorized fill relies on (docs/ps_device.md); the old
    shared-rng initializer made row values depend on every pull that
    came before."""
    for initializer in ("uniform", "normal"):
        a = create_embedding_table("emb", 4, initializer)
        b = create_embedding_table("emb", 4, initializer)
        a.get([11, 2, 300])
        a.get([5])
        b.get([5, 300])
        b.get([2])
        b.get([11])
        everything = [2, 5, 11, 300]
        np.testing.assert_array_equal(
            a.get(everything), b.get(everything)
        ), initializer


def test_embedding_table_set_and_slot_name():
    t = EmbeddingTable("emb", 2)
    t.set([5], np.array([[1.0, 2.0]], dtype=np.float32))
    np.testing.assert_array_equal(t.get([5])[0], [1.0, 2.0])
    assert get_slot_table_name("emb", "momentum") == "emb-momentum"


def test_parameters_init_once_and_check_grad():
    p = Parameters()
    infos = [EmbeddingTableInfo("emb", 4)]
    assert p.init_from_model(3, {"w": np.ones((2, 3), np.float32)}, infos)
    # second init is a no-op
    assert not p.init_from_model(9, {"w": np.zeros((2, 3))}, [])
    assert p.version == 3
    np.testing.assert_array_equal(p.get_non_embedding_param("w"), 1.0)

    p.check_grad(Tensor("w", np.zeros((2, 3), np.float32)))
    with pytest.raises(ValueError):
        p.check_grad(Tensor("w", np.zeros((2, 4), np.float32)))
    with pytest.raises(ValueError):
        p.check_grad(Tensor("nope", np.zeros((2, 3), np.float32)))
    p.check_grad(
        Tensor("emb", np.zeros((2, 4), np.float32), indices=[0, 1])
    )
    with pytest.raises(ValueError):
        p.check_grad(
            Tensor("emb", np.zeros((2, 5), np.float32), indices=[0, 1])
        )


def test_combine_duplicate_ids():
    ids = [4, 1, 4, 9]
    vals = np.arange(8, dtype=np.float32).reshape(4, 2)
    unique, combined = OptimizerWrapper.combine_duplicate_ids(ids, vals)
    np.testing.assert_array_equal(unique, [1, 4, 9])
    np.testing.assert_array_equal(
        combined, [[2.0, 3.0], [4.0, 6.0], [6.0, 7.0]]
    )


def _store_with_table(vocab, dim, seed=0):
    p = Parameters()
    p.init_from_model(0, {}, [EmbeddingTableInfo("emb", dim)])
    rng = np.random.default_rng(seed)
    init = rng.standard_normal((vocab, dim)).astype(np.float32)
    p.embedding_params["emb"].set(range(vocab), init)
    return p, init


def test_sparse_sgd_matches_closed_form():
    p, init = _store_with_table(10, 3)
    w = OptimizerWrapper(optax.sgd(0.5), p)
    grad = np.ones((2, 3), dtype=np.float32)
    w.apply_sparse_gradients("emb", [2, 6], grad)
    table = p.embedding_params["emb"]
    np.testing.assert_allclose(table.get([2])[0], init[2] - 0.5, rtol=1e-6)
    np.testing.assert_allclose(table.get([6])[0], init[6] - 0.5, rtol=1e-6)
    # untouched rows unchanged
    np.testing.assert_array_equal(table.get([0])[0], init[0])


@pytest.mark.parametrize(
    "make_opt",
    [
        lambda: optax.sgd(0.1),
        lambda: optax.sgd(0.1, momentum=0.9),
        lambda: optax.adam(0.05),
        lambda: optax.adagrad(0.1),
        lambda: optax.rmsprop(0.05),
        lambda: optax.adadelta(0.5),
        lambda: optax.adamax(0.05),
        lambda: optax.nadam(0.05),
    ],
)
def test_sparse_matches_dense_when_all_rows_touched(make_opt):
    """With every row touched every step, sparse row updates must equal a
    dense optax run on the full table — for any optimizer (the wrapper is
    structure-generic, unlike the reference's 8 slot registries)."""
    vocab, dim, steps = 6, 4, 5
    p, init = _store_with_table(vocab, dim, seed=1)
    wrapper = OptimizerWrapper(make_opt(), p)

    dense_opt = make_opt()
    dense_params = init.copy()
    dense_state = dense_opt.init(dense_params)

    rng = np.random.default_rng(2)
    for _ in range(steps):
        grads = rng.standard_normal((vocab, dim)).astype(np.float32)
        wrapper.apply_sparse_gradients("emb", np.arange(vocab), grads)
        updates, dense_state = dense_opt.update(
            grads, dense_state, dense_params
        )
        dense_params = np.asarray(optax.apply_updates(dense_params, updates))

    got = p.embedding_params["emb"].get(np.arange(vocab))
    np.testing.assert_allclose(got, dense_params, rtol=1e-4, atol=1e-5)


def test_dense_gradient_apply():
    p = Parameters()
    p.init_from_model(0, {"w": np.ones((2, 2), np.float32)}, [])
    w = OptimizerWrapper(optax.sgd(1.0), p)
    w.apply_dense_gradients({"w": np.full((2, 2), 0.25, np.float32)})
    np.testing.assert_allclose(p.non_embedding_params["w"], 0.75)


def test_dense_absent_params_use_cached_zero_grads():
    """A param absent from a push still steps (stateful optimizers
    decay its moments) through ONE cached zero gradient — not a fresh
    ``np.zeros_like`` allocation per absent param per apply."""
    p = Parameters()
    p.init_from_model(
        0,
        {
            "w": np.ones((2, 2), np.float32),
            "v": np.full((3,), 2.0, np.float32),
        },
        [],
    )
    w = OptimizerWrapper(optax.adam(0.1), p)
    for step in range(3):
        w.apply_dense_gradients({"w": np.full((2, 2), 0.5, np.float32)})
        # the cache holds exactly the absent param, and re-applies the
        # SAME array object every round
        assert set(w._zero_grads) == {"v"}
        cached = w._zero_grads["v"]
        if step == 0:
            first = cached
        assert cached is first
    # zero gradient => adam moves nothing on the absent param
    np.testing.assert_allclose(p.non_embedding_params["v"], 2.0)
    assert not np.allclose(p.non_embedding_params["w"], 1.0)
