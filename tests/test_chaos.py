"""The PS recovery plane's reconnect protocol + the scripted fault
plane (docs/ps_recovery.md).

In-process and deterministic: servicers stand in for PS pods, a
relaunch is a servicer swap behind a stable stub (exactly the
same-id/same-address contract the instance manager provides), and
chaos scripts replay exact fault interleavings. Pins the four
client-side reconnect obligations — epoch detection, shard-selective
cache invalidation with a re-anchored version clock, in-flight push
window abandonment (dropped, NEVER resent), and model re-push on an
uninitialized relaunch — plus the scripted fault plane's determinism.
"""

import threading

import numpy as np
import optax
import pytest

from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.ps.parameters import EmbeddingTableInfo, Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.ps.snapshot import ShardSnapshotter
from elasticdl_tpu.tools.chaos import (
    ChaosOp,
    ChaosPartitionError,
    FleetChaos,
    ScriptedFaultPS,
    seeded_schedule,
)
from elasticdl_tpu.utils import profiling
from elasticdl_tpu.worker.ps_client import PSClient


def make_servicer(epoch, snapshotter=None, restored=None, use_async=True):
    p = Parameters()
    return PserverServicer(
        p,
        1,
        optax.sgd(0.1),
        use_async=use_async,
        snapshotter=snapshotter,
        shard_epoch=epoch,
        restored_version=restored,
    )


class Swappable:
    """Stable stub fronting a swappable servicer — the same-id relaunch
    seam (workers keep their address; the incarnation behind changes)."""

    def __init__(self, inner):
        self.inner = inner

    def __getattr__(self, method):
        return getattr(self.inner, method)


def push_model(client, n_dense=4, dims=4):
    model = {
        "w%d" % i: np.full((2, 2), float(i + 1), np.float32)
        for i in range(n_dense)
    }
    client.push_model(model, [EmbeddingTableInfo("emb", dims)])


# ---------------------------------------------------------------------------
# the reconnect protocol
# ---------------------------------------------------------------------------


def test_epoch_change_invalidates_only_that_shards_cache(tmp_path):
    snap = ShardSnapshotter(str(tmp_path), every_versions=1)
    s0 = make_servicer(1, snapshotter=snap)
    s1 = make_servicer(21)
    shard0 = Swappable(s0)
    client = PSClient(
        [shard0, s1], hot_row_cache_rows=64, staleness_window=8
    )
    try:
        push_model(client)
        client.pull_embedding_vectors("emb", np.arange(6))
        assert len(client.hot_row_cache) == 6
        client.push_gradient(
            {},
            [
                Tensor(
                    "emb",
                    np.ones((2, 4), np.float32),
                    indices=np.array([0, 2]),
                )
            ],
            0,
        )
        snap.wait()

        # relaunch shard 0 restored from its snapshot, new epoch
        p2 = Parameters()
        snap2 = ShardSnapshotter(str(tmp_path), every_versions=1)
        restored = snap2.restore_into(p2)
        assert restored == 1
        shard0.inner = PserverServicer(
            p2, 1, optax.sgd(0.1), use_async=True,
            shard_epoch=2, restored_version=restored,
        )
        profiling.events.reset()
        ok, version, _ = client.pull_dense()
        assert ok
        # shard 0's (even-id) entries dropped, shard 1's kept
        probe = client.hot_row_cache.get_rows("emb", np.arange(6))
        assert [r is not None for r in probe] == [
            False, True, False, True, False, True,
        ]
        assert client.shard_epochs[0] == 2
        assert client.shard_epochs[1] == 21
        events = [
            e
            for e in profiling.events.tail()
            if e["kind"] == "ps_shard_restore"
        ]
        assert len(events) == 1
        assert events[0]["shard"] == 0
        assert events[0]["old_epoch"] == 1
        assert events[0]["new_epoch"] == 2
        assert events[0]["rollback_depth"] >= 0
        snap2.close()
    finally:
        client.close()
        snap.close()


def test_version_clock_reanchors_after_rollback(tmp_path):
    """The max-only note_version clock would hold the dead
    incarnation's high-water mark and turn every post-restore pull into
    an instant stale miss; invalidate_shard must re-anchor it."""
    snap = ShardSnapshotter(str(tmp_path), every_versions=2)
    s0 = make_servicer(1, snapshotter=snap)
    shard0 = Swappable(s0)
    client = PSClient([shard0], hot_row_cache_rows=64, staleness_window=1)
    try:
        push_model(client)
        # advance past the snapshot cadence; the last push (version 5)
        # is NOT snapshotted, so the relaunch rolls back to v4
        for i in range(5):
            client.push_gradient(
                {},
                [
                    Tensor(
                        "emb",
                        np.ones((1, 4), np.float32),
                        indices=np.array([0]),
                    )
                ],
                i,
            )
        snap.wait()
        p2 = Parameters()
        snap2 = ShardSnapshotter(str(tmp_path), every_versions=2)
        restored = snap2.restore_into(p2)
        assert restored is not None and restored < 5
        shard0.inner = PserverServicer(
            p2, 1, optax.sgd(0.1), use_async=True, shard_epoch=2,
        )
        client.pull_dense()  # detects the epoch change
        rows = client.pull_embedding_vectors("emb", np.arange(4))
        assert rows.shape == (4, 4)
        # rows pulled from the ROLLED-BACK version must be cache hits
        # on the very next probe (no permanent miss storm)
        hits_before = client.hot_row_cache.hits
        client.pull_embedding_vectors("emb", np.arange(4))
        assert client.hot_row_cache.hits >= hits_before + 4
        snap2.close()
    finally:
        client.close()
        snap.close()


def test_epoch_bumped_shard_never_gets_a_resent_push():
    """THE non-idempotency pin (ISSUE 10 satellite): an in-flight push
    that raced a shard relaunch is dropped — the restored incarnation
    must never see it again, and drain() must not re-raise its
    failure."""
    s0 = make_servicer(1)
    shard0 = Swappable(s0)
    client = PSClient([shard0], push_inflight=1)
    release = threading.Event()
    calls = {"push": 0}

    class GatedPS:
        """First push parks until released, then fails — the in-flight
        window racing a dying pod."""

        def __getattr__(self, method):
            inner = getattr(s0, method)
            if method != "push_gradient":
                return inner

            def push(req):
                calls["push"] += 1
                release.wait(timeout=5)
                raise RuntimeError("connection lost mid-push")

            return push

    try:
        push_model(client)
        shard0.inner = GatedPS()
        client.push_gradient(
            {},
            [
                Tensor(
                    "emb",
                    np.ones((1, 4), np.float32),
                    indices=np.array([0]),
                )
            ],
            0,
        )
        # the relaunch happens while that push is still in flight
        p2 = Parameters()
        relaunched = PserverServicer(
            p2, 1, optax.sgd(0.1), use_async=True, shard_epoch=2,
        )
        pushes_seen = []
        orig_push = relaunched.push_gradient
        relaunched.push_gradient = lambda req: pushes_seen.append(req) or (
            orig_push(req)
        )
        shard0.inner = relaunched
        # detection: a status reply from the new incarnation
        client._note_shard_reply(0, relaunched.ps_status({}))
        release.set()
        accepted, version = client.drain()  # must NOT raise
        assert accepted
        # the gated push died once and was never replayed anywhere
        assert calls["push"] == 1
        assert pushes_seen == []
        events = [
            e
            for e in profiling.events.tail()
            if e["kind"] == "ps_push_window_dropped"
        ]
        assert events, "the dropped window must be telemetered"
    finally:
        client.close()


def test_stale_reply_from_dead_incarnation_is_ignored():
    """Epochs are monotonic: a delayed reply from the DEAD incarnation
    (a fan-out leg that resolved after the relaunch was detected) must
    not regress the epoch record or spuriously re-run the reset
    against the live incarnation."""
    s0 = make_servicer(1)
    shard0 = Swappable(s0)
    client = PSClient([shard0], hot_row_cache_rows=16, staleness_window=8)
    try:
        push_model(client, n_dense=1)
        shard0.inner = make_servicer(2)
        client.pull_dense()  # detect the relaunch
        assert client.shard_epochs[0] == 2
        push_model(client, n_dense=1)  # re-init the empty incarnation
        gen = client._gen_snapshot()
        client.pull_embedding_vectors("emb", np.array([0]))
        assert len(client.hot_row_cache) == 1
        # the dead incarnation's delayed reply arrives now
        client._note_shard_reply(0, s0.ps_status({}))
        assert client.shard_epochs[0] == 2  # not regressed
        assert client._gen_snapshot() == gen  # no spurious reset
        assert len(client.hot_row_cache) == 1  # cache untouched
    finally:
        client.close()


def test_reinit_flag_survives_a_failed_repush():
    """A transient failure of the re-push callback must re-arm the
    reinit flag — losing it would wedge every later pull against the
    still-empty shard."""
    s0 = make_servicer(1)
    shard0 = Swappable(s0)
    client = PSClient([shard0])
    attempts = []

    def flaky_reset(shards):
        attempts.append(tuple(shards))
        if len(attempts) == 1:
            raise RuntimeError("shard still flapping")

    client.set_on_shard_reset(flaky_reset)
    try:
        push_model(client, n_dense=1)
        shard0.inner = make_servicer(2)  # empty relaunch
        client.pull_dense()  # detects; marks needs_reinit
        with pytest.raises(RuntimeError):
            client.pull_dense()  # first service attempt fails
        client.pull_dense()  # re-armed: runs again and succeeds
        assert attempts == [(0,), (0,)]
    finally:
        client.close()


def test_uninitialized_relaunch_triggers_model_repush():
    """Relaunch with NO snapshot: the shard reports uninitialized and
    the client's next data-plane call re-pushes the model + infos via
    the on_shard_reset callback (first-write-wins on live shards)."""
    s0 = make_servicer(1)
    s1 = make_servicer(11)
    shard0 = Swappable(s0)
    client = PSClient([shard0, s1])
    resets = []
    client.set_on_shard_reset(lambda shards: resets.append(tuple(shards)))
    try:
        push_model(client)
        shard0.inner = make_servicer(2)  # empty relaunch
        ok, _, _ = client.pull_dense()
        assert not ok  # uninitialized surfaces, never wedges
        assert resets == []  # marked during the pull; served on the NEXT call
        ok, _, _ = client.pull_dense()
        assert resets == [(0,)]
    finally:
        client.close()


def test_dead_shard_probe_detects_relaunch_before_retry():
    """A data-plane failure probes ps_status: when the shard is already
    back as a new incarnation, the reset runs BEFORE the worker's retry
    re-pulls — the retry sees an invalidated cache, not stale rows."""
    s0 = make_servicer(1)
    shard0 = Swappable(s0)
    client = PSClient([shard0], hot_row_cache_rows=16, staleness_window=8)

    class DeadOnData:
        """Data RPCs fail (pod died mid-relaunch); ps_status answers
        from the NEW incarnation (it came back between the failure and
        the probe)."""

        def __init__(self, new_servicer):
            self._new = new_servicer

        def __getattr__(self, method):
            if method == "ps_status":
                return self._new.ps_status

            def dead(req):
                raise RuntimeError("UNAVAILABLE: shard relaunching")

            return dead

    try:
        push_model(client, n_dense=1)
        client.pull_embedding_vectors("emb", np.array([0, 2]))
        assert len(client.hot_row_cache) == 2
        new_inc = make_servicer(2)
        shard0.inner = DeadOnData(new_inc)
        # uncached ids force a wire pull, which hits the dead data path
        with pytest.raises(RuntimeError):
            client.pull_embedding_vectors("emb", np.array([4, 6]))
        # the probe already ran the reset: epoch recorded, cache empty
        assert client.shard_epochs[0] == 2
        assert len(client.hot_row_cache) == 0
    finally:
        client.close()


# ---------------------------------------------------------------------------
# the scripted fault plane
# ---------------------------------------------------------------------------


def test_scripted_fault_ps_partition_window_is_deterministic():
    s = make_servicer(1)
    faulty = ScriptedFaultPS(
        s, [ChaosOp("partition", 0, at_call=2, n_calls=2)], shard=0
    )
    client = PSClient([faulty], fanout=False)
    push_model(client, n_dense=1)  # calls 0 (push_model)
    client.pull_dense()  # call 1
    for _ in range(2):  # calls 2, 3: the window
        with pytest.raises(ChaosPartitionError):
            client.pull_dense()
    ok, _, _ = client.pull_dense()  # call 4: window closed
    assert ok
    assert [op.kind for op, _ in faulty.executed] == [
        "partition",
        "partition",
    ]
    client.close()


def test_scripted_fault_ps_kill_at_version_latches_until_revive():
    s = make_servicer(1)
    faulty = ScriptedFaultPS(
        s, [ChaosOp("kill", 0, at_version=2)], shard=0
    )
    client = PSClient([faulty], fanout=False)
    push_model(client, n_dense=1)
    grad = [
        Tensor("emb", np.ones((1, 4), np.float32), indices=np.array([0]))
    ]
    client.push_gradient({}, grad, 0)  # version 1
    client.push_gradient({}, grad, 1)  # version 2
    with pytest.raises(ChaosPartitionError):
        client.push_gradient({}, grad, 2)  # at_version crossed: dead
    with pytest.raises(ChaosPartitionError):
        client.pull_dense()  # stays dead (latched)
    # the relaunch: restored incarnation behind the same stub. Its
    # version may still be >= at_version (a cadence snapshot can
    # publish exactly at the kill point) — the one-shot op must NOT
    # re-fire, or revive() could never succeed
    restored = make_servicer(2)
    restored._parameters.version = 5
    faulty.revive(restored)
    st = faulty.ps_status({})
    assert st["shard_epoch"] == 2
    client.pull_dense()  # counted call: would re-kill without the latch
    client.close()


def test_scripted_fault_ps_reject_window():
    s = make_servicer(1)
    faulty = ScriptedFaultPS(
        s, [ChaosOp("reject", 0, at_call=1, n_calls=1)], shard=0
    )
    client = PSClient([faulty], fanout=False)
    push_model(client, n_dense=1)
    grad = [
        Tensor("emb", np.ones((1, 4), np.float32), indices=np.array([0]))
    ]
    accepted, _ = client.push_gradient({}, grad, 0)
    assert not accepted  # forced rejection, still applied/forwarded
    accepted, _ = client.push_gradient({}, grad, 1)
    assert accepted
    client.close()


def test_seeded_schedule_is_reproducible():
    a = seeded_schedule(42, num_ps=4, max_version=9, n_ops=3)
    b = seeded_schedule(42, num_ps=4, max_version=9, n_ops=3)
    assert [(o.kind, o.shard, o.at_version) for o in a] == [
        (o.kind, o.shard, o.at_version) for o in b
    ]
    c = seeded_schedule(43, num_ps=4, max_version=9, n_ops=3)
    assert [(o.shard, o.at_version) for o in a] != [
        (o.shard, o.at_version) for o in c
    ] or [o.kind for o in a] != [o.kind for o in c]


def test_fleet_chaos_fires_once_at_version_crossing():
    killed = []

    class Manager:
        def kill_ps(self, shard):
            killed.append(("kill", shard))

        def terminate_ps(self, shard):
            killed.append(("term", shard))

    versions = {0: 0, 1: 0}

    def status_fn(shard):
        return {"version": versions[shard]}

    chaos = FleetChaos(
        Manager(),
        status_fn,
        [ChaosOp("kill", 0, at_version=3)],
        poll_s=0.01,
    ).start()
    try:
        import time

        deadline = time.monotonic() + 5
        versions[0] = 2
        time.sleep(0.05)
        assert killed == []  # below the trigger
        versions[0] = 3
        while not chaos.done() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert chaos.done()
        time.sleep(0.05)  # no double fire on later polls
        assert killed == [("kill", 0)]
    finally:
        chaos.stop()


def test_fleet_chaos_master_op_fires_at_done_count():
    """Scripted master outages (docs/master_recovery.md): a
    kill_master op triggers on the master journal's cumulative
    done-task count, polled through master_status — and fires once."""
    import time

    executed = []

    class Manager:
        def kill_master(self):
            executed.append("kill_master")

        def terminate_master(self):
            executed.append("term_master")

    status = {"version": 0, "journal": {"done": 0}}
    chaos = FleetChaos(
        Manager(),
        lambda shard: {},
        [ChaosOp("kill_master", -1, at_done=3)],
        poll_s=0.01,
        master_status_fn=lambda: status,
    ).start()
    try:
        time.sleep(0.05)
        assert executed == []
        status["journal"]["done"] = 3
        deadline = time.monotonic() + 5
        while not chaos.done() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert chaos.done()
        time.sleep(0.05)
        assert executed == ["kill_master"]
    finally:
        chaos.stop()


def test_local_instance_manager_supervises_master(tmp_path):
    """The external-supervisor form: SIGTERM's rc-75 drain relaunches
    the master WITHOUT spending the crash budget (PS-plane parity);
    SIGKILL relaunches on the budget."""
    import sys
    import time

    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )

    ready = tmp_path / "master-ready"

    def master_cmd():
        return [
            sys.executable,
            "-c",
            "import signal, sys, time\n"
            "signal.signal(signal.SIGTERM, lambda *a: sys.exit(75))\n"
            # readiness marker AFTER the handler is installed: the
            # drain test must not SIGTERM a still-booting interpreter
            "open(%r, 'w').close()\n"
            "while True:\n"
            "    time.sleep(0.1)\n" % str(ready),
        ]

    def wait_ready(deadline_s=15):
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if ready.exists():
                return True
            time.sleep(0.05)
        return False

    class _NoTasks:
        def recover_tasks(self, worker_id):
            pass

    lim = LocalInstanceManager(
        _NoTasks(),
        num_workers=0,
        worker_command=lambda wid: [],
        master_command=master_cmd,
        max_relaunches=2,
        log_dir=str(tmp_path),
    )
    try:
        lim.start_master()
        assert wait_ready(), "supervised master never came up"
        assert lim.live_master()

        # graceful drain: exit 75, relaunched, budget untouched
        ready.unlink()
        lim.terminate_master()
        assert wait_ready(), "rc-75 drain must relaunch the master"
        assert lim.live_master()
        assert lim.exit_codes[("master", 0)] == 75
        assert lim._relaunches == 0, "rc-75 must not spend the budget"

        # hard kill: relaunched on the crash budget
        ready.unlink()
        lim.kill_master()
        assert wait_ready(), "SIGKILL must relaunch the master"
        assert lim.live_master()
        deadline = time.monotonic() + 5
        while lim._relaunches == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert lim._relaunches == 1
    finally:
        lim.stop_relaunch_and_remove_all_pods()
