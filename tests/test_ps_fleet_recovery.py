"""Live-fleet PS crash recovery: SIGKILL a real PS process, relaunch
it same-id/same-port, and observe what workers see (docs/ps_recovery.md).

Two regimes over real loopback gRPC, both driven through the worker's
own data-plane client (PSClient + BoundPS):

- **No durability flags** (the seed behavior, kept as the documented
  no-snapshot contract): the relaunched shard boots EMPTY — it reports
  uninitialized and a worker's re-push re-initializes dense params
  while trained embedding rows are silently gone. This is the hazard
  ISSUE 10 pinned before the recovery plane landed.
- **With ``--ps_snapshot_versions``/``--ps_snapshot_dir``**: the
  relaunched shard restores the newest snapshot BEFORE serving, mints
  a fresh shard_epoch, and the client's reconnect protocol fires —
  epoch change detected, that shard's hot-row cache entries
  invalidated, ``ps_shard_restore`` telemetry with a bounded rollback.
"""

import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.ps.parameters import EmbeddingTableInfo
from elasticdl_tpu.utils import profiling
from elasticdl_tpu.worker.ps_client import BoundPS, PSClient
from tests.fake_ps import free_port
from tests.test_utils import MODEL_ZOO_PATH

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL_DEF = "mnist_subclass.mnist_subclass.CustomModel"


def _ps_cmd(ps_id, port, extra=()):
    return [
        sys.executable,
        "-m",
        "elasticdl_tpu.ps.main",
        "--ps_id", str(ps_id),
        "--port", str(port),
        "--model_zoo", MODEL_ZOO_PATH,
        "--model_def", MODEL_DEF,
        "--use_async", "true",
        "--grads_to_wait", "1",
    ] + list(extra)


def _spawn_ps(ps_id, port, extra=(), log_dir=None):
    env = dict(os.environ)
    env.update({"PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"})
    out = subprocess.DEVNULL
    if log_dir:
        out = open(os.path.join(log_dir, "ps-%d.log" % ps_id), "ab")
    proc = subprocess.Popen(
        _ps_cmd(ps_id, port, extra), env=env, stdout=out, stderr=out
    )
    if log_dir:
        out.close()
    return proc


def _wait_port(proc, port, timeout=90):
    deadline = time.time() + timeout
    while True:
        assert proc.poll() is None, (
            "PS exited rc=%d at boot" % proc.returncode
        )
        try:
            with socket.create_connection(("localhost", port), 1.0):
                return
        except OSError:
            assert time.time() < deadline, "PS did not come up"
            time.sleep(0.2)


def _stop(procs):
    for proc in procs:
        if proc.poll() is None:
            proc.kill()
    for proc in procs:
        try:
            proc.wait(timeout=10)
        except Exception:
            pass


def _client(ports, **kw):
    return PSClient(
        [
            BoundPS(
                "localhost:%d" % p,
                deadline_s=5.0,
                retries=2,
                backoff_s=0.2,
            )
            for p in ports
        ],
        **kw
    )


def _train_fleet(client, n_pushes=4):
    """Init the fleet and push a few sparse+dense gradients; returns
    (dense snapshot, trained embedding rows, per-shard versions)."""
    client.push_model(
        {
            "w_a": np.full((3, 3), 1.5, np.float32),
            "w_b": np.full((2, 4), -0.5, np.float32),
        },
        [EmbeddingTableInfo("emb", 4)],
    )
    ids = np.arange(8, dtype=np.int64)
    client.pull_embedding_vectors("emb", ids)  # materialize rows
    for i in range(n_pushes):
        client.push_gradient(
            {"w_a": np.full((3, 3), 0.125, np.float32)},
            [
                Tensor(
                    "emb",
                    np.ones((8, 4), np.float32),
                    indices=ids,
                )
            ],
            i,
        )
    client.drain()
    ok, version, dense = client.pull_dense()
    assert ok and version >= 1
    rows = client.pull_embedding_vectors("emb", ids)
    return dense, rows, version


def test_sigkill_without_durability_resets_shard_state(tmp_path):
    """The pre-recovery-plane hazard, pinned as the documented
    no-durability behavior: a SIGKILLed+relaunched shard reports
    UNINITIALIZED and its trained state is gone."""
    ports = [free_port(), free_port()]
    procs = [_spawn_ps(i, p, log_dir=str(tmp_path)) for i, p in enumerate(ports)]
    try:
        for proc, port in zip(procs, ports):
            _wait_port(proc, port)
        client = _client(ports)
        try:
            _train_fleet(client)
        finally:
            client.close()

        procs[0].send_signal(signal.SIGKILL)
        procs[0].wait(timeout=10)
        procs[0] = _spawn_ps(0, ports[0], log_dir=str(tmp_path))
        _wait_port(procs[0], ports[0])

        probe = _client([ports[0]])
        try:
            status = probe._ps[0].ps_status({})
            assert status["initialized"] is False
            assert status["restored_version"] == -1
            resp = probe._ps[0].pull_variable({})
            # the shard lost everything: it answers exactly like a
            # freshly booted, never-pushed instance
            assert resp["model_init_status"] is False
            assert resp["version"] == -1
        finally:
            probe.close()
    finally:
        _stop(procs)


def test_sigkill_with_durability_restores_and_reconnects(tmp_path):
    """The recovery plane end to end on a live 2-shard fleet: the
    relaunched shard restores its snapshot before serving, and the
    worker-side client detects the new incarnation — cache entries for
    that shard invalidated, ps_shard_restore emitted with a rollback
    bounded by the cadence."""
    snap_dir = str(tmp_path / "snaps")
    tport = free_port()
    extra = [
        "--ps_snapshot_versions", "1",
        "--ps_snapshot_dir", snap_dir,
    ]
    # only shard 0 serves the (per-pod) telemetry endpoint in this
    # test; a shared port would fail the second shard's bind
    extras = [
        extra + ["--ps_telemetry_port", str(tport)],
        extra,
    ]
    ports = [free_port(), free_port()]
    procs = [
        _spawn_ps(i, p, extra=extras[i], log_dir=str(tmp_path))
        for i, p in enumerate(ports)
    ]
    try:
        for proc, port in zip(procs, ports):
            _wait_port(proc, port)
        client = _client(
            ports, hot_row_cache_rows=64, staleness_window=8
        )
        try:
            dense, rows, version = _train_fleet(client)
            cached_before = len(client.hot_row_cache)
            assert cached_before == 8
            epoch_before = client.shard_epochs[0]

            # the shard serves its own /metrics plane: the snapshot-age
            # gauge is scrapeable per pod (docs/ps_recovery.md)
            import urllib.request

            body = urllib.request.urlopen(
                "http://localhost:%d/metrics" % tport, timeout=5
            ).read().decode("utf-8")
            assert "edl_ps_snapshot_age_seconds" in body

            procs[0].send_signal(signal.SIGKILL)
            procs[0].wait(timeout=10)
            procs[0] = _spawn_ps(
                0, ports[0], extra=extra, log_dir=str(tmp_path)
            )
            _wait_port(procs[0], ports[0])

            # the restore contract: the relaunched shard serves exactly
            # its newest PUBLISHED snapshot (a SIGKILL may have caught
            # the last async capture still queued — that version is the
            # bounded rollback, not a failure), while the surviving
            # shard's partition is untouched
            import glob

            from elasticdl_tpu.common.hash_utils import string_to_id
            from elasticdl_tpu.ps.snapshot import read_shard_snapshot

            snaps = sorted(
                glob.glob(
                    os.path.join(snap_dir, "ps-0", "snap_v*")
                ),
                key=lambda d: int(
                    os.path.basename(d)[len("snap_v"):]
                ),
            )
            assert snaps, "cadence snapshots must have published"
            snap_state = read_shard_snapshot(snaps[-1])
            assert version - snap_state["version"] <= 2

            profiling.events.reset()
            ok, got_version, dense_after = client.pull_dense()
            assert ok, "restored shard must serve without a re-push"
            # SSP sees the bounded rollback, not a wedge: the merged
            # version is the min over shards, <= the pre-kill version
            assert 0 <= got_version <= version
            for name, arr in dense_after.items():
                expect = (
                    snap_state["dense"][name]
                    if string_to_id(name, 2) == 0
                    else dense[name]
                )
                np.testing.assert_allclose(
                    arr, expect, rtol=0, atol=1e-6
                )
            rows_after = client.pull_embedding_vectors(
                "emb", np.arange(8, dtype=np.int64)
            )
            snap_rows = dict(
                zip(
                    snap_state["tables"]["emb"]["ids"].tolist(),
                    snap_state["tables"]["emb"]["rows"],
                )
            )
            for i in range(8):
                expect = snap_rows[i] if i % 2 == 0 else rows[i]
                np.testing.assert_allclose(
                    rows_after[i], expect, rtol=0, atol=1e-6
                )

            # reconnect protocol observables
            assert client.shard_epochs[0] == epoch_before + 1
            restore_events = [
                e
                for e in profiling.events.tail()
                if e["kind"] == "ps_shard_restore"
            ]
            assert len(restore_events) == 1
            ev = restore_events[0]
            assert ev["shard"] == 0
            # at most the in-flight captures can roll back (cadence 1,
            # async writer queue depth 2)
            assert 0 <= ev["rollback_depth"] <= 2
            assert ev["cache_rows_invalidated"] >= 1

            status = client._ps[0].ps_status({})
            assert status["initialized"] is True
            assert status["restored_version"] >= 1
        finally:
            client.close()
    finally:
        _stop(procs)


@pytest.mark.slow
def test_sigterm_drains_final_snapshot_and_exits_75(tmp_path):
    """Graceful preemption: SIGTERM makes the shard drain ONE final
    snapshot (even past the cadence) and exit 75 — the code the
    instance manager relaunches without spending the crash budget."""
    snap_dir = str(tmp_path / "snaps")
    # cadence 1000: no cadence snapshot will ever fire — whatever the
    # relaunch restores can only have come from the SIGTERM drain
    extra = [
        "--ps_snapshot_versions", "1000",
        "--ps_snapshot_dir", snap_dir,
    ]
    port = free_port()
    proc = _spawn_ps(0, port, extra=extra, log_dir=str(tmp_path))
    try:
        _wait_port(proc, port)
        client = _client([port])
        try:
            client.push_model(
                {"w": np.full((2, 2), 3.0, np.float32)},
                [EmbeddingTableInfo("emb", 4)],
            )
            client.push_gradient(
                {"w": np.ones((2, 2), np.float32)},
                [],
                0,
            )
        finally:
            client.close()

        proc.terminate()  # SIGTERM: drain + exit 75
        assert proc.wait(timeout=30) == 75

        proc = _spawn_ps(0, port, extra=extra, log_dir=str(tmp_path))
        _wait_port(proc, port)
        probe = _client([port])
        try:
            status = probe._ps[0].ps_status({})
            assert status["initialized"] is True
            assert status["restored_version"] == 1
            ok, version, dense = probe.pull_dense()
            assert ok and version == 1
            # the drained state carries the applied gradient, not init
            assert not np.allclose(
                dense["w"], np.full((2, 2), 3.0, np.float32)
            )
        finally:
            probe.close()
    finally:
        _stop([proc])