"""Async checkpointing: background IO, ordering, donation safety.

The reference blocks training for every checkpoint write (reference
master/checkpoint_service.py:47-72). The TPU rebuild splits a save into
a device->host snapshot (must precede the next donating step) and disk
IO (backgrounded); these tests pin the ordering, error-relay, and
donation-safety contracts.
"""

import glob
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.common.async_checkpoint import AsyncCheckpointer
from elasticdl_tpu.common.sharded_checkpoint import (
    ShardedCheckpointManager,
    load_sharded_to_host,
)
from elasticdl_tpu.parallel.mesh import create_mesh


class TestAsyncCheckpointer:
    def test_jobs_run_in_submission_order(self):
        ckpt = AsyncCheckpointer()
        seen = []
        gate = threading.Event()

        def first():
            gate.wait(5)
            seen.append(1)

        ckpt.submit(first)
        ckpt.submit(lambda: seen.append(2))
        ckpt.submit(lambda: seen.append(3))
        gate.set()
        ckpt.wait()
        assert seen == [1, 2, 3]
        ckpt.close()

    def test_worker_error_reraised_on_next_submit_then_cleared(self):
        ckpt = AsyncCheckpointer()

        def boom():
            raise IOError("disk gone")

        ckpt.submit(boom)
        ckpt._queue.join()
        with pytest.raises(IOError, match="disk gone"):
            ckpt.submit(lambda: None)
        # the error was consumed; the queue still works
        done = []
        ckpt.submit(lambda: done.append(True))
        ckpt.wait()
        assert done == [True]
        ckpt.close()

    def test_wait_reraises_and_close_rejects_submit(self):
        ckpt = AsyncCheckpointer()

        def boom():
            raise ValueError("bad write")

        ckpt.submit(boom)
        with pytest.raises(ValueError, match="bad write"):
            ckpt.wait()
        ckpt.close()
        with pytest.raises(RuntimeError):
            ckpt.submit(lambda: None)

    def test_max_pending_bounds_queue(self):
        ckpt = AsyncCheckpointer(max_pending=1)
        release = threading.Event()
        started = threading.Event()

        def slow():
            started.set()
            release.wait(5)

        ckpt.submit(slow)
        started.wait(5)
        ckpt.submit(lambda: None)  # fills the single queue slot
        t0 = time.monotonic()
        blocker = threading.Thread(
            target=lambda: ckpt.submit(lambda: None)
        )
        blocker.start()
        blocker.join(0.2)
        assert blocker.is_alive(), "third submit should block on the bound"
        release.set()
        blocker.join(5)
        assert not blocker.is_alive()
        ckpt.wait()
        ckpt.close()
        assert time.monotonic() - t0 < 10


def _sharded_state(mesh, value, v=32, d=4):
    table = jax.device_put(
        np.full((v, d), value, dtype=np.float32),
        NamedSharding(mesh, P("data", None)),
    )
    dense = jax.device_put(
        np.full((6, 2), value, dtype=np.float32),
        NamedSharding(mesh, P()),
    )
    return {"table": table, "w": dense}


class TestAsyncShardedManager:
    def test_async_save_restores_identically_and_ring_evicts(self, tmp_path):
        mesh = create_mesh({"data": 8}, axis_names=("data",))
        mgr = ShardedCheckpointManager(
            str(tmp_path), checkpoint_steps=1, keep_max=2, async_io=True
        )
        for version in (1, 2, 3):
            mgr.save(_sharded_state(mesh, float(version)), version)
        mgr.wait()
        assert mgr.versions() == [2, 3]
        got_version, host = load_sharded_to_host(mgr.latest_dir())
        assert got_version == 3
        np.testing.assert_array_equal(host["table"], np.full((32, 4), 3.0))
        np.testing.assert_array_equal(host["w"], np.full((6, 2), 3.0))
        mgr.close()

    def test_save_is_snapshot_consistent_under_donation(self, tmp_path):
        """save(version) must capture the state AS OF the call even when
        the very next step donates (invalidates) those buffers."""
        mesh = create_mesh({"data": 8}, axis_names=("data",))
        state = _sharded_state(mesh, 7.0)

        donating = jax.jit(
            lambda tree: jax.tree_util.tree_map(lambda a: a + 1.0, tree),
            donate_argnums=(0,),
        )

        mgr = ShardedCheckpointManager(
            str(tmp_path), checkpoint_steps=1, async_io=True
        )
        mgr.save(state, 1)
        state = donating(state)  # invalidates the buffers save() saw
        _ = float(np.asarray(state["w"])[0, 0])
        mgr.wait()
        _, host = load_sharded_to_host(mgr.latest_dir())
        np.testing.assert_array_equal(host["table"], np.full((32, 4), 7.0))
        np.testing.assert_array_equal(host["w"], np.full((6, 2), 7.0))
        mgr.close()

    def test_io_error_surfaces_on_training_thread(self, tmp_path):
        mesh = create_mesh({"data": 8}, axis_names=("data",))
        mgr = ShardedCheckpointManager(
            str(tmp_path), checkpoint_steps=1, async_io=True
        )
        mgr.save(_sharded_state(mesh, 1.0), 1)
        mgr.wait()
        # occupy the next version's directory path with a plain file so
        # the background write fails (chmod tricks don't bind: tests run
        # as root)
        with open(os.path.join(str(tmp_path), "ckpt_v2"), "w") as f:
            f.write("in the way")
        mgr.save(_sharded_state(mesh, 2.0), 2)
        with pytest.raises(Exception):
            mgr.wait()
        mgr.close()

    def test_sync_mode_unchanged(self, tmp_path):
        mesh = create_mesh({"data": 8}, axis_names=("data",))
        mgr = ShardedCheckpointManager(str(tmp_path), checkpoint_steps=1)
        mgr.save(_sharded_state(mesh, 5.0), 1)
        _, host = load_sharded_to_host(mgr.latest_dir())
        np.testing.assert_array_equal(host["w"], np.full((6, 2), 5.0))
        mgr.wait()  # no-op
        mgr.close()  # no-op
