"""End-to-end sparse-path correctness (reference
optimizer_wrapper_test.py:576-812 pattern): a full elastic-embedding
training job through the master store must produce the same weights as
plain dense training on the identical batch stream — sync AND async —
and the sparse path must survive a PS kill mid-job.
"""

import numpy as np
import optax
import pytest

import jax

from elasticdl_tpu.common.constants import JobType
from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.data.recordio import RecordIOWriter
from elasticdl_tpu.master.checkpoint_service import CheckpointService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.nn.model_api import init_variables, split_variables
from elasticdl_tpu.ps.parameters import EmbeddingTableInfo
from elasticdl_tpu.training.step import TrainState, make_train_step
from elasticdl_tpu.worker.worker import Worker
from model_zoo.deepfm_edl_embedding import deepfm_edl_embedding as zoo
from tests.in_process_master import InProcessMaster
from tests.test_utils import MODEL_ZOO_PATH

VOCAB = 60
DIM = 8
LR = 0.1
BATCH = 16
RECORDS = 64
EPOCHS = 2


@pytest.fixture
def fixed_data(tmp_path):
    """Deterministic frappe-style records; ids < VOCAB."""
    rng = np.random.default_rng(5)
    path = str(tmp_path / "sparse.edlr")
    records = []
    with RecordIOWriter(path) as w:
        for _ in range(RECORDS):
            ids = rng.integers(0, VOCAB, size=(10,)).astype(np.int64)
            label = np.array([rng.integers(0, 2)], np.int64)
            records.append((ids, label))
            w.write(encode_example({"feature": ids, "label": label}))
    return path, records


@pytest.fixture
def no_shuffle(monkeypatch):
    """Deterministic batch order: identical for both trainings."""
    from elasticdl_tpu.data.dataset import Dataset

    monkeypatch.setattr(Dataset, "shuffle", lambda self, *a, **k: self)


def _run_elastic_job(data_file, use_async):
    """Train deepfm through the elastic-embedding master store; returns
    (initial_rows, final_rows, initial_dense, final_dense)."""
    task_d = TaskDispatcher(
        {data_file: (0, RECORDS)}, {}, {}, RECORDS, EPOCHS
    )
    master = MasterServicer(
        1,
        BATCH,
        optax.sgd(LR),
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=use_async,
    )
    # pre-init every row so the initial tables are observable (lazy init
    # would otherwise interleave with training)
    master.push_embedding_info(
        [
            EmbeddingTableInfo("embedding", DIM, "uniform"),
            EmbeddingTableInfo("id_bias", 1, "uniform"),
        ]
    )
    all_ids = np.arange(VOCAB)
    init_rows = {
        "embedding": master.pull_embedding_vectors(
            "embedding", all_ids
        ).copy(),
        "id_bias": master.pull_embedding_vectors("id_bias", all_ids).copy(),
    }
    worker = Worker(
        worker_id=1,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=BATCH,
        model_zoo=MODEL_ZOO_PATH,
        model_def=(
            "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
        ),
        model_params="embedding_dim=%d,fc_unit=8" % DIM,
        stub=None,
    )
    worker._stub = InProcessMaster(master)
    worker.run()
    assert task_d.finished()
    final_rows = {
        "embedding": master.pull_embedding_vectors("embedding", all_ids),
        "id_bias": master.pull_embedding_vectors("id_bias", all_ids),
    }
    _, final_dense = master.get_model(master.get_model_version())
    return init_rows, final_rows, final_dense


def _run_dense_twin(records, init_rows):
    """Plain dense training (jnp.take tables) on the identical batches."""
    model = zoo.DeepFMEdl(
        embedding_dim=DIM, fc_unit=8, vocab_size=VOCAB, force_hbm=True
    )
    first = {"feature": np.stack([r[0] for r in records[:1]])}
    variables = init_variables(model, jax.random.PRNGKey(0), first)
    params, state = split_variables(variables)
    params["embedding"]["table"] = init_rows["embedding"].astype(
        np.float32
    )
    params["id_bias"]["table"] = init_rows["id_bias"].astype(np.float32)
    opt = optax.sgd(LR)
    ts = TrainState.create(params, state, opt)
    step = make_train_step(model, zoo.loss, opt)
    key = jax.random.PRNGKey(9)
    for _ in range(EPOCHS):
        for i in range(0, RECORDS, BATCH):
            chunk = records[i : i + BATCH]
            feats = {"feature": np.stack([r[0] for r in chunk])}
            labels = np.stack([r[1] for r in chunk])
            ts, _ = step(ts, feats, labels, key)
    return jax.tree_util.tree_map(np.asarray, ts.params)


@pytest.mark.parametrize("use_async", [False, True])
def test_elastic_embedding_training_matches_dense(
    fixed_data, no_shuffle, use_async
):
    data_file, records = fixed_data
    init_rows, final_rows, final_dense = _run_elastic_job(
        data_file, use_async
    )
    twin = _run_dense_twin(records, init_rows)

    np.testing.assert_allclose(
        final_rows["embedding"],
        twin["embedding"]["table"],
        rtol=2e-4,
        atol=2e-5,
    )
    np.testing.assert_allclose(
        final_rows["id_bias"],
        twin["id_bias"]["table"],
        rtol=2e-4,
        atol=2e-5,
    )
    # dense (non-embedding) weights agree as well
    twin_flat = {
        "/".join(p): v
        for p, v in (
            (
                [str(getattr(k, "key", getattr(k, "name", "?"))) for k in kp],
                np.asarray(leaf),
            )
            for kp, leaf in jax.tree_util.tree_flatten_with_path(twin)[0]
        )
    }
    for name, value in final_dense.items():
        match = [
            v for k, v in twin_flat.items() if k == name or name in k
        ]
        assert match, (name, list(twin_flat))
        np.testing.assert_allclose(
            value, match[0], rtol=2e-4, atol=2e-5, err_msg=name
        )


def test_ps_kill_mid_job_sparse_path(fixed_data, no_shuffle):
    """Sparse training over a real-gRPC PS fleet survives killing and
    relaunching a PS shard mid-job (reference
    worker_ps_interaction_test.py:84-91, extended to the sparse path).
    Embedding rows on the dead shard are lost and lazily re-initialize —
    the reference's exact semantics (its replicated-PS design was never
    built)."""
    from elasticdl_tpu.ps.parameter_server import ParameterServer
    from elasticdl_tpu.worker.ps_client import BoundPS, PSClient
    from tests.test_utils import PserverArgs

    data_file, _ = fixed_data
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"

    def start_ps(ps_id, port=0):
        args = PserverArgs(
            grads_to_wait=1,
            use_async=True,
            port=port,
            model_zoo=MODEL_ZOO_PATH,
            model_def=model_def,
        )
        args.ps_id = ps_id
        args.lr_staleness_modulation = False
        ps = ParameterServer(args)
        ps.prepare()
        return ps, ps._server._edl_port

    servers, addrs = [], []
    for ps_id in range(2):
        ps, port = start_ps(ps_id)
        servers.append(ps)
        addrs.append("localhost:%d" % port)

    task_d = TaskDispatcher(
        {data_file: (0, RECORDS)}, {}, {}, BATCH, EPOCHS
    )
    master = MasterServicer(
        1,
        BATCH,
        None,  # params live on the PS fleet
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=True,
    )
    worker = Worker(
        worker_id=1,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=BATCH,
        model_zoo=MODEL_ZOO_PATH,
        model_def=model_def,
        model_params="embedding_dim=%d,fc_unit=8" % DIM,
        ps_client=PSClient([BoundPS(a) for a in addrs]),
    )
    worker._stub = InProcessMaster(master)

    # kill + relaunch PS 1 (same port = same stable address) after the
    # first few batches, from a callback on the worker's report path
    state = {"reports": 0, "killed": False}
    orig_report = worker.report_gradient

    def report_and_kill(*a, **k):
        out = orig_report(*a, **k)
        state["reports"] += 1
        if state["reports"] == 3 and not state["killed"]:
            state["killed"] = True
            port = int(addrs[1].split(":")[1])
            servers[1].stop()
            ps, _ = start_ps(1, port=port)
            servers[1] = ps
        return out

    worker.report_gradient = report_and_kill
    try:
        worker.run()
        assert state["killed"], "kill never triggered"
        assert task_d.finished()
        # dense params were re-pushed to the fresh shard and training
        # continued: both shards hold initialized state again
        total_dense = sum(
            len(ps.parameters.non_embedding_params) for ps in servers
        )
        assert total_dense > 0
        rows = worker._ps_client.pull_embedding_vectors(
            "embedding", np.arange(VOCAB)
        )
        assert rows.shape == (VOCAB, DIM)
        assert np.isfinite(rows).all()
    finally:
        for ps in servers:
            ps.stop()


TIED_ZOO_MODULE = '''
"""Tied-embedding test model: one elastic Embedding called twice per
forward (the case the reference degrades to eager, worker.py:514-524)."""
import flax.linen as nn
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.data.example import FixedLenFeature, parse_example
from elasticdl_tpu.nn.embedding import Embedding


class TiedModel(nn.Module):
    dim: int = 8

    @nn.compact
    def __call__(self, features, training=False):
        ids = features["feature"]
        emb = Embedding(output_dim=self.dim, name="tied")
        a = emb(ids)
        b = emb((ids + 1) % 60)
        bias = self.param("bias", nn.initializers.zeros, (1,))
        return (a.sum(axis=(1, 2)) + 2.0 * b.sum(axis=(1, 2)))[:, None] + bias


def custom_model(dim=8):
    return TiedModel(dim=int(dim))


def loss(output, labels):
    return ((output - labels.astype(jnp.float32)) ** 2).mean()


def optimizer(lr=0.1):
    return optax.sgd(float(lr))


def dataset_fn(dataset, mode, metadata):
    spec = {
        "feature": FixedLenFeature([10], np.int64),
        "label": FixedLenFeature([1], np.int64),
    }

    def parse(record):
        r = parse_example(record, spec)
        return {"feature": r["feature"]}, r["label"]

    return dataset.map(parse)


def eval_metrics_fn():
    return {}
'''


def test_tied_embedding_worker_matches_dense(
    fixed_data, no_shuffle, tmp_path
):
    """A model calling one elastic Embedding twice per forward trains
    through the PS plane and lands the same table as dense training —
    beyond the reference, which drops to eager for this case."""
    data_file, records = fixed_data
    zoo_dir = tmp_path / "zoo" / "tied_model"
    zoo_dir.mkdir(parents=True)
    (zoo_dir / "tied_model.py").write_text(TIED_ZOO_MODULE)

    task_d = TaskDispatcher(
        {data_file: (0, RECORDS)}, {}, {}, RECORDS, EPOCHS
    )
    master = MasterServicer(
        1,
        BATCH,
        optax.sgd(LR),
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=False,
    )
    master.push_embedding_info(
        [EmbeddingTableInfo("tied", DIM, "uniform")]
    )
    all_ids = np.arange(VOCAB)
    init_rows = master.pull_embedding_vectors("tied", all_ids).copy()

    worker = Worker(
        worker_id=1,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=BATCH,
        model_zoo=str(tmp_path / "zoo"),
        model_def="tied_model.tied_model.custom_model",
        model_params="dim=%d" % DIM,
        stub=None,
    )
    worker._stub = InProcessMaster(master)
    worker.run()
    assert task_d.finished()
    final_rows = master.pull_embedding_vectors("tied", all_ids)

    # dense twin: identical batches against a (VOCAB, DIM) table + bias
    import jax.numpy as jnp

    twin = {
        "table": jnp.asarray(init_rows.astype(np.float32)),
        "bias": jnp.zeros((1,), jnp.float32),
    }
    for _ in range(EPOCHS):
        for i in range(0, RECORDS, BATCH):
            chunk = records[i : i + BATCH]
            ids = np.stack([r[0] for r in chunk])
            labels = np.stack([r[1] for r in chunk]).astype(np.float32)

            def dense_loss(p):
                a = p["table"][ids]
                b = p["table"][(ids + 1) % VOCAB]
                out = (
                    a.sum(axis=(1, 2)) + 2.0 * b.sum(axis=(1, 2))
                )[:, None] + p["bias"]
                return ((out - labels) ** 2).mean()

            g = jax.grad(dense_loss)(twin)
            twin = {k: v - LR * g[k] for k, v in twin.items()}

    np.testing.assert_allclose(
        final_rows, np.asarray(twin["table"]), rtol=2e-4, atol=2e-5
    )
    _, final_dense = master.get_model(master.get_model_version())
    np.testing.assert_allclose(
        final_dense["bias"], np.asarray(twin["bias"]), rtol=2e-4, atol=2e-5
    )
