"""Master recovery plane: journal replay edge cases + failover channel
(docs/master_recovery.md).

The dispatcher half runs the REAL TaskDispatcher against a real
on-disk journal through kill/relaunch cycles (simulated by dropping
the dispatcher and re-folding the chain); the channel half runs a real
loopback gRPC master, kills it, and relaunches it on the same port
with a new ``master_epoch``.
"""

import glob
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from elasticdl_tpu.common.constants import (
    TaskExecCounterKey,
    TaskType,
)
from elasticdl_tpu.master.journal import (
    MasterJournal,
    RecoveryState,
    mint_master_epoch,
    task_key,
)
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

SHARDS = {"data.edlr": (0, 120)}
RECORDS_PER_TASK = 12  # 10 tasks per epoch


def make_dispatcher(journal, num_epochs=2, shards=None):
    return TaskDispatcher(
        dict(shards if shards is not None else SHARDS),
        {},
        {},
        RECORDS_PER_TASK,
        num_epochs,
        journal=journal,
    )


def boot(tmpdir, num_epochs=2, **journal_kw):
    """One master boot: journal replay -> dispatcher recovery -> start
    writing (the Master.prepare sequence, without the RPC plane)."""
    journal = MasterJournal(str(tmpdir), **journal_kw)
    state = journal.replay()
    d = make_dispatcher(journal, num_epochs=num_epochs)
    d.apply_recovery(state)
    journal.start()
    return journal, d, state


def ack_counters(task):
    return {
        TaskExecCounterKey.TRACE_ID: task.extended_config["trace_id"],
        TaskExecCounterKey.ATTEMPT: task.extended_config.get(
            "_attempt", 0
        ),
    }


def test_fresh_boot_is_empty_recovery(tmp_path):
    journal, d, state = boot(tmp_path)
    assert state.done_keys == set() and state.pending == {}
    assert d.queue_depths()["todo"] == 10
    journal.close()


def test_done_tasks_stay_done_across_relaunch(tmp_path):
    journal, d, _ = boot(tmp_path)
    for _ in range(3):
        tid, _task = d.get(worker_id=1)
        d.report(tid, True)
    journal.close()

    journal2, d2, state = boot(tmp_path)
    assert len(state.done_keys) == 3
    assert d2.queue_depths()["todo"] == 7
    journal2.close()


def test_inflight_tasks_requeue_exactly_once_with_preserved_trace(
    tmp_path,
):
    journal, d, _ = boot(tmp_path)
    dispatched = [d.get(worker_id=1) for _ in range(4)]
    d.report(dispatched[0][0], True)
    traces = {
        tid: t.extended_config["trace_id"] for tid, t in dispatched
    }
    attempts = {
        tid: t.extended_config["_attempt"] for tid, t in dispatched
    }
    journal.close()  # the "crash": 3 tasks in flight

    journal2, d2, state = boot(tmp_path)
    # requeued exactly once: full epoch minus the one done task
    depths = d2.queue_depths()
    assert depths["todo"] == 9 and depths["doing"] == 0
    # the in-flight tasks kept their traces, attempt bumped by one
    todo_traces = {
        t.extended_config.get("trace_id"): t.extended_config.get(
            "_attempt"
        )
        for t in d2._todo
        if t.extended_config.get("trace_id")
    }
    for tid, _task in dispatched[1:]:
        assert todo_traces[traces[tid]] == attempts[tid] + 1
    # counters: the boot journaled one recovery requeue per survivor
    assert journal2.counts()["requeued"] == 3
    journal2.close()


def test_replay_twice_equals_once(tmp_path):
    journal, d, _ = boot(tmp_path)
    for _ in range(3):
        tid, _t = d.get(worker_id=1)
        d.report(tid, True)
    d.get(worker_id=1)  # leave one in flight
    journal.close()

    j_a = MasterJournal(str(tmp_path))
    s_a = j_a.replay()
    s_b = j_a.replay()
    assert s_a.done_keys == s_b.done_keys
    assert s_a.done_traces == s_b.done_traces
    assert set(s_a.pending) == set(s_b.pending)
    assert s_a.epoch == s_b.epoch and s_a.version == s_b.version
    # and a second journal instance folds identically
    j_c = MasterJournal(str(tmp_path))
    s_c = j_c.replay()
    assert s_c.done_keys == s_a.done_keys
    assert set(s_c.pending) == set(s_a.pending)


def test_torn_final_record_is_dropped(tmp_path):
    journal, d, _ = boot(tmp_path)
    tid1, _ = d.get(worker_id=1)
    tid2, _ = d.get(worker_id=1)
    d.report(tid1, True)
    journal.close()

    segs = sorted(glob.glob(str(tmp_path / "seg-*.jsonl")))
    assert segs
    with open(segs[-1], "ab") as f:
        # the crash catches the writer mid-line: valid json prefix, no
        # terminator — exactly what a torn batched write leaves
        f.write(b'{"k": "done", "trace": "t0000')

    journal2, d2, state = boot(tmp_path)
    # the torn done never counted: one done, one still pending
    assert len(state.done_keys) == 1
    assert len(state.pending) == 1
    assert d2.queue_depths()["todo"] == 9
    journal2.close()


def test_replayed_ack_resolves_and_dedups(tmp_path):
    """The worker-side replay protocol end to end at the ledger: an ack
    for an in-flight-at-crash task resolves by trace (exactly-once),
    and an ack the dead master already counted dedups."""
    journal, d, _ = boot(tmp_path)
    done_tid, done_task = d.get(worker_id=1)
    inflight_tid, inflight_task = d.get(worker_id=1)
    d.report(done_tid, True, exec_counters=ack_counters(done_task))
    journal.close()

    journal2, d2, _ = boot(tmp_path)
    before = d2.queue_depths()["todo"]
    # the worker's held ack replays with the OLD task id + trace
    d2.report(
        inflight_tid, True, exec_counters=ack_counters(inflight_task)
    )
    assert d2.queue_depths()["todo"] == before - 1
    # replaying it again is a no-op (dedup)
    d2.report(
        inflight_tid, True, exec_counters=ack_counters(inflight_task)
    )
    assert d2.queue_depths()["todo"] == before - 1
    # an ack the dead incarnation already counted dedups too
    d2.report(done_tid, True, exec_counters=ack_counters(done_task))
    counts = journal2.counts()
    assert counts["deduped"] == 2
    # done counts once per unique task, never twice
    assert counts["done"] == 2
    journal2.close()


def test_full_job_exactly_once_accounting_across_kill(tmp_path):
    """Drive a 2-epoch job to completion with a mid-epoch crash:
    every task counts done exactly once in the final journal."""
    journal, d, _ = boot(tmp_path)
    for _ in range(6):
        tid, _t = d.get(worker_id=1)
        d.report(tid, True)
    d.get(worker_id=1)  # in flight at the kill
    journal.close()

    journal2, d2, _ = boot(tmp_path)
    while True:
        tid, task = d2.get(worker_id=1)
        if task is None:
            break
        d2.report(tid, True, exec_counters=ack_counters(task))
    assert d2.finished()
    counts = journal2.counts()
    # 10 tasks x 2 epochs, each done exactly once
    assert counts["done"] == 20, counts
    assert counts["pending"] == 0
    journal2.close()


def test_mid_second_epoch_crash_resumes_that_epoch(tmp_path):
    journal, d, _ = boot(tmp_path)
    # drain epoch 0 fully
    for _ in range(10):
        tid, _t = d.get(worker_id=1)
        d.report(tid, True)
    # epoch 1 rolls lazily; complete 4 of its tasks
    for _ in range(4):
        tid, _t = d.get(worker_id=1)
        d.report(tid, True)
    journal.close()

    journal2, d2, state = boot(tmp_path)
    assert state.epoch == 1
    assert d2._epoch == 1
    assert d2.queue_depths()["todo"] == 6
    journal2.close()


def test_segment_rotation_compacts_and_replays_identically(tmp_path):
    journal = MasterJournal(
        str(tmp_path), fsync_interval_s=0.005, segment_records=32
    )
    state = journal.replay()
    d = make_dispatcher(journal, num_epochs=4)
    d.apply_recovery(state)
    journal.start()
    done = 0
    for _ in range(3):  # 30 dispatch+done pairs, forcing rotations
        for _ in range(10):
            tid, task = d.get(worker_id=1)
            if task is None:
                break
            d.report(tid, True)
            done += 1
    deadline = time.time() + 10
    while journal.counts()["unflushed"] and time.time() < deadline:
        time.sleep(0.02)
    journal.close()

    segs = glob.glob(str(tmp_path / "seg-*.jsonl"))
    assert len(segs) <= 2, "rotation must unlink superseded segments"
    with open(sorted(segs)[0], "rb") as f:
        head = json.loads(f.readline())
    assert head["k"] == "state"

    j2 = MasterJournal(str(tmp_path))
    s2 = j2.replay()
    assert s2.counters["done"] == done
    assert len(s2.pending) == 0


def test_version_and_member_epoch_fold(tmp_path):
    journal = MasterJournal(str(tmp_path))
    journal.replay()
    journal.start()
    journal.append("version", version=3)
    journal.append("version", version=7)
    journal.append("member", event="join", worker=1, epoch=2)
    journal.append("member", event="leave", worker=1, epoch=5)
    journal.flush()
    journal.close()
    state = MasterJournal(str(tmp_path)).replay()
    assert state.version == 7
    assert state.member_epoch == 5


def test_master_epoch_mint_is_monotonic(tmp_path):
    e1 = mint_master_epoch(str(tmp_path))
    e2 = mint_master_epoch(str(tmp_path))
    assert e2 == e1 + 1
    # dirless mint still yields a fresh nonzero id
    assert mint_master_epoch(None) > 0


def test_task_shuffle_seed_pins_task_order(tmp_path, monkeypatch):
    def order(seed):
        if seed is None:
            monkeypatch.delenv("EDL_TASK_SHUFFLE_SEED", raising=False)
        else:
            monkeypatch.setenv("EDL_TASK_SHUFFLE_SEED", str(seed))
        d = make_dispatcher(None)
        return [t._info() for t in d._todo]

    assert order(11) == order(11)
    assert order(11) != order(12) or len(order(11)) <= 1


def test_save_model_task_recovers_from_journal(tmp_path):
    journal, d, _ = boot(tmp_path, num_epochs=1)
    d.add_deferred_callback_create_save_model_task(
        str(tmp_path / "export")
    )
    for _ in range(10):
        tid, _t = d.get(worker_id=1)
        d.report(tid, True)
    assert d.invoke_deferred_callback()
    save_tid, save_task = d.get(worker_id=1)
    assert save_task.type == TaskType.SAVE_MODEL
    journal.close()  # crash with the export task in flight

    journal2, d2, state = boot(tmp_path, num_epochs=1)
    # the save task requeued from its journaled extended config, and
    # the deferred callback does NOT fire a second export
    saves = [
        t for t in d2._todo if t.type == TaskType.SAVE_MODEL
    ]
    assert len(saves) == 1
    assert saves[0].extended_config.get("saved_model_path") == str(
        tmp_path / "export"
    )
    assert not d2.invoke_deferred_callback()
    tid, task = d2.get(worker_id=1)
    assert task.type == TaskType.SAVE_MODEL
    d2.report(tid, True, exec_counters=ack_counters(task))
    assert d2.finished()
    journal2.close()


# ---------------------------------------------------------------------------
# the serving surface: epoch stamping, master_status, /healthz, failover
# ---------------------------------------------------------------------------


def _serve_master(task_d, master_epoch, port=0, health=None, journal=None):
    from elasticdl_tpu.master.rpc_service import MasterRpcService
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.rpc.core import serve

    servicer = MasterServicer(1, 16, None, task_d, use_async=True)

    def status_fn():
        out = {"state": health() if health else "serving"}
        if journal is not None:
            out["journal"] = journal.counts()
        return out

    methods = MasterRpcService(
        servicer,
        master_epoch=master_epoch,
        status_fn=status_fn,
    ).rpc_methods()
    server = serve(methods, port)
    return server, server._edl_port


def test_master_epoch_stamped_in_every_reply(tmp_path):
    from elasticdl_tpu.master.rpc_service import MasterClient

    d = make_dispatcher(None)
    server, port = _serve_master(d, master_epoch=41)
    client = MasterClient("localhost:%d" % port)
    try:
        status = client.master_status()
        assert status["master_epoch"] == 41
        task = client.get_task(1)
        assert task.task_id > 0
        assert client.master_epoch == 41
    finally:
        client.close()
        server.stop(grace=None)


def test_failover_rides_out_master_relaunch_and_detects_epoch(tmp_path):
    """Kill the serving master mid-conversation; the failover channel
    retries through the outage, lands on the relaunched incarnation,
    and fires the epoch-change hook exactly once."""
    from elasticdl_tpu.master.rpc_service import MasterClient

    d1 = make_dispatcher(None)
    server, port = _serve_master(d1, master_epoch=1)
    client = MasterClient(
        "localhost:%d" % port, failover_s=30.0
    )
    changes = []
    client.set_on_master_epoch_change(
        lambda old, new: changes.append((old, new))
    )
    try:
        assert client.get_task(1).task_id > 0
        server.stop(grace=None)

        relaunched = {}

        def relaunch():
            time.sleep(1.0)
            d2 = make_dispatcher(None)
            relaunched["server"], _ = _serve_master(
                d2, master_epoch=2, port=port
            )

        t = threading.Thread(target=relaunch)
        t.start()
        try:
            # issued against a dead port: rides the retry loop until
            # the new incarnation binds, then lands there
            task = client.get_task(1)
            assert task.task_id > 0
            assert client.master_epoch == 2
            assert changes == [(1, 2)]
        finally:
            t.join()
            relaunched["server"].stop(grace=None)
    finally:
        client.close()


def test_failover_budget_zero_raises_immediately():
    import grpc

    from elasticdl_tpu.master.rpc_service import MasterClient

    from tests.fake_ps import free_port

    client = MasterClient(
        "localhost:%d" % free_port(), failover_s=0.0
    )
    try:
        t0 = time.monotonic()
        with pytest.raises(grpc.RpcError):
            client.get_task(1)
        assert time.monotonic() - t0 < 5.0
    finally:
        client.close()


def test_healthz_reports_restoring_then_serving():
    from elasticdl_tpu.master.telemetry import TelemetryHTTPServer

    class _T:
        @staticmethod
        def prometheus_text():
            return ""

        @staticmethod
        def events_tail(n=200):
            return []

    state = {"health": "restoring"}
    http_server = TelemetryHTTPServer(
        _T(), port=0, health_fn=lambda: state["health"]
    )
    try:
        url = "http://localhost:%d/healthz" % http_server.port
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url, timeout=5)
        assert err.value.code == 503
        assert err.value.read().decode().strip() == "restoring"
        state["health"] = "serving"
        body = urllib.request.urlopen(url, timeout=5)
        assert body.status == 200
        assert body.read().decode().strip() == "serving"
    finally:
        http_server.close()


def test_recovery_state_pure_fold_unknown_kinds_skipped():
    s = RecoveryState()
    s.apply({"k": "dispatch", "trace": "t000005", "attempt": 0,
             "key": [0, 0, "f", 0, 12]})
    s.apply({"k": "some_future_kind", "x": 1})
    s.apply({"k": "done", "trace": "t000005", "attempt": 0,
             "key": [0, 0, "f", 0, 12]})
    assert s.trace_seq == 5
    assert task_key(0, 0, "f", 0, 12) in s.done_keys
    assert s.pending == {}
