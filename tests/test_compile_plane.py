"""Compile-plane fast path (parallel/compile_plane.py + the elastic
trainer's establish/step integration).

Everything runs single-process on the virtual 8-device CPU mesh,
driving the SAME trainer surfaces the elastic worker uses — the mesh is
swapped in-process (the bench_compile recipe) so the backend survives
resizes and the in-memory executable reuse is observable. The
trace-counting tests use a loss_fn that bumps a Python counter: the
counter only advances while jax is TRACING, so "no retrace" is asserted
directly rather than inferred from timings.

Run under ``EDL_LOCKTRACE=1`` (scripts/check.sh) these tests also
assert, via the conftest guard, that no non-daemon thread leaks out of
the speculative compiler / H2D feeder lifecycles.
"""

import threading
import time

import numpy as np
import pytest

import jax
import optax
from jax.sharding import Mesh

from elasticdl_tpu.parallel import compile_plane, distributed
from elasticdl_tpu.parallel import elastic as elastic_mod
from elasticdl_tpu.parallel.compile_plane import (
    ExecutableCache,
    SpeculativeCompiler,
    mesh_signature,
)
from elasticdl_tpu.parallel.distributed import WorldSpec
from elasticdl_tpu.parallel.elastic import ElasticDPTrainer
from model_zoo.transformer_lm import transformer_lm as zoo

VOCAB = 64
LENGTH = 8
BATCH = 16
MODEL_KW = dict(
    vocab_size=VOCAB,
    num_layers=2,
    num_heads=2,
    head_dim=8,
    embed_dim=16,
    mlp_dim=32,
    use_flash=False,
)


def _batch(seed=0, batch=BATCH):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, VOCAB, size=(batch, LENGTH)).astype(np.int32)
    return {"tokens": ids}, ids


def _make_trainer(loss_fn=None, minibatch=BATCH):
    model = zoo.custom_model(**MODEL_KW)
    trainer = ElasticDPTrainer(
        model, loss_fn or zoo.loss, optax.sgd(0.05)
    )
    trainer.default_minibatch_size = minibatch
    trainer._spec = WorldSpec(
        coordinator="", num_processes=1, process_id=0, epoch=0
    )
    trainer._host_ts = trainer._host_init_ts(_batch())
    return trainer


def _establish_at(trainer, k):
    """In-process resize: the establish phases minus the world RPC —
    exactly what bench.py --compile times."""
    if trainer._ts is not None:
        trainer._host_ts = trainer.snapshot()
    trainer._mesh = Mesh(np.asarray(jax.devices()[:k]), ("data",))
    trainer._ts = elastic_mod.broadcast_from_device0(
        trainer._mesh, trainer._host_ts
    )
    trainer._checked_ts = trainer._ts
    trainer._spec_example = _batch()
    return trainer._acquire_step_fn()


def _counting_loss():
    """A loss whose Python body runs only while jax traces."""
    calls = {"n": 0}

    def loss(output, labels):
        calls["n"] += 1
        return zoo.loss(output, labels)

    return loss, calls


# ---------------------------------------------------------------------------
# executable cache: reuse without retracing, correct misses
# ---------------------------------------------------------------------------


def test_reestablish_at_seen_size_reuses_executable_without_retrace():
    loss_fn, calls = _counting_loss()
    t = _make_trainer(loss_fn)
    features, labels = _batch(1)

    assert _establish_at(t, 8) is False  # first visit: miss
    t.train_step(features, labels, BATCH, sync=True)
    traces_8 = calls["n"]
    assert traces_8 > 0
    fn_8 = t._step_fn

    assert _establish_at(t, 4) is False  # new size: miss, retraces
    t.train_step(features, labels, BATCH, sync=True)
    traces_4 = calls["n"]
    assert traces_4 > traces_8

    assert _establish_at(t, 8) is True  # revisit: cache hit
    assert t._step_fn is fn_8  # the SAME jitted callable
    loss, n, count = t.train_step(features, labels, BATCH, sync=True)
    assert calls["n"] == traces_4, "revisit at a seen size retraced"
    assert np.isfinite(loss) and n == 8 and count == BATCH
    stats = t.compile_stats.snapshot()
    assert stats["hits"] == 1 and stats["misses"] == 2
    t.close()


def test_batch_shape_change_misses_instead_of_stale_reuse():
    loss_fn, calls = _counting_loss()
    t = _make_trainer(loss_fn)
    features, labels = _batch(2)
    _establish_at(t, 8)
    t.train_step(features, labels, BATCH, sync=True)
    traces = calls["n"]

    # same executable-cache entry, DIFFERENT batch shape (a larger
    # minibatch pads to more rows): jax's aval cache must miss and
    # compile the new shape — reusing the 16-row executable for 32-row
    # input would be a stale-executable bug
    wide_f, wide_l = _batch(3, batch=32)
    loss, n, count = t.train_step(wide_f, wide_l, 32, sync=True)
    assert calls["n"] > traces, "batch-shape change did not retrace"
    assert np.isfinite(loss) and count == 32
    t.close()


def test_cached_executable_matches_fresh_build_bitwise():
    batches = [_batch(seed) for seed in (10, 11, 12)]

    def journey(cache_enabled):
        t = _make_trainer()
        t.compile_cache_enabled = cache_enabled
        for k in (8, 4, 8):
            _establish_at(t, k)
            for features, labels in batches:
                t.train_step(features, labels, BATCH, sync=True)
        host = t.snapshot()
        t.close()
        return host

    cold = journey(cache_enabled=False)
    cached = journey(cache_enabled=True)
    for a, b in zip(
        jax.tree_util.tree_leaves(cold.params),
        jax.tree_util.tree_leaves(cached.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_cache_evicts_entries_from_dead_backends():
    cache = ExecutableCache()
    key = ("mesh-sig", "config-sig")
    cache.put(key, object())
    assert cache.get(key) is not None
    # a world re-form drops every backend; entries minted before must
    # never be handed back (their device handles are dead)
    distributed._bump_backend_epoch()
    assert cache.get(key) is None
    assert cache.stats.get("stale_evictions") == 1


def test_cache_lru_bounds_entries():
    cache = ExecutableCache(max_entries=2)
    for i in range(3):
        cache.put(("k", i), object())
    assert cache.size() == 2
    assert cache.get(("k", 0), count=False) is None  # evicted oldest
    assert cache.get(("k", 2), count=False) is not None


def test_mesh_signature_distinguishes_device_sets():
    devices = np.asarray(jax.devices())
    m8 = Mesh(devices, ("data",))
    m4 = Mesh(devices[:4], ("data",))
    m8b = Mesh(devices, ("data",))
    assert mesh_signature(m8) == mesh_signature(m8b)
    assert mesh_signature(m8) != mesh_signature(m4)


# ---------------------------------------------------------------------------
# speculative compiler: lifecycle, drops, cache pre-warm
# ---------------------------------------------------------------------------


def _wait(predicate, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def test_speculative_compile_prewarms_establish():
    t = _make_trainer()
    _establish_at(t, 8)
    features, labels = _batch(4)
    t.train_step(features, labels, BATCH, sync=True)

    t.speculative_compile = True
    t._start_speculative_compiler()
    t.hint_world_sizes([4])
    assert _wait(
        lambda: t.compile_stats.get("speculative_builds") >= 1
        and t._spec_compiler.idle()
    ), "speculative compile never landed"

    assert _establish_at(t, 4) is True  # the speculated entry
    assert t.compile_stats.get("speculative_hits") == 1
    # the AOT executable dispatches this exact signature — no retrace
    loss, n, count = t.train_step(features, labels, BATCH, sync=True)
    assert np.isfinite(loss) and n == 4 and count == BATCH
    t.close()


def test_speculative_size_that_never_materializes_is_dropped():
    t = _make_trainer()
    _establish_at(t, 8)
    features, labels = _batch(5)
    t.train_step(features, labels, BATCH, sync=True)
    t.speculative_compile = True
    t._start_speculative_compiler()
    before = time.perf_counter()
    t.hint_world_sizes([999])  # more devices than the backend has
    hint_cost = time.perf_counter() - before
    assert hint_cost < 0.5, "hint() blocked the hot loop"
    assert _wait(lambda: t.compile_stats.get("dropped") >= 1)
    # the hot loop keeps stepping while the hint dies in the background
    loss, _, _ = t.train_step(features, labels, BATCH, sync=True)
    assert np.isfinite(loss)
    t.close()


def test_speculative_compiler_shuts_down_on_establish_and_close():
    t = _make_trainer()
    _establish_at(t, 8)
    t.speculative_compile = True
    t._start_speculative_compiler()
    sc = t._spec_compiler
    thread = sc._thread
    assert thread is not None and thread.is_alive()

    # establish()'s first act is _shutdown_compile_helpers(): the old
    # backend's compiler must be gone before the world is torn down
    t._shutdown_compile_helpers()
    assert t._spec_compiler is None
    assert not thread.is_alive()

    # restart then close(): same guarantee at worker teardown
    t._start_speculative_compiler()
    thread = t._spec_compiler._thread
    t.close()
    assert not thread.is_alive()


def test_speculative_compiler_shutdown_drops_pending_hints():
    started = threading.Event()
    release = threading.Event()
    built = []

    def slow_compile(size):
        started.set()
        release.wait(timeout=30)
        built.append(size)
        return True

    sc = SpeculativeCompiler(slow_compile)
    sc.start()
    sc.hint([3])
    assert started.wait(timeout=10)
    sc.hint([5, 7])  # queued behind the in-flight compile
    assert sc.pending_count() == 2
    # cooperative cancel lands BEFORE the in-flight compile finishes:
    # the worker completes size 3 (C++ compiles are uninterruptible)
    # and must then exit without touching the queue again
    sc._cancel.set()
    release.set()
    sc.shutdown()
    assert sc.pending_count() == 0
    assert built == [3], "pending hints ran after shutdown"
    assert sc.stats.get("dropped") == 2
    # post-shutdown hints are ignored, not queued
    sc.hint([9])
    assert sc.pending_count() == 0


def test_speculative_compiler_dedups_hints():
    seen = []
    done = threading.Event()

    def compile_fn(size):
        seen.append(size)
        if len(seen) >= 2:
            done.set()
        return True

    sc = SpeculativeCompiler(compile_fn)
    sc.start()
    sc.hint([4, 4, 6, 4, 6])
    assert done.wait(timeout=10)
    sc.shutdown()
    assert sorted(seen) == [4, 6]


def test_speculative_compiler_accepts_layout_hints():
    """ISSUE 20: hints may be (world_size, layout) tuples — the layout
    half is opaque to the compiler but participates in dedup, so two
    different layouts of ONE world size both compile, while a repeated
    (world, layout) pair does not."""
    seen = []
    done = threading.Event()

    def compile_fn(hint):
        seen.append(hint)
        if len(seen) >= 3:
            done.set()
        return True

    lay_a = (8, (("data", 4), ("model", 2)))
    lay_b = (8, (("data", 2), ("model", 4)))
    sc = SpeculativeCompiler(compile_fn)
    sc.start()
    sc.hint([lay_a, lay_b, lay_a, 8, lay_b])
    assert done.wait(timeout=10)
    sc.shutdown()
    assert sorted(seen, key=str) == sorted(
        [lay_a, lay_b, 8], key=str
    )
    # zero/negative world sizes are dropped in either form
    sc2 = SpeculativeCompiler(compile_fn)
    sc2.hint([0, (0, (("data", 1),))])
    assert sc2.pending_count() == 0


# ---------------------------------------------------------------------------
# step overlap: staged H2D equivalence + deferred metric collection
# ---------------------------------------------------------------------------


def test_staged_h2d_placement_is_equivalent_and_feeder_shuts_down():
    batches = [_batch(seed) for seed in (20, 21, 22, 23)]

    def run(staged):
        t = _make_trainer()
        _establish_at(t, 8)
        losses = []
        for i, (features, labels) in enumerate(batches):
            loss, _, _ = t.train_step(features, labels, BATCH, sync=True)
            losses.append(loss)
            if staged and i + 1 < len(batches):
                # stage AFTER the take-side step so the slot is not
                # superseded before train_step(i+1) collects it
                nxt_f, nxt_l = batches[i + 1]
                t.stage_next(nxt_f, nxt_l, BATCH)
        host = t.snapshot()
        feeder_thread = (
            t._feeder._thread if t._feeder is not None else None
        )
        t.close()
        if feeder_thread is not None:
            assert not feeder_thread.is_alive()
        return losses, host

    plain_losses, plain = run(staged=False)
    staged_losses, staged = run(staged=True)
    assert plain_losses == staged_losses
    for a, b in zip(
        jax.tree_util.tree_leaves(plain.params),
        jax.tree_util.tree_leaves(staged.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_deferred_metrics_match_per_step_sync_stream():
    batches = [_batch(seed) for seed in range(30, 39)]

    def run(deferred):
        t = _make_trainer()
        _establish_at(t, 8)
        losses = []
        for i, (features, labels) in enumerate(batches):
            if deferred:
                sync = (i + 1) % 4 == 0 or i == len(batches) - 1
                loss, _, _ = t.train_step(
                    features, labels, BATCH, sync=sync
                )
                if sync:
                    losses.extend(t.drain_metrics())
                    losses.append(loss)
            else:
                loss, _, _ = t.train_step(
                    features, labels, BATCH, sync=True
                )
                losses.append(loss)
        t.close()
        return losses

    assert run(deferred=False) == run(deferred=True)


def test_drain_metrics_empty_and_wedged():
    t = _make_trainer()
    _establish_at(t, 8)
    assert t.drain_metrics() == []
    features, labels = _batch(40)
    t.train_step(features, labels, BATCH, sync=False)
    assert len(t._pending_metrics) == 1
    # a wedged trainer must not fetch (the device stream would block
    # forever); pending is dropped
    t._wedged = True
    assert t.drain_metrics() == []
    assert t._pending_metrics == []
    t._wedged = False
    t.close()


def test_take_staged_mismatched_batch_places_inline():
    t = _make_trainer()
    _establish_at(t, 8)
    f1, l1 = _batch(50)
    f2, l2 = _batch(51)
    t.stage_next(f1, l1, BATCH)
    # a DIFFERENT batch steps next (reform reshuffled the stream): the
    # staged placement must be ignored, not misapplied
    loss, _, _ = t.train_step(f2, l2, BATCH, sync=True)
    assert np.isfinite(loss)

    # and the superseded stage slot does not poison the next take
    t.stage_next(f1, l1, BATCH)
    loss2, _, _ = t.train_step(f1, l1, BATCH, sync=True)
    assert np.isfinite(loss2)
    t.close()


def test_persistent_cache_skipped_on_cpu(tmp_path, monkeypatch):
    """CPU-pinned processes must NOT take the persistent cache (reloaded
    donated executables crash this toolchain; see compile_plane)."""
    prev = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv("EDL_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    monkeypatch.delenv("EDL_COMPILE_CACHE_CPU", raising=False)
    try:
        assert compile_plane.enable_persistent_cache() is False
        assert jax.config.jax_compilation_cache_dir == prev
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)


def test_persistent_cache_config(tmp_path, monkeypatch):
    prev = jax.config.jax_compilation_cache_dir
    monkeypatch.setenv("EDL_COMPILE_CACHE_DIR", str(tmp_path / "cc"))
    # the suite runs CPU-pinned; exercise the config path via the
    # explicit override the caveat documents
    monkeypatch.setenv("EDL_COMPILE_CACHE_CPU", "1")
    try:
        assert compile_plane.enable_persistent_cache() is True
        assert jax.config.jax_compilation_cache_dir == str(
            tmp_path / "cc"
        )
        # idempotent
        assert compile_plane.enable_persistent_cache() is True
        # unset env: a no-op (config untouched, returns False)
        monkeypatch.delenv("EDL_COMPILE_CACHE_DIR")
        assert compile_plane.enable_persistent_cache() is False
        assert jax.config.jax_compilation_cache_dir == str(
            tmp_path / "cc"
        )
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
