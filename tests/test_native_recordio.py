"""C++ EDLR reader vs the Python implementation (same file, same bytes)."""

import os
import subprocess
import sys

import pytest

from elasticdl_tpu.data.recordio import (
    RecordIOReader,
    RecordIOWriter,
    open_recordio,
)
from elasticdl_tpu.native import NativeRecordIOReader, native_lib


def _ensure_built():
    if native_lib() is None:
        subprocess.check_call(
            [sys.executable, "-m", "elasticdl_tpu.native.build"]
        )
        # reset the load cache
        import elasticdl_tpu.native as native_mod

        native_mod._load_failed = False
        native_mod._handle = None
    return native_lib() is not None


pytestmark = pytest.mark.skipif(
    not _ensure_built(), reason="native toolchain unavailable"
)


def _write(tmp_path, records):
    path = str(tmp_path / "data.edlr")
    with RecordIOWriter(path) as w:
        for r in records:
            w.write(r)
    return path


def test_native_matches_python(tmp_path):
    records = [b"alpha", b"", b"x" * 10000, b"tail"]
    path = _write(tmp_path, records)
    with NativeRecordIOReader(path) as native, RecordIOReader(path) as py:
        assert len(native) == len(py) == 4
        for i in range(4):
            assert native.read(i) == bytes(py.read(i)) == records[i]
        assert list(native.read_range(1, 3)) == records[1:3]


def test_native_crc_validation(tmp_path):
    path = _write(tmp_path, [b"payload"])
    with NativeRecordIOReader(path) as r:
        assert r.read(0, validate=True) == b"payload"
    # corrupt the payload in place
    with open(path, "r+b") as f:
        f.seek(8 + 8)  # header + record header
        f.write(b"X")
    with NativeRecordIOReader(path) as r:
        with pytest.raises(ValueError):
            r.read(0, validate=True)


def test_native_rejects_garbage(tmp_path):
    bad = str(tmp_path / "bad.bin")
    with open(bad, "wb") as f:
        f.write(b"not an edlr file at all........")
    with pytest.raises(ValueError):
        NativeRecordIOReader(bad)


def test_factory_prefers_native(tmp_path):
    path = _write(tmp_path, [b"a"])
    reader = open_recordio(path)
    assert isinstance(reader, NativeRecordIOReader)
    reader.close()


def _patch(path, offset, value_u64):
    import struct

    with open(path, "r+b") as f:
        f.seek(offset)
        f.write(struct.pack("<Q", value_u64))


def test_native_rejects_wrapping_index_offset(tmp_path):
    # index_offset chosen so index_offset + 8 wraps past 2**64 and the
    # additive bounds check would accept it
    path = _write(tmp_path, [b"abc"])
    size = os.path.getsize(path)
    _patch(path, size - 12, 2**64 - 4)
    with pytest.raises(ValueError):
        NativeRecordIOReader(path)


def test_native_rejects_wrapping_record_count(tmp_path):
    # count * 8 == 0 mod 2**64: additive check would pass, reader would
    # then index 2**61 "records" off the end of the mapping
    path = _write(tmp_path, [b"abc"])
    size = os.path.getsize(path)
    import struct

    with open(path, "rb") as f:
        f.seek(size - 12)
        index_offset = struct.unpack("<Q", f.read(8))[0]
    _patch(path, index_offset, 2**61)
    with pytest.raises(ValueError):
        NativeRecordIOReader(path)


def test_native_rejects_wrapping_record_offset(tmp_path):
    # offsets[0] near 2**64: off + header wraps, payload_len check would
    # read out of the mapping without the subtraction-form bounds
    path = _write(tmp_path, [b"abc"])
    size = os.path.getsize(path)
    import struct

    with open(path, "rb") as f:
        f.seek(size - 12)
        index_offset = struct.unpack("<Q", f.read(8))[0]
    _patch(path, index_offset + 8, 2**64 - 2)
    with NativeRecordIOReader(path) as r:
        with pytest.raises(IndexError):
            r.read(0)


def test_native_writer_round_trip(tmp_path):
    """Native writer -> both readers; byte-identical layout to the
    Python writer for the same records."""
    pytest.importorskip("ctypes")
    from elasticdl_tpu.data.recordio import RecordIOReader, RecordIOWriter
    from elasticdl_tpu.native import (
        NativeRecordIOReader,
        NativeRecordIOWriter,
        native_lib,
    )

    if native_lib() is None:
        pytest.skip("native library not built")

    records = [b"alpha", b"", b"\x00\x01\x02" * 100, b"tail"]
    native_path = str(tmp_path / "native.edlr")
    with NativeRecordIOWriter(native_path) as w:
        for r in records:
            w.write(r)
        assert w.num_records == len(records)

    python_path = str(tmp_path / "python.edlr")
    with RecordIOWriter(python_path) as w:
        for r in records:
            w.write(r)

    # identical bytes: one format, two implementations
    assert (
        open(native_path, "rb").read() == open(python_path, "rb").read()
    )

    for reader_cls in (RecordIOReader, NativeRecordIOReader):
        r = reader_cls(native_path)
        assert len(r) == len(records)
        got = [bytes(r.read(i, validate=True)) for i in range(len(r))]
        assert got == records
        r.close()


def test_native_writer_abort_leaves_rejectable_file(tmp_path):
    """An exception mid-write must NOT finalize: the tail-less file is
    rejected by both readers instead of serving a partial index."""
    from elasticdl_tpu.data.recordio import RecordIOReader
    from elasticdl_tpu.native import NativeRecordIOWriter, native_lib

    if native_lib() is None:
        pytest.skip("native library not built")

    path = str(tmp_path / "torn.edlr")
    with pytest.raises(RuntimeError):
        with NativeRecordIOWriter(path) as w:
            w.write(b"only record")
            raise RuntimeError("boom")
    with pytest.raises(ValueError):
        RecordIOReader(path)


def test_create_recordio_factory(tmp_path):
    from elasticdl_tpu.data.recordio import create_recordio, open_recordio
    from elasticdl_tpu.native import native_lib

    path = str(tmp_path / "f.edlr")
    with create_recordio(path) as w:
        w.write(b"one")
        w.write(b"two")
    r = open_recordio(path)
    assert [bytes(r.read(i)) for i in range(len(r))] == [b"one", b"two"]
    r.close()
    if native_lib() is not None:
        from elasticdl_tpu.native import NativeRecordIOWriter

        assert isinstance(create_recordio(path + "2"), NativeRecordIOWriter)
