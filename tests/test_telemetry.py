"""Telemetry-plane tests (docs/observability.md).

Covers the metrics registry (label cardinality bound, histogram bucket
edges, concurrent increments — this suite runs under EDL_LOCKTRACE=1 in
scripts/check.sh), the Prometheus text exposition (golden parse), the
JSONL event log (monotonic ids across a simulated resize + task
requeue), the dispatcher's task-lifecycle tracing, the worker
snapshot -> master aggregation path, the /metrics HTTP endpoint, the
RPC-layer instrumentation, the TensorBoard export, and the step_timer
percentile fix.
"""

import json
import threading
import time
import urllib.request

import pytest

from elasticdl_tpu.common.constants import TaskType
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.master.telemetry import (
    JobTelemetry,
    TelemetryHTTPServer,
    TelemetryTBExporter,
)
from elasticdl_tpu.utils import profiling
from elasticdl_tpu.utils.profiling import (
    EventLog,
    MetricsRegistry,
    step_timer,
)
from elasticdl_tpu.worker.telemetry import WorkerTelemetry


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------


def test_counter_gauge_basicss():
    r = MetricsRegistry()
    c = r.counter("edl_t_total", "help", labels=("method",))
    c.inc(method="a")
    c.inc(2, method="a")
    c.inc(method="b")
    assert c.value(method="a") == 3
    assert c.value(method="b") == 1
    g = r.gauge("edl_t_depth")
    g.set(5)
    g.inc(2)
    assert g.value() == 7
    # re-registration returns the same family; mismatched shape refuses
    assert r.counter("edl_t_total", labels=("method",)) is c
    with pytest.raises(ValueError):
        r.counter("edl_t_total", labels=("other",))
    with pytest.raises(ValueError):
        r.gauge("edl_t_total")


def test_histogram_bucket_edges_are_le_inclusive():
    r = MetricsRegistry()
    h = r.histogram("edl_t_lat", buckets=(0.01, 0.1, 1.0))
    # exactly-on-edge observations land IN that bucket (prometheus le)
    for v in (0.01, 0.005, 0.1, 0.5, 1.0, 3.0):
        h.observe(v)
    buckets, total, count = h.data()
    assert buckets == [2, 1, 2, 1]  # <=0.01, <=0.1, <=1.0, +Inf
    assert count == 6
    assert total == pytest.approx(sum((0.01, 0.005, 0.1, 0.5, 1.0, 3.0)))
    # exposition buckets are CUMULATIVE
    text = r.prometheus_text()
    assert 'edl_t_lat_bucket{le="0.01"} 2' in text
    assert 'edl_t_lat_bucket{le="0.1"} 3' in text
    assert 'edl_t_lat_bucket{le="1"} 5' in text
    assert 'edl_t_lat_bucket{le="+Inf"} 6' in text
    assert "edl_t_lat_count 6" in text


def test_label_cardinality_is_bounded():
    r = MetricsRegistry()
    c = r.counter("edl_t_total", labels=("id",))
    for i in range(MetricsRegistry.MAX_SERIES + 50):
        c.inc(id="row-%d" % i)
    # the runaway label collapsed into the overflow series
    assert c.series_count() <= MetricsRegistry.MAX_SERIES + 1
    from elasticdl_tpu.utils.profiling import _Metric

    assert c.value(id=_Metric.OVERFLOW) == 50
    # existing series keep incrementing normally after the overflow
    c.inc(5, id="row-0")
    assert c.value(id="row-0") == 6


def test_concurrent_increments_are_exact():
    r = MetricsRegistry()
    c = r.counter("edl_t_total", labels=("who",))
    h = r.histogram("edl_t_lat", buckets=(0.5,))
    n_threads, per_thread = 8, 500

    def work(i):
        for _ in range(per_thread):
            c.inc(who="w%d" % (i % 2))
            h.observe(0.1)

    threads = [
        threading.Thread(target=work, args=(i,)) for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(who="w0") + c.value(who="w1") == n_threads * per_thread
    _, _, count = h.data()
    assert count == n_threads * per_thread


def test_metrics_disabled_is_a_noop():
    r = MetricsRegistry()
    c = r.counter("edl_t_total")
    profiling.set_metrics_enabled(False)
    try:
        c.inc(5)
        assert c.value() == 0
    finally:
        profiling.set_metrics_enabled(True)
    c.inc(1)
    assert c.value() == 1


# ---------------------------------------------------------------------------
# prometheus exposition: golden parse
# ---------------------------------------------------------------------------


def _parse_prometheus(text):
    """Minimal 0.0.4 parser: {name: {frozenset(label items): value}},
    plus the TYPE map. Raises on malformed sample lines, so the test
    doubles as a format check."""
    import re

    types, samples = {}, {}
    sample_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? ([^ ]+)$"
    )
    label_re = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        m = sample_re.match(line)
        assert m, "malformed sample line: %r" % line
        name, labels, value = m.groups()
        parsed = frozenset(label_re.findall(labels or ""))
        samples.setdefault(name, {})[parsed] = float(value)
    return types, samples


def test_prometheus_exposition_golden_parse():
    r = MetricsRegistry()
    c = r.counter("edl_rpc_errors_total", "errors", labels=("method", "code"))
    c.inc(3, method="get_task", code="UNAVAILABLE")
    g = r.gauge("edl_queue_depth", labels=("queue",))
    g.set(7, queue="todo")
    h = r.histogram("edl_lat_seconds", labels=("m",), buckets=(0.1,))
    h.observe(0.05, m='we"ird\nname')  # exercises label escaping
    r.register_collector(lambda: [("edl_live", {"k": "v"}, 1.5)])
    types, samples = _parse_prometheus(r.prometheus_text())
    assert types["edl_rpc_errors_total"] == "counter"
    assert types["edl_queue_depth"] == "gauge"
    assert types["edl_lat_seconds"] == "histogram"
    assert (
        samples["edl_rpc_errors_total"][
            frozenset(
                {("method", "get_task"), ("code", "UNAVAILABLE")}
            )
        ]
        == 3
    )
    assert samples["edl_queue_depth"][frozenset({("queue", "todo")})] == 7
    assert samples["edl_live"][frozenset({("k", "v")})] == 1.5
    # the escaped label round-trips through the parser
    (key,) = samples["edl_lat_seconds_count"].keys()
    assert ("m", 'we\\"ird\\nname') in key


def test_counters_shim_bridges_into_the_default_registry():
    profiling.counters.inc("telemetry_test/bridge", 4)
    try:
        text = profiling.metrics.prometheus_text()
        assert 'edl_counter{name="telemetry_test/bridge"} 4' in text
    finally:
        profiling.counters.reset("telemetry_test/")


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------


def test_event_log_monotonic_ids_and_jsonl_sink(tmp_path):
    log = EventLog()
    path = str(tmp_path / "events.jsonl")
    log.attach_file(path)
    log.emit("resize_begin", epoch=1, world_size=4)
    log.emit("task_requeued", task_id=7, trace_id="t000007")
    log.emit("resize_end", epoch=1, compile_phase="cache_miss")
    lines = [
        json.loads(l)
        for l in open(path, encoding="utf-8").read().splitlines()
    ]
    assert [e["kind"] for e in lines] == [
        "resize_begin",
        "task_requeued",
        "resize_end",
    ]
    ids = [e["id"] for e in lines]
    assert ids == sorted(ids) and len(set(ids)) == 3
    assert lines[1]["trace_id"] == "t000007"
    log.close_file()


def test_event_log_pending_drain_and_ingest_do_not_loop():
    log = EventLog()
    log.emit("ps_shard_failure", addr="x:1")
    shipped = log.drain_pending()
    assert [e["kind"] for e in shipped] == ["ps_shard_failure"]
    assert log.drain_pending() == []  # drained exactly once
    # master-side re-log: new ids, provenance kept, and NOT re-shipped
    log.ingest(shipped, worker="3")
    assert log.drain_pending() == []
    tail = log.tail(10)
    assert tail[-1]["kind"] == "ps_shard_failure"
    assert tail[-1]["worker"] == "3"
    assert tail[-1]["src_id"] == shipped[0]["id"]
    assert tail[-1]["id"] > shipped[0]["id"]


# ---------------------------------------------------------------------------
# dispatcher: trace ids, timeline events, queue depth
# ---------------------------------------------------------------------------


def _dispatcher(records=8, per_task=2):
    return TaskDispatcher({"f": (0, records)}, {}, {}, per_task, 1)


def test_dispatcher_stamps_stable_trace_ids_across_requeue():
    profiling.events.reset()
    d = _dispatcher()
    task_id, task = d.get(worker_id=0)
    trace = task.extended_config["trace_id"]
    assert trace.startswith("t")
    d.report(task_id, False)  # requeue
    # the SAME logical task redispatches under the same trace id with a
    # bumped attempt
    seen = {}
    for _ in range(d.queue_depths()["todo"]):
        tid, t = d.get(worker_id=1)
        seen[t.extended_config["trace_id"]] = (
            tid,
            t.extended_config["_attempt"],
        )
    assert trace in seen
    assert seen[trace][1] == 1  # second attempt
    events = profiling.events.tail(10)
    requeues = [e for e in events if e["kind"] == "task_requeued"]
    assert len(requeues) == 1
    assert requeues[0]["trace_id"] == trace
    assert requeues[0]["attempt"] == 0
    assert requeues[0]["dispatch_to_report_s"] >= 0


def test_event_ordering_across_simulated_resize_plus_requeue(tmp_path):
    """The JSONL log interleaves a resize with a task requeue in emit
    order, ids strictly increasing (the satellite's ordering pin)."""
    profiling.events.reset()
    path = str(tmp_path / "events.jsonl")
    profiling.events.attach_file(path)
    try:
        d = _dispatcher()
        t1, _ = d.get(worker_id=0)
        profiling.events.emit(
            "resize_begin", epoch=2, world_size=3, _ship=False
        )
        d.report(t1, False)  # requeue lands INSIDE the resize window
        profiling.events.emit(
            "resize_end",
            epoch=2,
            compile_phase="cache_hit",
            _ship=False,
        )
        t2, _ = d.get(worker_id=1)
        d.report(t2, True)
        lines = [
            json.loads(l)
            for l in open(path, encoding="utf-8").read().splitlines()
        ]
        kinds = [e["kind"] for e in lines]
        assert kinds == [
            "resize_begin",
            "task_requeued",
            "resize_end",
            "task_done",
        ]
        ids = [e["id"] for e in lines]
        assert all(b > a for a, b in zip(ids, ids[1:]))
    finally:
        profiling.events.reset()


def test_queue_depths_track_dispatch_lifecycle():
    d = _dispatcher(records=8, per_task=2)
    assert d.queue_depths() == {"todo": 4, "doing": 0, "eval_todo": 0}
    tid, _ = d.get(worker_id=0)
    assert d.queue_depths()["doing"] == 1
    assert d.queue_depths()["todo"] == 3
    d.report(tid, True)
    assert d.queue_depths()["doing"] == 0


def test_timeline_event_carries_worker_consume_time():
    profiling.events.reset()
    d = _dispatcher()
    tid, _ = d.get(worker_id=5)
    d.report(tid, True, exec_counters={"consume_s": 0.25})
    done = [
        e for e in profiling.events.tail(5) if e["kind"] == "task_done"
    ]
    assert done and done[0]["consume_s"] == 0.25
    assert done[0]["worker_id"] == 5


# ---------------------------------------------------------------------------
# worker snapshot -> master aggregation -> endpoint
# ---------------------------------------------------------------------------


def test_worker_telemetry_snapshot_rates_and_interval_gate():
    from elasticdl_tpu.data.input_stats import InputPlaneStats

    stats = InputPlaneStats()
    wt = WorkerTelemetry(3, stats=stats, interval_s=3600.0)
    wt.on_batch(16)
    wt.on_batch(16)
    assert wt.maybe_snapshot() is None  # interval not elapsed
    stats.add("consumer_starved_s", 0.5)
    snap = wt.maybe_snapshot(force=True)
    assert snap["worker_id"] == 3
    assert snap["examples_total"] == 32
    assert snap["steps_total"] == 2
    assert snap["examples_per_sec"] > 0
    assert snap["input"]["consumer_starved_s"] == pytest.approx(0.5)
    assert 0.0 <= snap["consumer_starved_ratio"] <= 1.0


def test_job_telemetry_aggregates_and_serves_metrics_endpoint():
    profiling.events.reset()
    d = _dispatcher()
    registry = MetricsRegistry()
    jt = JobTelemetry(task_dispatcher=d, registry=registry)
    jt.ingest(
        {
            "worker_id": 0,
            "examples_per_sec": 100.0,
            "steps_per_sec": 5.0,
            "input": {"consumer_starved_s": 0.1, "read_s": 0.2},
            "consumer_starved_ratio": 0.05,
            "hot_row_hit_rate": 0.9,
            "events": [
                {"kind": "ps_shard_failure", "id": 9, "addr": "x:1"}
            ],
        }
    )
    jt.ingest({"worker_id": 1, "examples_per_sec": 50.0})
    server = TelemetryHTTPServer(jt, port=0)
    try:
        body = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % server.port, timeout=10
        ).read().decode("utf-8")
        _, samples = _parse_prometheus(body)
        per_worker = samples["edl_worker_examples_per_sec"]
        assert per_worker[frozenset({("worker", "0")})] == 100.0
        assert per_worker[frozenset({("worker", "1")})] == 50.0
        assert (
            samples["edl_job_examples_per_sec"][frozenset()] == 150.0
        )
        # live queue depth from the dispatcher collector
        assert (
            samples["edl_task_queue_depth"][
                frozenset({("queue", "todo")})
            ]
            == 4
        )
        assert (
            samples["edl_worker_hot_row_hit_rate"][
                frozenset({("worker", "0")})
            ]
            == 0.9
        )
        # shipped worker event was re-logged with the worker label
        ev_body = urllib.request.urlopen(
            "http://127.0.0.1:%d/events" % server.port, timeout=10
        ).read().decode("utf-8")
        events = [
            json.loads(l) for l in ev_body.splitlines() if l.strip()
        ]
        failures = [
            e for e in events if e["kind"] == "ps_shard_failure"
        ]
        assert failures and failures[0]["worker"] == "0"
        assert failures[0]["src_id"] == 9
    finally:
        server.close()
        profiling.events.reset()


def test_servicer_report_telemetry_path():
    import optax

    from elasticdl_tpu.master.servicer import MasterServicer

    d = _dispatcher()
    registry = MetricsRegistry()
    jt = JobTelemetry(task_dispatcher=d, registry=registry)
    servicer = MasterServicer(
        1, 16, optax.sgd(0.1), d, telemetry=jt
    )
    servicer.report_telemetry(
        {"worker_id": 7, "examples_per_sec": 42.0}
    )
    assert jt.worker_snapshots()["7"]["examples_per_sec"] == 42.0
    text = jt.prometheus_text()
    assert 'edl_worker_examples_per_sec{worker="7"} 42' in text


# ---------------------------------------------------------------------------
# RPC-layer instrumentation (client + servicer side)
# ---------------------------------------------------------------------------


def test_rpc_layer_records_client_and_server_histograms():
    from elasticdl_tpu.rpc.core import Client, serve
    from elasticdl_tpu.utils.profiling import (
        instrument_service_methods,
    )

    methods = instrument_service_methods(
        {"echo": lambda req: {"x": req.get("x", 0) + 1}},
        role="testsrv",
    )
    server = serve(methods, 0)
    client = Client("localhost:%d" % server._edl_port)
    try:
        before = profiling.metrics.histogram(
            "edl_rpc_client_latency_seconds", labels=("method",)
        ).data(method="echo")
        n_before = before[2] if before else 0
        assert client.call("echo", x=41)["x"] == 42
        after = profiling.metrics.histogram(
            "edl_rpc_client_latency_seconds", labels=("method",)
        ).data(method="echo")
        assert after[2] == n_before + 1
        srv = profiling.metrics.histogram(
            "edl_rpc_server_latency_seconds", labels=("role", "method")
        ).data(role="testsrv", method="echo")
        assert srv is not None and srv[2] >= 1
    finally:
        client.close()
        server.stop(grace=None)


def test_rpc_client_error_counter_on_dead_endpoint():
    import grpc

    from elasticdl_tpu.rpc.core import Client

    errors = profiling.metrics.counter(
        "edl_rpc_client_errors_total", labels=("method", "code")
    )
    before = errors.value(method="nope", code="UNAVAILABLE")
    client = Client("localhost:1", deadline_s=2.0)  # nothing listens
    try:
        with pytest.raises(grpc.RpcError):
            client.call("nope")
    finally:
        client.close()
    assert errors.value(method="nope", code="UNAVAILABLE") == before + 1


# ---------------------------------------------------------------------------
# TensorBoard export
# ---------------------------------------------------------------------------


def test_telemetry_tb_exporter_round_trip(tmp_path):
    import glob

    from elasticdl_tpu.common.tb_events import read_events

    registry = MetricsRegistry()
    registry.counter("edl_t_total").inc(3)
    h = registry.histogram("edl_t_lat", buckets=(0.1,))
    h.observe(0.05)
    h.observe(0.15)
    exporter = TelemetryTBExporter(
        str(tmp_path), registry=registry, interval_s=3600.0, step_fn=lambda: 7
    )
    try:
        exporter.flush()
    finally:
        exporter.close()
    (path,) = glob.glob(str(tmp_path / "*.telemetry"))
    scalars = {}
    for _, step, pairs in read_events(path):
        for tag, value in pairs:
            scalars[tag] = (step, value)
    assert scalars["telemetry/edl_t_total"] == (7, 3.0)
    assert scalars["telemetry/edl_t_lat/count"][1] == 2.0
    assert scalars["telemetry/edl_t_lat/mean"][1] == pytest.approx(
        0.1, rel=1e-5
    )


def test_telemetry_tb_exporter_concurrent_flush_exactness(tmp_path):
    """edlint R8 regression (static lockset finding): the exporter
    thread and close()'s final flush both run flush(); the _flushes
    bump must not lose updates and two flushes must not interleave
    add_scalars. Serialized flushes make this exact."""
    import threading

    registry = MetricsRegistry()
    registry.counter("edl_t_total").inc(1)
    exporter = TelemetryTBExporter(
        str(tmp_path), registry=registry, interval_s=3600.0
    )
    n, per = 8, 5
    try:
        def pound():
            for _ in range(per):
                exporter.flush()

        threads = [threading.Thread(target=pound) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert exporter._flushes == n * per
    finally:
        exporter.close()
    # close() ran one final flush after the join
    assert exporter._flushes == n * per + 1


# ---------------------------------------------------------------------------
# step_timer percentile fix
# ---------------------------------------------------------------------------


def test_step_timer_nearest_rank_percentiles():
    t = step_timer()
    # inject a known sample set: 1..4 (seconds)
    t._times = [4.0, 1.0, 3.0, 2.0]
    s = t.stats()
    # nearest-rank: p50 of [1,2,3,4] is the 2nd value, NOT the 3rd
    # (the old n//2 indexing returned 3.0 here)
    assert s["p50_ms"] == 2000.0
    assert s["p90_ms"] == 4000.0
    assert s["p99_ms"] == 4000.0
    assert s["max_ms"] == 4000.0
    # n=2: the old code called the MAX the median
    t._times = [1.0, 9.0]
    assert t.stats()["p50_ms"] == 1000.0


def test_worker_ships_snapshot_through_stub():
    class _Stub:
        def __init__(self):
            self.snaps = []

        def report_telemetry(self, snap):
            self.snaps.append(snap)

    wt = WorkerTelemetry(2, interval_s=0.001)
    wt.on_batch(8)
    time.sleep(0.005)
    stub = _Stub()
    assert wt.ship(stub)
    assert stub.snaps and stub.snaps[0]["worker_id"] == 2
    # a stub without the method is silently skipped (bare test fixtures)
    assert not wt.ship(object(), force=True)


def test_failed_ship_requeues_drained_events():
    class _DownStub:
        def report_telemetry(self, snap):
            raise RuntimeError("master unreachable")

    profiling.events.reset()
    profiling.events.emit("ps_shard_failure", addr="x:1")
    wt = WorkerTelemetry(4, interval_s=0.001)
    time.sleep(0.005)
    assert not wt.ship(_DownStub())
    # the drained event went back on the pending buffer and rides the
    # next successful snapshot
    class _UpStub:
        def __init__(self):
            self.snaps = []

        def report_telemetry(self, snap):
            self.snaps.append(snap)

    up = _UpStub()
    assert wt.ship(up, force=True)
    kinds = [e["kind"] for e in up.snaps[0].get("events", [])]
    assert "ps_shard_failure" in kinds
