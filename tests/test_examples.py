"""Model-zoo smoke/convergence tests against the in-process master.

Parity: reference tests/example_test.py — each zoo model runs a full
train+eval job in-process (mnist/cifar10/deepfm/resnet50), sync and async.
"""

import pytest

from tests.test_utils import (
    MODEL_ZOO_PATH,
    DatasetName,
    distributed_train_and_evaluate,
)


def test_mnist_subclass_train():
    version = distributed_train_and_evaluate(
        (28, 28),
        MODEL_ZOO_PATH,
        "mnist_subclass.mnist_subclass.CustomModel",
        training=True,
    )
    assert version == 4


def test_cifar10_functional_train():
    version = distributed_train_and_evaluate(
        (32, 32, 3),
        MODEL_ZOO_PATH,
        "cifar10_functional_api.cifar10_functional_api.custom_model",
        training=True,
    )
    assert version == 4


def test_cifar10_subclass_train():
    version = distributed_train_and_evaluate(
        (32, 32, 3),
        MODEL_ZOO_PATH,
        "cifar10_subclass.cifar10_subclass.CustomModel",
        training=True,
    )
    assert version == 4


def test_deepfm_functional_train():
    # FRAPPE fixture holds one batch of records: sync mode with
    # grads_to_wait=2 therefore never applies (version stays 0), matching
    # the reference fixture sizing (test_utils.py:188-191)
    version = distributed_train_and_evaluate(
        10,
        MODEL_ZOO_PATH,
        "deepfm_functional_api.deepfm_functional_api.custom_model",
        training=True,
        dataset_name=DatasetName.FRAPPE,
        use_async=True,
    )
    assert version == 1


def test_deepfm_edl_embedding_train():
    """Elastic-embedding DeepFM: rows pulled from the master store, sparse
    gradients applied by the OptimizerWrapper (reference
    example_test.py deepfm_edl flavour)."""
    version = distributed_train_and_evaluate(
        10,
        MODEL_ZOO_PATH,
        "deepfm_edl_embedding.deepfm_edl_embedding.custom_model",
        training=True,
        dataset_name=DatasetName.FRAPPE,
        use_async=True,
    )
    assert version == 1


@pytest.mark.slow
def test_resnet50_subclass_train():
    version = distributed_train_and_evaluate(
        (32, 32, 3),
        MODEL_ZOO_PATH,
        "resnet50_subclass.resnet50_subclass.CustomModel",
        training=True,
        dataset_name=DatasetName.IMAGENET,
        use_async=True,
    )
    assert version == 1
