"""Ring attention correctness on the virtual 8-device mesh.

The sequence-sharded ring computation must match full-sequence attention
exactly (same softmax, different blocking), causal and non-causal, and
gradients must flow through the shard_map.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.ring_attention import (
    make_ring_attention,
    reference_attention,
)


def _qkv(b=2, l=32, h=4, d=8, seed=0):
    rng = np.random.default_rng(seed)
    shape = (b, l, h, d)
    return (
        rng.standard_normal(shape).astype(np.float32),
        rng.standard_normal(shape).astype(np.float32),
        rng.standard_normal(shape).astype(np.float32),
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_full_attention(causal):
    mesh = create_mesh({"seq": 8}, axis_names=("seq",))
    q, k, v = _qkv()
    ring = make_ring_attention(mesh, "seq", causal=causal)
    with mesh:
        got = np.asarray(jax.jit(ring)(q, k, v))
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_gradients_match():
    mesh = create_mesh({"seq": 8}, axis_names=("seq",))
    q, k, v = _qkv(l=16)
    ring = make_ring_attention(mesh, "seq", causal=True)

    def ring_loss(q, k, v):
        return (ring(q, k, v) ** 2).sum()

    def ref_loss(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    with mesh:
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-4
        )


def test_ring_with_data_parallel_axis():
    """seq parallelism composes with a data axis on the same mesh."""
    mesh = create_mesh(
        {"data": 2, "seq": 4}, axis_names=("data", "seq")
    )
    q, k, v = _qkv(b=4, l=16)
    ring = make_ring_attention(mesh, "seq", causal=False)
    with mesh:
        got = np.asarray(jax.jit(ring)(q, k, v))
    want = np.asarray(reference_attention(q, k, v))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_gradients_match_reference(causal):
    """The fused per-block backward (K/V re-rotation against the global
    lse) must reproduce dense-attention gradients."""
    mesh = create_mesh({"seq": 8}, axis_names=("seq",))
    q, k, v = _qkv()
    ring = make_ring_attention(mesh, "seq", causal=causal, use_flash=True)

    def loss_ring(q, k, v):
        return (ring(q, k, v) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=causal) ** 2).sum()

    with mesh:
        g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-4
        )


def test_ring_flash_and_xla_paths_agree():
    mesh = create_mesh({"seq": 8}, axis_names=("seq",))
    q, k, v = _qkv(seed=3)
    fused = make_ring_attention(mesh, "seq", causal=True, use_flash=True)
    xla = make_ring_attention(mesh, "seq", causal=True, use_flash=False)
    with mesh:
        a = np.asarray(jax.jit(fused)(q, k, v))
        b = np.asarray(jax.jit(xla)(q, k, v))
    np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)
