"""Fault-injection callbacks for the in-process master.

Parity: reference tests/test_call_back.py — callbacks fire at named stages
inside InProcessMaster; used to force gradient rejection/retry and to
assert worker/master weight sync at boundaries (reference
tests/worker_test.py:46-101).
"""

import numpy as np

from elasticdl_tpu.common.tensor import pytree_to_named_arrays

ON_REPORT_GRADIENT_BEGIN = "on_report_gradient_begin"
ON_REPORT_EVALUATION_METRICS_BEGIN = "on_report_evaluation_metrics_begin"


class BaseCallback:
    """A callback invoked at given stages of master RPC processing."""

    def __init__(self, master, worker, call_times=None):
        self._master = master
        self._worker = worker
        self.call_times = call_times or []

    def __call__(self):
        raise NotImplementedError


class CheckRetryCallback(BaseCallback):
    """Bumps the master version mid-flight to force rejection + retry.

    Parity: reference tests/worker_test.py:46-66.
    """

    def __init__(self, master, worker):
        super().__init__(
            master, worker, call_times=[ON_REPORT_GRADIENT_BEGIN]
        )
        self._retry_injected = False

    def __call__(self):
        if not self._retry_injected and self._master._version >= 2:
            self._retry_injected = True
            self._master._version += 1


class CheckWorkerModelCallback(BaseCallback):
    """Asserts worker-local weights equal master weights at sync points.

    Parity: reference tests/worker_test.py:69-101.
    """

    def __init__(self, master, worker):
        super().__init__(
            master,
            worker,
            call_times=[ON_REPORT_EVALUATION_METRICS_BEGIN],
        )
        self.checks_run = 0

    def __call__(self):
        if self._worker._model_version != self._master._version:
            # worker evaluates a pinned (checkpointed) snapshot; only
            # compare when it is in sync with the live model
            return
        _, master_named = self._master._get_model_no_lock()
        worker_named = pytree_to_named_arrays(self._worker._params)
        assert set(master_named) == set(worker_named)
        for name in master_named:
            np.testing.assert_allclose(
                master_named[name],
                np.asarray(worker_named[name]),
                rtol=1e-5,
                atol=1e-5,
            )
        self.checks_run += 1
