"""rpc/core deadline + retry/backoff coverage, over real loopback gRPC
(tests/fake_ps.serve_slow_ps) and injected fake stubs.

Pins the split the overlap design relies on: the PS DATA plane is
deadline-bounded (a dead pod fails a call in ~``--rpc_deadline_s``, no
indefinite hang), while CONTROL-plane master RPCs keep their historical
block-forever channel (a worker parked on ``get_task`` must wait).
"""

import time

import numpy as np
import pytest

from elasticdl_tpu.rpc.core import Client
from elasticdl_tpu.worker.ps_client import BoundPS, PSClient, PSRpcError
from tests.fake_ps import free_port, serve_slow_ps

grpc = pytest.importorskip("grpc")


@pytest.fixture
def slow_ps():
    server, addr = serve_slow_ps(delay_s=5.0)
    yield addr
    server.stop(None)


def test_deadline_expires_within_bound(slow_ps):
    """A hung handler fails with DEADLINE_EXCEEDED in ~deadline_s,
    surfaced as PSRpcError — a RuntimeError, so the worker's minibatch
    machinery reports a failed task instead of dying."""
    ps = BoundPS(slow_ps, deadline_s=0.5)
    t0 = time.monotonic()
    with pytest.raises(PSRpcError) as err:
        ps.pull_variable({})
    elapsed = time.monotonic() - t0
    assert isinstance(err.value, RuntimeError)
    assert err.value.code == grpc.StatusCode.DEADLINE_EXCEEDED
    assert elapsed < 3.0, "deadline not honored: %.1fs" % elapsed


def test_deadline_expiry_is_not_retried(slow_ps):
    """retries only cover UNAVAILABLE: with retries=3 a deadline expiry
    still surfaces in ~one deadline, not deadline * 4."""
    ps = BoundPS(slow_ps, deadline_s=0.5, retries=3)
    t0 = time.monotonic()
    with pytest.raises(PSRpcError):
        ps.pull_variable({})
    assert time.monotonic() - t0 < 2.0


def test_no_deadline_keeps_blocking_semantics(slow_ps):
    """deadline_s=None (the control-plane default) waits the handler
    out — the historical behavior, preserved."""
    ps = BoundPS(slow_ps)  # no deadline
    t0 = time.monotonic()
    resp = ps.pull_variable({})
    assert resp["model_init_status"]
    assert time.monotonic() - t0 >= 4.5


def test_dead_shard_fails_fanout_within_deadline():
    """One live shard + one shard killed mid-job: the next fan-out call
    errors within the deadline envelope instead of hanging."""
    live_server, live_addr = serve_slow_ps(delay_s=0.0)
    dead_server, dead_addr = serve_slow_ps(delay_s=0.0)
    try:
        client = PSClient(
            [
                BoundPS(a, deadline_s=1.0, retries=1, backoff_s=0.05)
                for a in (live_addr, dead_addr)
            ],
            fanout=True,
        )
        rows = client.pull_embedding_vectors("emb", np.arange(4))
        assert rows.shape == (4, 4)
        dead_server.stop(None)  # shard 1 dies
        t0 = time.monotonic()
        with pytest.raises(PSRpcError):
            client.pull_embedding_vectors("emb", np.arange(4))
        # UNAVAILABLE fails fast; the bound is deadline + one backoff
        assert time.monotonic() - t0 < 3.0
        client.close()
    finally:
        live_server.stop(None)


def test_async_push_surfaces_dead_shard_on_drain():
    """A shard killed while a double-buffered push is in flight raises
    at the drain (the worker's reconcile point), within the deadline."""
    server0, addr0 = serve_slow_ps(delay_s=0.0)
    server1, addr1 = serve_slow_ps(delay_s=0.0)
    try:
        client = PSClient(
            [
                BoundPS(a, deadline_s=1.0, retries=0)
                for a in (addr0, addr1)
            ],
            fanout=True,
            push_inflight=1,
        )
        grads = {"w": np.ones((2,), np.float32)}
        accepted, _ = client.push_gradient(grads, [], 0)
        assert accepted
        client.drain()
        server1.stop(None)  # dies before the next push's wire time
        client.push_gradient(grads, [], 1)  # optimistic non-blocking
        t0 = time.monotonic()
        with pytest.raises(PSRpcError):
            client.drain()
        assert time.monotonic() - t0 < 3.0
        client.close()
    finally:
        server0.stop(None)


class _FakeUnavailable(grpc.RpcError):
    def code(self):
        return grpc.StatusCode.UNAVAILABLE


def test_unavailable_retries_with_doubling_backoff():
    """UNAVAILABLE retries `retries` times with doubling backoff, then
    surfaces; sleeps are injectable so this is timing-free."""
    client = Client(
        "localhost:%d" % free_port(), retries=2, backoff_s=0.1
    )
    sleeps = []
    client._sleep = sleeps.append
    calls = []

    def stub(request, timeout=None):
        calls.append(timeout)
        raise _FakeUnavailable()

    client._stubs["pull_variable"] = stub
    with pytest.raises(grpc.RpcError):
        client.call("pull_variable")
    assert len(calls) == 3  # initial + 2 retries
    assert sleeps == [0.1, 0.2]


def test_push_gradient_is_never_retried():
    """push_gradient is non-idempotent (an async PS applies on
    receipt): a post-apply connection drop must surface, not resend —
    resending would apply the same gradient twice."""
    from elasticdl_tpu.rpc.core import pack_message

    ps = BoundPS(
        "localhost:%d" % free_port(), retries=3, backoff_s=0.0
    )
    ps._client._sleep = lambda s: None
    pushes, pulls = [], []

    def push_stub(request, timeout=None):
        pushes.append(timeout)
        raise _FakeUnavailable()

    def pull_stub(request, timeout=None):
        pulls.append(timeout)
        if len(pulls) < 2:
            raise _FakeUnavailable()
        return pack_message({"ok": True})

    ps._client._stubs["push_gradient"] = push_stub
    ps._client._stubs["pull_variable"] = pull_stub
    with pytest.raises(PSRpcError):
        ps.push_gradient({"model_version": 0})
    assert len(pushes) == 1  # no resend of a maybe-applied gradient
    assert ps.pull_variable({})["ok"] is True
    assert len(pulls) == 2  # idempotent pulls still retry


def test_unavailable_retry_recovers():
    """A transient UNAVAILABLE (restarting pod) succeeds on retry."""
    from elasticdl_tpu.rpc.core import pack_message

    client = Client("localhost:%d" % free_port(), retries=2, backoff_s=0.0)
    client._sleep = lambda s: None
    attempts = []

    def stub(request, timeout=None):
        attempts.append(timeout)
        if len(attempts) < 3:
            raise _FakeUnavailable()
        return pack_message({"ok": True})

    client._stubs["push_gradient"] = stub
    assert client.call("push_gradient")["ok"] is True
    assert len(attempts) == 3


def test_deadline_passed_to_stub():
    client = Client("localhost:%d" % free_port(), deadline_s=7.5)
    seen = []

    def stub(request, timeout=None):
        from elasticdl_tpu.rpc.core import pack_message

        seen.append(timeout)
        return pack_message({})

    client._stubs["m"] = stub
    client.call("m")
    assert seen == [7.5]
    # deadline_s=0 means "disabled", i.e. block forever
    assert Client("localhost:1", deadline_s=0)._deadline_s is None


def test_master_control_plane_stays_blocking():
    """MasterClient must NOT pick up data-plane deadlines: get_task
    parks legitimately while the master is busy/forming. All master
    traffic routes through the audited failover wrapper
    (docs/master_recovery.md), whose INNER channel stays blocking and
    retry-free — outage retry is the wrapper's own loop, opt-in via
    failover_s and UNAVAILABLE-only."""
    from elasticdl_tpu.master.rpc_service import MasterClient
    from elasticdl_tpu.rpc.failover import MasterFailoverChannel

    mc = MasterClient("localhost:%d" % free_port())
    assert isinstance(mc._client, MasterFailoverChannel)
    assert mc._client._client._deadline_s is None
    assert mc._client._client._retries == 0
    # failover is opt-in: the default channel is a pure pass-through
    assert mc._client.outage_budget_s == 0.0
    # while the PS data-plane default wiring DOES bound its calls
    from elasticdl_tpu.common.args import parse_worker_args

    args = parse_worker_args(
        [
            "--worker_id", "0",
            "--job_type", "training",
            "--minibatch_size", "1",
            "--model_zoo", "z",
            "--model_def", "m.m.f",
        ]
    )
    assert args.rpc_deadline_s == 60.0
    assert args.rpc_retries == 2
    assert args.ps_fanout is True
    assert args.ps_push_inflight == 0
