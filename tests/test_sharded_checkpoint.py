"""Sharded checkpointing: per-shard files + manifest (SURVEY §7.1).

The reference gathers the whole model to one host and writes a single
protobuf blob; device-resident (vocab-sharded) state must checkpoint
without that gather and restore across *different* mesh shapes.
"""

import glob
import os

import jax
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.common.sharded_checkpoint import (
    ShardedCheckpointManager,
    load_sharded,
    load_sharded_to_host,
    save_sharded,
)
from elasticdl_tpu.parallel.mesh import create_mesh


def _sharded_tree(mesh, v=64, d=4, seed=0):
    rng = np.random.default_rng(seed)
    table = jax.device_put(
        rng.standard_normal((v, d)).astype(np.float32),
        NamedSharding(mesh, P("data", None)),
    )
    dense = jax.device_put(
        rng.standard_normal((8, 3)).astype(np.float32),
        NamedSharding(mesh, P()),
    )
    return {"emb": {"table": table}, "w": dense}


def test_roundtrip_preserves_values_and_never_writes_dense_table(tmp_path):
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    tree = _sharded_tree(mesh)
    save_sharded(str(tmp_path), tree, version=7)

    # the sharded table exists only as (V/8, D) per-shard files —
    # no file holds the dense (V, D) array
    table_files = glob.glob(str(tmp_path / "emb.table*.npy"))
    assert len(table_files) == 8
    for f in table_files:
        assert np.load(f).shape == (8, 4)
    # the replicated leaf is written exactly once
    assert len(glob.glob(str(tmp_path / "w*.npy"))) == 1

    shardings = jax.tree_util.tree_map(lambda a: a.sharding, tree)
    version, restored = load_sharded(str(tmp_path), shardings)
    assert version == 7
    for a, b in zip(
        jax.tree_util.tree_leaves(restored), jax.tree_util.tree_leaves(tree)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding.is_equivalent_to(b.sharding, a.ndim)


def test_restore_onto_different_mesh_shape(tmp_path):
    """The world changed between save and restore: shards re-slice."""
    mesh8 = create_mesh({"data": 8}, axis_names=("data",))
    tree = _sharded_tree(mesh8)
    save_sharded(str(tmp_path), tree, version=1)

    mesh4 = create_mesh(
        {"data": 4}, axis_names=("data",), devices=jax.devices()[:4]
    )
    shardings = {
        "emb": {"table": NamedSharding(mesh4, P("data", None))},
        "w": NamedSharding(mesh4, P()),
    }
    _, restored = load_sharded(str(tmp_path), shardings)
    np.testing.assert_array_equal(
        np.asarray(restored["emb"]["table"]),
        np.asarray(tree["emb"]["table"]),
    )
    assert len(restored["emb"]["table"].sharding.device_set) == 4


def test_host_restore_for_export(tmp_path):
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    tree = _sharded_tree(mesh)
    save_sharded(str(tmp_path), tree, version=3)
    version, host = load_sharded_to_host(str(tmp_path))
    assert version == 3
    np.testing.assert_array_equal(
        host["emb"]["table"], np.asarray(tree["emb"]["table"])
    )


def test_manager_ring_retention(tmp_path):
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    tree = _sharded_tree(mesh)
    mgr = ShardedCheckpointManager(str(tmp_path), 10, keep_max=2)
    assert mgr.need_to_checkpoint(10) and not mgr.need_to_checkpoint(11)
    for v in (10, 20, 30):
        mgr.save(tree, v)
    assert mgr.versions() == [20, 30]
    assert mgr.latest_dir().endswith("ckpt_v30")


def test_ring_eviction_holds_while_newer_version_is_torn(tmp_path):
    """Multi-writer window (advisor finding): with keep_max=1, rank 0
    must NOT evict the last fully-written version while the newest one is
    still missing a straggler rank's manifest — a kill in that window
    would leave nothing restorable."""
    import json
    import os

    mgr = ShardedCheckpointManager(str(tmp_path), 10, keep_max=1)
    mgr.set_expected_writers(2)

    def write_manifest(version, pid):
        d = mgr._dir_for(version)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "manifest-%d.json" % pid), "w") as f:
            json.dump({"version": version, "leaves": {}}, f)

    # v10 complete (both ranks); v20 torn (rank 1 still writing)
    write_manifest(10, 0)
    write_manifest(10, 1)
    write_manifest(20, 0)
    mgr._evict(2)
    assert mgr.versions() == [10, 20], "evicted the only complete version"

    # straggler lands: v20 complete -> v10 becomes evictable
    write_manifest(20, 1)
    mgr._evict(2)
    assert mgr.versions() == [20]

    # world GROWS to 4: a newer version with only the old world's count
    # of manifests is still torn — must not unlock eviction
    mgr.set_expected_writers(4)
    write_manifest(30, 0)
    write_manifest(30, 1)
    mgr._evict(4)
    assert mgr.versions() == [20, 30], "torn post-grow version evicted v20"
    write_manifest(30, 2)
    write_manifest(30, 3)
    mgr._evict(4)
    assert mgr.versions() == [30]

    # without expected_writers the conservative rule (newer must match
    # the victim's manifest count) gives the same protection
    mgr2 = ShardedCheckpointManager(str(tmp_path / "b"), 10, keep_max=1)

    def wm2(version, pid):
        d = mgr2._dir_for(version)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "manifest-%d.json" % pid), "w") as f:
            json.dump({"version": version, "leaves": {}}, f)

    wm2(10, 0)
    wm2(10, 1)
    wm2(20, 0)
    mgr2._evict(None)
    assert mgr2.versions() == [10, 20]
    wm2(20, 1)
    mgr2._evict(None)
    assert mgr2.versions() == [20]


def test_eviction_fallback_grow_tie(tmp_path, monkeypatch):
    """Reviewer-found tie: world grows 2->4 and set_expected_writers was
    never called. A torn 4-world version with as many manifests as the
    complete 2-world victim must not unlock eviction — in a multi-process
    jax world the process_count term is the bar."""
    import json

    from elasticdl_tpu.common import sharded_checkpoint as sc

    mgr = ShardedCheckpointManager(str(tmp_path), 10, keep_max=1)

    def wm(version, pid):
        d = mgr._dir_for(version)
        os.makedirs(d, exist_ok=True)
        with open(os.path.join(d, "manifest-%d.json" % pid), "w") as f:
            json.dump({"version": version, "leaves": {}}, f)

    wm(10, 0)
    wm(10, 1)  # complete under the old 2-process world
    wm(20, 0)
    wm(20, 1)  # torn: 2 of 4 manifests after the grow
    monkeypatch.setattr(sc.jax, "process_count", lambda: 4)
    mgr._evict(None)
    assert mgr.versions() == [10, 20], "grow-tie evicted the only complete version"
    wm(20, 2)
    wm(20, 3)
    mgr._evict(None)
    assert mgr.versions() == [20]


def test_async_save_snapshots_world_config_at_submit(tmp_path, monkeypatch):
    """edlint R8 regression (static lockset finding): the async-io write
    runs on the checkpoint writer thread, so an elastic resize landing
    between submit and write must NOT leak the NEW world's
    expected_writers into the in-flight eviction — the value travels
    with the snapshot it describes."""
    import threading

    import numpy as np

    from elasticdl_tpu.common import sharded_checkpoint as sc

    mgr = ShardedCheckpointManager(
        str(tmp_path), 10, keep_max=1, async_io=True
    )
    mgr.set_expected_writers(2)
    gate = threading.Event()
    evict_saw = []

    def slow_write(directory, snap, **kwargs):
        assert gate.wait(timeout=10.0), "test gate never released"

    monkeypatch.setattr(sc, "write_snapshot", slow_write)
    monkeypatch.setattr(
        mgr, "_evict", lambda expected: evict_saw.append(expected)
    )
    try:
        mgr.save({"w": np.zeros(2)}, 10)
        # the resize arrives while the write is still in flight
        mgr.set_expected_writers(8)
        gate.set()
        mgr.wait()
    finally:
        gate.set()
        mgr.close()
    assert evict_saw == [2], (
        "in-flight eviction saw the post-resize writer count: %r"
        % evict_saw
    )


def test_trainer_sharded_checkpoint_roundtrip(tmp_path):
    """AllReduceTrainer with an HBM-sharded deepfm: save, mutate, restore
    — exact state recovery including co-sharded optimizer slots."""
    from elasticdl_tpu.parallel.trainer import AllReduceTrainer
    from model_zoo.deepfm_edl_embedding import deepfm_edl_embedding as zoo

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    model = zoo.build_distributed_model(
        mesh, embedding_dim=8, fc_unit=8, vocab_size=96
    )
    trainer = AllReduceTrainer(
        model,
        zoo.loss,
        optax.adam(1e-2),
        mesh=mesh,
        param_specs=zoo.param_shardings(mesh),
    )
    rng = np.random.default_rng(0)
    feats = {"feature": rng.integers(0, 96, size=(16, 10)).astype(np.int64)}
    labels = rng.integers(0, 2, size=(16, 1)).astype(np.int64)
    with mesh:
        trainer.train_step(feats, labels)
        trainer.train_step(feats, labels)
    saved_params = jax.tree_util.tree_map(
        np.asarray, trainer.train_state.params
    )
    trainer.save_sharded(str(tmp_path))

    with mesh:
        trainer.train_step(feats, labels)  # diverge
    version = trainer.restore_sharded(str(tmp_path))
    assert version == 2
    assert trainer.version == 2
    for a, b in zip(
        jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, trainer.train_state.params)
        ),
        jax.tree_util.tree_leaves(saved_params),
    ):
        np.testing.assert_array_equal(a, b)
    # the table came back sharded, not replicated
    table = trainer.train_state.params["embedding"]["table"]
    shard_shapes = {s.data.shape for s in table.addressable_shards}
    assert shard_shapes == {(96 // 8, 8)}


def test_bfloat16_roundtrip(tmp_path):
    """bf16 leaves (the MXU compute dtype) must survive the npy codec —
    numpy alone stores them as unreadable void bytes."""
    import jax.numpy as jnp

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    x = jax.device_put(
        (np.arange(32).reshape(8, 4) / 7.0).astype(jnp.bfloat16),
        NamedSharding(mesh, P("data", None)),
    )
    save_sharded(str(tmp_path), {"x": x}, version=1)
    _, restored = load_sharded(
        str(tmp_path), {"x": NamedSharding(mesh, P("data", None))}
    )
    assert restored["x"].dtype == jnp.bfloat16
    np.testing.assert_array_equal(
        np.asarray(restored["x"], dtype=np.float32),
        np.asarray(x, dtype=np.float32),
    )


def test_partial_checkpoint_dir_ignored(tmp_path):
    """A crash mid-save leaves shards but no manifest: the manager must
    resume from the previous complete version, not wedge."""
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    tree = _sharded_tree(mesh)
    mgr = ShardedCheckpointManager(str(tmp_path), 10)
    mgr.save(tree, 10)
    partial = tmp_path / "ckpt_v20"
    partial.mkdir()
    np.save(str(partial / "emb.table.p0.s0.npy"), np.zeros((8, 4)))
    assert mgr.versions() == [10]
    assert mgr.latest_dir().endswith("ckpt_v10")
