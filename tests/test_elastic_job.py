"""Elastic multi-process job test — the north-star behavior.

A real master RPC server + two worker OS processes; one worker is killed
mid-job. Its in-flight tasks must be recovered and the job must complete
(BASELINE.md: "survives killing 50% of worker processes"). Mirrors the
reference's k8s pod-deletion recovery (k8s_instance_manager_test.py) at
the process level.
"""

import os
import sys
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.common.args import parse_master_args
from elasticdl_tpu.master.local_instance_manager import LocalInstanceManager
from elasticdl_tpu.master.master import Master
from tests.test_utils import MODEL_ZOO_PATH, DatasetName, create_recordio_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_elastic_job_survives_worker_kill(tmp_path):
    data_file = create_recordio_file(
        512, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(tmp_path)
    )
    data_dir = str(tmp_path)

    args = parse_master_args(
        [
            "--job_name",
            "elastic-test",
            "--model_zoo",
            MODEL_ZOO_PATH,
            "--model_def",
            "mnist_subclass.mnist_subclass.CustomModel",
            "--minibatch_size",
            "16",
            "--num_epochs",
            "2",
            "--training_data",
            data_dir,
            "--num_ps_pods",
            "0",
            "--port",
            "0",
            "--use_async",
            "true",
        ]
    )
    master = Master(args)
    master.prepare()

    env = dict(os.environ)
    env.update(
        {
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
        }
    )

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id",
            str(worker_id),
            "--job_type",
            "training_only",
            "--master_addr",
            "localhost:%d" % master.port,
            "--model_zoo",
            MODEL_ZOO_PATH,
            "--model_def",
            "mnist_subclass.mnist_subclass.CustomModel",
            "--minibatch_size",
            "16",
        ]

    manager = LocalInstanceManager(
        master.task_d, 2, worker_command, env=env
    )
    master.instance_manager = manager
    manager.start_workers()

    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()

    # wait until real progress, then kill 50% of the workers
    deadline = time.time() + 180
    while master.master_servicer.get_model_version() < 3:
        assert time.time() < deadline, "job made no progress"
        time.sleep(0.5)
    victims = manager.live_workers()
    assert victims, "no live workers to kill"
    manager.kill_worker(victims[0])

    runner.join(timeout=240)
    assert not runner.is_alive(), "master did not finish"
    assert master.task_d.finished()
    # all 512*2 records were processed despite the kill
    assert master.master_servicer.get_model_version() >= 512 * 2 // 16 - 8
    manager.stop_relaunch_and_remove_all_pods()
