"""EDLR format + data reader tests (parity: reference tests/data_reader_test.py)."""

import os
import tempfile
import unittest

import numpy as np

from elasticdl_tpu.data.example import (
    FixedLenFeature,
    decode_example,
    encode_example,
    parse_example,
)
from elasticdl_tpu.data.data_reader import (
    RecordIODataReader,
    create_data_reader,
)
from elasticdl_tpu.data.recordio import (
    RecordIOReader,
    RecordIOWriter,
    write_recordio,
)
from elasticdl_tpu.master.task_dispatcher import Task
from elasticdl_tpu.common.constants import TaskType


class RecordIOTest(unittest.TestCase):
    def test_write_read_roundtrip(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "x.edlr")
            payloads = [b"rec%d" % i for i in range(100)]
            n = write_recordio(path, payloads)
            self.assertEqual(n, 100)
            with RecordIOReader(path) as r:
                self.assertEqual(len(r), 100)
                self.assertEqual(r.read(0), b"rec0")
                self.assertEqual(r.read(99, validate=True), b"rec99")
                self.assertEqual(
                    list(r.read_range(10, 13)), [b"rec10", b"rec11", b"rec12"]
                )
                # out-of-range end clamps
                self.assertEqual(len(list(r.read_range(98, 200))), 2)

    def test_empty_file(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "empty.edlr")
            with RecordIOWriter(path):
                pass
            with RecordIOReader(path) as r:
                self.assertEqual(len(r), 0)

    def test_truncated_file_rejected(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "x.edlr")
            write_recordio(path, [b"abc"] * 5)
            data = open(path, "rb").read()
            trunc = os.path.join(d, "t.edlr")
            with open(trunc, "wb") as f:
                f.write(data[:-7])
            with self.assertRaises(ValueError):
                RecordIOReader(trunc)

    def test_data_reader_shards_and_tasks(self):
        with tempfile.TemporaryDirectory() as d:
            for fname, count in (("a.edlr", 7), ("b.edlr", 5)):
                write_recordio(
                    os.path.join(d, fname),
                    [b"%s-%d" % (fname.encode(), i) for i in range(count)],
                )
            reader = RecordIODataReader(data_dir=d)
            shards = reader.create_shards()
            self.assertEqual(
                shards,
                {
                    os.path.join(d, "a.edlr"): (0, 7),
                    os.path.join(d, "b.edlr"): (0, 5),
                },
            )
            task = Task(os.path.join(d, "b.edlr"), 1, 4, TaskType.TRAINING)
            recs = list(reader.read_records(task))
            self.assertEqual(recs, [b"b.edlr-1", b"b.edlr-2", b"b.edlr-3"])
            reader.close()

    def test_factory_defaults_to_recordio(self):
        with tempfile.TemporaryDirectory() as d:
            r = create_data_reader(d)
            self.assertIsInstance(r, RecordIODataReader)


class ExampleCodecTest(unittest.TestCase):
    def test_roundtrip_and_parse(self):
        ex = encode_example(
            {
                "image": np.random.rand(28, 28).astype(np.float32),
                "label": np.array([3], dtype=np.int64),
            }
        )
        raw = decode_example(ex)
        self.assertEqual(raw["image"].shape, (28, 28))
        parsed = parse_example(
            ex,
            {
                "image": FixedLenFeature((28, 28), np.float32),
                "label": FixedLenFeature((1,), np.int32),
            },
        )
        self.assertEqual(parsed["label"].dtype, np.int32)

    def test_parse_missing_feature(self):
        ex = encode_example({"a": np.zeros(3, np.float32)})
        with self.assertRaises(KeyError):
            parse_example(ex, {"b": FixedLenFeature((3,), np.float32)})
        out = parse_example(
            ex, {"b": FixedLenFeature((2,), np.float32, default_value=1.0)}
        )
        np.testing.assert_array_equal(out["b"], [1.0, 1.0])

    def test_parse_shape_mismatch(self):
        ex = encode_example({"a": np.zeros(3, np.float32)})
        with self.assertRaises(ValueError):
            parse_example(ex, {"a": FixedLenFeature((4,), np.float32)})


if __name__ == "__main__":
    unittest.main()


def test_python_writer_abort_leaves_rejectable_file(tmp_path):
    """Same crash contract as the native writer: an exception inside the
    with block must NOT finalize — the tail-less file reads as
    truncated instead of silently serving a partial shard."""
    import pytest

    from elasticdl_tpu.data.recordio import RecordIOReader, RecordIOWriter

    path = str(tmp_path / "torn.edlr")
    with pytest.raises(RuntimeError):
        with RecordIOWriter(path) as w:
            w.write(b"only")
            raise RuntimeError("boom")
    with pytest.raises(ValueError):
        RecordIOReader(path)
