"""Dataset shim tests."""

import unittest

import numpy as np

from elasticdl_tpu.data.dataset import Dataset


class DatasetTest(unittest.TestCase):
    def test_map_batch(self):
        ds = Dataset.from_tensors(range(10)).map(lambda x: x * 2).batch(4)
        batches = list(ds)
        self.assertEqual(len(batches), 3)
        np.testing.assert_array_equal(batches[0], [0, 2, 4, 6])
        np.testing.assert_array_equal(batches[2], [16, 18])

    def test_batch_drop_remainder(self):
        ds = Dataset.from_tensors(range(10)).batch(4, drop_remainder=True)
        self.assertEqual(len(list(ds)), 2)

    def test_batch_nested_structure(self):
        ds = Dataset.from_tensors(
            ({"x": np.full((2,), i)}, i) for i in range(4)
        ).batch(2)
        (feats, labels) = next(iter(ds))
        self.assertEqual(feats["x"].shape, (2, 2))
        np.testing.assert_array_equal(labels, [0, 1])

    def test_shuffle_is_permutation(self):
        ds = Dataset.from_tensors(range(100)).shuffle(16, seed=0)
        out = list(ds)
        self.assertNotEqual(out, list(range(100)))
        self.assertEqual(sorted(out), list(range(100)))

    def test_repeat_take(self):
        ds = Dataset.from_tensors(range(3)).repeat().take(7)
        self.assertEqual(list(ds), [0, 1, 2, 0, 1, 2, 0])

    def test_repeat_count(self):
        ds = Dataset.from_tensors(range(2)).repeat(2)
        self.assertEqual(list(ds), [0, 1, 0, 1])

    def test_prefetch_preserves_order_and_errors(self):
        ds = Dataset.from_tensors(range(50)).prefetch(4)
        self.assertEqual(list(ds), list(range(50)))

        def bad_gen():
            yield 1
            raise RuntimeError("boom")

        with self.assertRaises(RuntimeError):
            list(Dataset.from_generator(bad_gen).prefetch(2))

    def test_filter(self):
        ds = Dataset.from_tensors(range(10)).filter(lambda x: x % 2 == 0)
        self.assertEqual(list(ds), [0, 2, 4, 6, 8])

    def test_reiterable(self):
        ds = Dataset.from_tensors(range(3)).map(lambda x: x + 1)
        self.assertEqual(list(ds), list(ds))

    def test_device_prefetch_yields_device_arrays_in_order(self):
        import jax
        import numpy as np

        elems = [
            {"x": np.full((2, 3), i, np.float32), "y": np.int32(i)}
            for i in range(7)
        ]
        out = list(Dataset.from_tensors(elems).device_prefetch())
        self.assertEqual(len(out), 7)
        for i, e in enumerate(out):
            self.assertIsInstance(e["x"], jax.Array)
            np.testing.assert_array_equal(
                np.asarray(e["x"]), np.full((2, 3), i, np.float32)
            )

    def test_device_prefetch_bounds_in_flight_elements(self):
        produced = []

        def gen():
            for i in range(10):
                produced.append(i)
                yield i

        it = iter(Dataset.from_generator(gen).device_prefetch(buffer_size=2))
        next(it)
        # one yielded + buffer_size in flight
        self.assertLessEqual(len(produced), 4)

    def test_device_prefetch_respects_sharding(self):
        import jax
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elasticdl_tpu.parallel.mesh import create_mesh

        mesh = create_mesh({"data": 8}, axis_names=("data",))
        placement = NamedSharding(mesh, P("data"))
        batches = [np.arange(16, dtype=np.float32) + i for i in range(3)]
        out = list(
            Dataset.from_tensors(batches).device_prefetch(
                placement=placement
            )
        )
        for i, arr in enumerate(out):
            self.assertEqual(arr.sharding, placement)
            np.testing.assert_array_equal(
                np.asarray(arr), np.arange(16, dtype=np.float32) + i
            )


if __name__ == "__main__":
    unittest.main()
