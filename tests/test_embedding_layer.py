"""Elastic embedding layer tests.

Parity: reference tests/layer_test.py (forward vs standard embedding,
mask_zero, combiners) and the BET-gradient path
(report_gradients_of_bet_test.py / indices_slices_gradient_test.py) —
here exercised through the jitted embedding grad step.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from elasticdl_tpu.nn.embedding import (
    IDX_COLLECTION,
    call_slot_name,
    ROWS_COLLECTION,
    Embedding,
    build_collection,
    capture_embedding_ids,
    flatten_collection,
    path_name,
    plan_lookup,
)
from elasticdl_tpu.training.step import make_embedding_grad_fn


class OneEmbeddingModel(nn.Module):
    dim: int = 4

    @nn.compact
    def __call__(self, features, training=False):
        ids = features["ids"]
        emb = Embedding(output_dim=self.dim, name="emb")(ids)
        return emb.sum(axis=(1, 2))


def _variables_for(model, features):
    return model.init(jax.random.PRNGKey(0), features)


def test_plan_lookup():
    ids = np.array([[3, 5, 3], [9, 5, 0]])
    unique, idx, bucket = plan_lookup(ids)
    np.testing.assert_array_equal(unique, [0, 3, 5, 9])
    assert bucket == 8
    # positions map back to the original ids
    np.testing.assert_array_equal(unique[idx], ids)


def test_capture_embedding_ids():
    model = OneEmbeddingModel()
    features = {"ids": np.array([[1, 2], [3, 4]], dtype=np.int64)}
    variables = _variables_for(model, features)
    params = {"params": variables.get("params", {})}
    captured = capture_embedding_ids(model, params, features)
    assert list(captured.keys()) == [("emb",)]
    assert len(captured[("emb",)]) == 1  # one call -> one slot
    np.testing.assert_array_equal(captured[("emb",)][0], features["ids"])
    assert path_name(("emb",)) == "emb"


def test_forward_matches_table_gather():
    model = OneEmbeddingModel(dim=3)
    ids = np.array([[2, 7], [7, 2]], dtype=np.int64)
    features = {"ids": ids}
    unique, idx, bucket = plan_lookup(ids)
    table = np.random.default_rng(0).standard_normal((10, 3)).astype(
        np.float32
    )
    rows = np.concatenate(
        [table[unique], np.zeros((bucket - len(unique), 3), np.float32)]
    )
    variables = _variables_for(model, features)
    out = model.apply(
        {
            "params": variables.get("params", {}),
            ROWS_COLLECTION: build_collection({("emb",): rows}, "rows"),
            IDX_COLLECTION: build_collection(
                {("emb", call_slot_name(0)): idx}, "idx"
            ),
        },
        features,
    )
    expected = table[ids].sum(axis=(1, 2))
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_mask_zero_and_combiners():
    ids = np.array([[1, 0, 2]], dtype=np.int64)
    unique, idx, bucket = plan_lookup(ids)
    rows = np.zeros((bucket, 2), np.float32)
    rows[: len(unique)] = [[9.0, 9.0], [1.0, 1.0], [3.0, 5.0]]  # 0,1,2

    for combiner, expected in (
        ("sum", [[4.0, 6.0]]),
        ("mean", [[2.0, 3.0]]),
        ("sqrtn", [[4.0 / np.sqrt(2), 6.0 / np.sqrt(2)]]),
    ):
        layer = Embedding(output_dim=2, mask_zero=True, combiner=combiner)
        out = layer.apply(
            {
                ROWS_COLLECTION: {"rows": rows},
                IDX_COLLECTION: {call_slot_name(0): {"idx": idx}},
            },
            ids,
        )
        np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_bet_gradients_flow_through_rows():
    """Row gradients from the jitted step equal the dense-table gradient
    gathered at the touched rows (the IndexedSlices invariant)."""
    model = OneEmbeddingModel(dim=3)
    ids = np.array([[2, 7], [7, 2]], dtype=np.int64)
    features = {"ids": ids}
    labels = np.zeros((2,), np.float32)
    unique, idx, bucket = plan_lookup(ids)
    rng = np.random.default_rng(1)
    rows = np.concatenate(
        [
            rng.standard_normal((len(unique), 3)).astype(np.float32),
            np.zeros((bucket - len(unique), 3), np.float32),
        ]
    )
    variables = _variables_for(model, features)
    params = variables.get("params", {})

    def loss_fn(output, labels):
        return ((output - labels) ** 2).mean()

    grad_fn = make_embedding_grad_fn(model, loss_fn)
    loss, param_grads, row_grads, new_state, output = grad_fn(
        params,
        build_collection({("emb",): rows}, "rows"),
        {},
        build_collection({("emb", call_slot_name(0)): idx}, "idx"),
        features,
        labels,
        jax.random.PRNGKey(0),
    )
    got = flatten_collection(
        jax.tree_util.tree_map(np.asarray, row_grads), "rows"
    )[("emb",)]
    # padded rows receive zero gradient
    np.testing.assert_array_equal(got[len(unique) :], 0.0)
    # autodiff cross-check against an explicit dense gather formulation
    def dense_loss(rows_):
        emb = rows_[idx]
        out = emb.sum(axis=(1, 2))
        return ((out - labels) ** 2).mean()

    expected = np.asarray(jax.grad(dense_loss)(jnp.asarray(rows)))
    np.testing.assert_allclose(got, expected, rtol=1e-5)


class TiedEmbeddingModel(nn.Module):
    """One Embedding instance called twice per forward (tied weights) —
    the case the reference can only train eagerly (worker.py:514-524)."""

    dim: int = 3

    @nn.compact
    def __call__(self, features, training=False):
        emb = Embedding(output_dim=self.dim, name="emb")
        a = emb(features["a"])
        b = emb(features["b"])
        return a.sum(axis=(1, 2)) + 2.0 * b.sum(axis=(1, 2))


def test_tied_embedding_two_calls_capture_and_plan():
    from elasticdl_tpu.nn.embedding import plan_lookup_multi

    model = TiedEmbeddingModel()
    features = {
        "a": np.array([[1, 2], [3, 4]], dtype=np.int64),
        "b": np.array([[2, 9], [9, 1]], dtype=np.int64),
    }
    variables = _variables_for(model, features)
    # init created one rows buffer but TWO idx slots
    idx_tree = flatten_collection(variables[IDX_COLLECTION], "idx")
    assert set(idx_tree) == {
        ("emb", call_slot_name(0)),
        ("emb", call_slot_name(1)),
    }
    captured = capture_embedding_ids(
        model, {"params": variables.get("params", {})}, features
    )
    assert [len(v) for v in captured.values()] == [2]
    np.testing.assert_array_equal(captured[("emb",)][0], features["a"])
    np.testing.assert_array_equal(captured[("emb",)][1], features["b"])

    unique, idxs, bucket = plan_lookup_multi(captured[("emb",)])
    np.testing.assert_array_equal(unique, [1, 2, 3, 4, 9])
    np.testing.assert_array_equal(unique[idxs[0]], features["a"])
    np.testing.assert_array_equal(unique[idxs[1]], features["b"])


def test_tied_embedding_grads_match_dense():
    """Row gradients of a twice-called layer equal the dense-table
    gradient of the tied formulation (contributions from both call
    sites accumulate into one IndexedSlices)."""
    from elasticdl_tpu.nn.embedding import plan_lookup_multi

    model = TiedEmbeddingModel(dim=3)
    features = {
        "a": np.array([[1, 2], [3, 4]], dtype=np.int64),
        "b": np.array([[2, 9], [9, 1]], dtype=np.int64),
    }
    labels = np.zeros((2,), np.float32)
    unique, idxs, bucket = plan_lookup_multi(
        [features["a"], features["b"]]
    )
    rng = np.random.default_rng(3)
    rows = np.concatenate(
        [
            rng.standard_normal((len(unique), 3)).astype(np.float32),
            np.zeros((bucket - len(unique), 3), np.float32),
        ]
    )
    variables = _variables_for(model, features)

    def loss_fn(output, labels):
        return ((output - labels) ** 2).mean()

    grad_fn = make_embedding_grad_fn(model, loss_fn)
    loss, param_grads, row_grads, new_state, output = grad_fn(
        variables.get("params", {}),
        build_collection({("emb",): rows}, "rows"),
        {},
        build_collection(
            {
                ("emb", call_slot_name(0)): idxs[0],
                ("emb", call_slot_name(1)): idxs[1],
            },
            "idx",
        ),
        features,
        labels,
        jax.random.PRNGKey(0),
    )
    got = flatten_collection(
        jax.tree_util.tree_map(np.asarray, row_grads), "rows"
    )[("emb",)]
    np.testing.assert_array_equal(got[len(unique):], 0.0)

    def dense_loss(rows_):
        out = rows_[idxs[0]].sum(axis=(1, 2)) + 2.0 * rows_[
            idxs[1]
        ].sum(axis=(1, 2))
        return ((out - labels) ** 2).mean()

    expected = np.asarray(jax.grad(dense_loss)(jnp.asarray(rows)))
    np.testing.assert_allclose(got, expected, rtol=1e-5)


def test_bound_handle_repeated_calls():
    """A long-lived `module.bind(variables)` handle (interactive/debug
    pattern) can be called across forwards: the per-call slot counter
    wraps onto the bound slot count instead of probing missing slots."""
    ids = np.array([[1, 2]], dtype=np.int64)
    unique, idx, bucket = plan_lookup(ids)
    rows = np.zeros((bucket, 2), np.float32)
    rows[: len(unique)] = [[1.0, 1.0], [2.0, 2.0]]
    layer = Embedding(output_dim=2)
    bound = layer.bind(
        {
            ROWS_COLLECTION: {"rows": rows},
            IDX_COLLECTION: {call_slot_name(0): {"idx": idx}},
        }
    )
    first = np.asarray(bound(ids))
    second = np.asarray(bound(ids))  # crashed before the wrap fix
    np.testing.assert_array_equal(first, second)
