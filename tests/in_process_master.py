"""In-process master stub: the central test fixture.

Parity: reference tests/in_process_master.py:5-35 — duck-types the worker's
master stub by calling MasterServicer methods directly, so a *full*
distributed train/eval job (task dispatch, gradient aggregation, version
sync, eval, checkpointing) runs single-process. Callbacks fire around
report calls for fault injection (reference tests/test_call_back.py).
"""

from tests.test_callbacks import (
    ON_REPORT_EVALUATION_METRICS_BEGIN,
    ON_REPORT_GRADIENT_BEGIN,
)


class InProcessMaster:
    def __init__(self, master, callbacks=None):
        self._m = master
        self._callbacks = callbacks or []

    def get_task(self, worker_id, task_type=None):
        return self._m.get_task(worker_id, task_type)

    def get_model(self, version, method):
        return self._m.get_model(version, method)

    def report_variable(self, named_arrays):
        return self._m.report_variable(named_arrays)

    def push_embedding_info(self, embedding_infos):
        return self._m.push_embedding_info(embedding_infos)

    def pull_embedding_vectors(self, layer_name, ids):
        return self._m.pull_embedding_vectors(layer_name, ids)

    def export_embedding_tables(self):
        return self._m.export_embedding_tables()

    def report_gradient(self, gradients, model_version):
        for callback in self._callbacks:
            if ON_REPORT_GRADIENT_BEGIN in callback.call_times:
                callback()
        return self._m.report_gradient(gradients, model_version)

    def report_task_result(self, task_id, err_msg="", exec_counters=None):
        return self._m.report_task_result(task_id, err_msg, exec_counters)

    def report_telemetry(self, snapshot):
        return self._m.report_telemetry(snapshot)

    def report_evaluation_metrics(
        self, model_version, model_outputs, labels, scored_version=None
    ):
        for callback in self._callbacks:
            if ON_REPORT_EVALUATION_METRICS_BEGIN in callback.call_times:
                callback()
        return self._m.report_evaluation_metrics(
            model_version,
            model_outputs,
            labels,
            scored_version=scored_version,
        )
