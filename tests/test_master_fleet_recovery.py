"""Live-fleet master crash recovery: SIGKILL the REAL master process
mid-job, relaunch it on the same port + journal dir, and assert the
job completes with exactly-once task accounting while the worker and
both PS shards ride the outage out (docs/master_recovery.md — the
``test_ps_fleet_recovery.py`` shape, pointed at the control plane).

The fleet is 4 OS processes over real loopback gRPC: master
(``master.main`` with ``--master_journal_dir``), 2 PS shards
(``ps.main``), and one worker (``worker.main`` with the default
failover budget). Observables asserted:

- the worker process NEVER exits during the outage (its master channel
  retries UNAVAILABLE through the window; its held acks replay against
  the new incarnation and dedup by trace),
- ``master_epoch`` advances across the relaunch (probed via
  ``master_status`` before and after),
- /healthz answers "restoring" (503) or "serving" (200) around the
  replay window, never routes-traffic-ok while half-restored,
- the final journal counts every task done EXACTLY once: done ==
  tasks-per-epoch x epochs, pending == 0 (requeue-exactly-once +
  ack dedup, journal-counted),
- both jobs exit 0: the relaunched master observes completion and the
  worker drains cleanly.
"""

import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

from elasticdl_tpu.master.journal import MasterJournal
from tests.fake_ps import free_port
from tests.test_utils import (
    MODEL_ZOO_PATH,
    DatasetName,
    create_recordio_file,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MODEL_DEF = "mnist_subclass.mnist_subclass.CustomModel"

RECORDS = 256
BATCH = 16
MINIBATCHES_PER_TASK = 2  # records_per_task = 32 -> 8 tasks/epoch
EPOCHS = 2
EXPECTED_TASKS = (RECORDS // (BATCH * MINIBATCHES_PER_TASK)) * EPOCHS


def _env():
    env = dict(os.environ)
    env.update(
        {
            "PYTHONPATH": REPO,
            "JAX_PLATFORMS": "cpu",
            # fast finish detection so the test doesn't wait out the
            # 30s poll default after the last ack lands
            "EDL_MASTER_POLL_SECS": "1",
            "EDL_TASK_SHUFFLE_SEED": "7",
        }
    )
    return env


def _spawn(cmd, log_path):
    out = open(log_path, "ab")
    proc = subprocess.Popen(cmd, env=_env(), stdout=out, stderr=out)
    out.close()
    return proc


def _ps_cmd(ps_id, port):
    return [
        sys.executable, "-m", "elasticdl_tpu.ps.main",
        "--ps_id", str(ps_id),
        "--port", str(port),
        "--model_zoo", MODEL_ZOO_PATH,
        "--model_def", MODEL_DEF,
        "--use_async", "true",
        "--grads_to_wait", "1",
    ]


def _master_cmd(port, data_dir, journal_dir, telemetry_port):
    return [
        sys.executable, "-m", "elasticdl_tpu.master.main",
        "--job_name", "master-recovery-test",
        "--port", str(port),
        "--model_zoo", MODEL_ZOO_PATH,
        "--model_def", MODEL_DEF,
        "--minibatch_size", str(BATCH),
        "--num_minibatches_per_task", str(MINIBATCHES_PER_TASK),
        "--num_epochs", str(EPOCHS),
        "--training_data", data_dir,
        "--num_workers", "0",
        "--num_ps_pods", "2",
        "--use_async", "true",
        "--grads_to_wait", "1",
        "--master_journal_dir", journal_dir,
        "--master_journal_fsync_ms", "20",
        "--telemetry_port", str(telemetry_port),
    ]


def _worker_cmd(worker_id, master_port, ps_ports):
    return [
        sys.executable, "-m", "elasticdl_tpu.worker.main",
        "--worker_id", str(worker_id),
        "--job_type", "training_only",
        "--master_addr", "localhost:%d" % master_port,
        "--ps_addrs", ",".join(
            "localhost:%d" % p for p in ps_ports
        ),
        "--model_zoo", MODEL_ZOO_PATH,
        "--model_def", MODEL_DEF,
        "--minibatch_size", str(BATCH),
        # survive the master outage: generous budget vs the master's
        # relaunch + replay time on a loaded CI host
        "--master_failover_s", "240",
        # keep the boundary drains frequent so acks replay mid-test
        "--task_ack_queue", "2",
    ]


def _wait_port(proc, port, what, timeout=120):
    deadline = time.time() + timeout
    while True:
        assert proc.poll() is None, (
            "%s exited rc=%s at boot" % (what, proc.returncode)
        )
        try:
            with socket.create_connection(("localhost", port), 1.0):
                return
        except OSError:
            assert time.time() < deadline, "%s did not come up" % what
            time.sleep(0.2)


def _stop(procs):
    for proc in procs:
        if proc and proc.poll() is None:
            proc.kill()
    for proc in procs:
        if proc:
            try:
                proc.wait(timeout=10)
            except Exception:
                pass


def _status(port, timeout=30):
    """Poll master_status on a FRESH channel per attempt: a channel
    that lived through the SIGKILL can wedge in gRPC's failure state
    ("FD Shutdown") long after the relaunched master serves, so probe
    channels are disposable."""
    import grpc

    from elasticdl_tpu.rpc.core import Client

    deadline = time.time() + timeout
    while True:
        client = Client("localhost:%d" % port, deadline_s=5.0)
        try:
            return client.call("master_status")
        except grpc.RpcError:
            if time.time() >= deadline:
                raise
            time.sleep(0.3)
        finally:
            client.close()


def test_sigkill_master_midjob_bounded_recovery(tmp_path):
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        RECORDS, DatasetName.IMAGE_DEFAULT, (28, 28),
        temp_dir=str(data_dir), seed=5,
    )
    journal_dir = str(tmp_path / "journal")
    master_port = free_port()
    telemetry_port = free_port()
    ps_ports = [free_port(), free_port()]

    ps_procs = [
        _spawn(_ps_cmd(i, p), str(tmp_path / ("ps-%d.log" % i)))
        for i, p in enumerate(ps_ports)
    ]
    master = _spawn(
        _master_cmd(
            master_port, str(data_dir), journal_dir, telemetry_port
        ),
        str(tmp_path / "master-1.log"),
    )
    worker = None
    try:
        for proc, port in zip(ps_procs, ps_ports):
            _wait_port(proc, port, "ps")
        _wait_port(master, master_port, "master")
        epoch_before = _status(master_port)["master_epoch"]

        worker = _spawn(
            _worker_cmd(1, master_port, ps_ports),
            str(tmp_path / "worker.log"),
        )

        # let the job make real progress: at least 2 tasks counted
        # done in the journal before the kill
        deadline = time.time() + 240
        while True:
            assert worker.poll() is None, (
                "worker died before the kill (rc=%s)" % worker.returncode
            )
            st = _status(master_port)
            done = (st.get("journal") or {}).get("done", 0)
            if done >= 2:
                break
            assert time.time() < deadline, (
                "job made no progress before the kill (status %r)" % st
            )
            time.sleep(0.3)
        assert st["state"] == "serving"

        # -- the crash: SIGKILL, no drain — the journal tail within the
        # fsync cadence is the only permissible loss ------------------
        master.send_signal(signal.SIGKILL)
        master.wait(timeout=10)

        # the worker rides the outage: still alive while the master is
        # gone (its channel is retrying UNAVAILABLE)
        time.sleep(2.0)
        assert worker.poll() is None, (
            "worker died during the master outage (rc=%s)"
            % worker.returncode
        )

        master = _spawn(
            _master_cmd(
                master_port, str(data_dir), journal_dir, telemetry_port
            ),
            str(tmp_path / "master-2.log"),
        )
        _wait_port(master, master_port, "relaunched master")

        # /healthz flips to serving (200) once replay finished; the
        # RPC plane only binds after replay, so by now it must say
        # serving — and must NEVER have said so while restoring
        body = urllib.request.urlopen(
            "http://localhost:%d/healthz" % telemetry_port, timeout=5
        )
        assert body.status == 200
        assert body.read().decode().strip() == "serving"

        st = _status(master_port, timeout=60)
        assert st["master_epoch"] == epoch_before + 1, (
            "master_epoch must advance across the relaunch: %r" % st
        )
        assert st["state"] == "serving"

        # -- completion: worker drains, both processes exit 0 ---------
        assert worker.wait(timeout=300) == 0, "worker failed the job"
        assert master.wait(timeout=60) == 0, (
            "relaunched master did not observe completion"
        )
    finally:
        _stop([worker, master] + ps_procs)

    # -- exactly-once accounting, journal-counted ---------------------
    state = MasterJournal(journal_dir).replay()
    assert state.counters["done"] == EXPECTED_TASKS, (
        "every task must count done exactly once: %r" % state.counters
    )
    assert len(state.pending) == 0, (
        "no task may be left pending after completion: %r"
        % state.pending
    )
    # progress genuinely spanned the kill: the second incarnation
    # dispatched work (its boot segment starts at the recovery point)
    assert state.counters["dispatched"] >= EXPECTED_TASKS


def test_sigterm_master_drains_journal_and_exits_75(tmp_path):
    """Graceful preemption parity with the PS plane: SIGTERM makes the
    master flush its journal and exit 75 — the budget-exempt code the
    instance manager relaunches."""
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        64, DatasetName.IMAGE_DEFAULT, (28, 28),
        temp_dir=str(data_dir), seed=5,
    )
    journal_dir = str(tmp_path / "journal")
    port = free_port()
    master = _spawn(
        _master_cmd(port, str(data_dir), journal_dir, free_port()),
        str(tmp_path / "master.log"),
    )
    try:
        _wait_port(master, port, "master")
        master.terminate()
        assert master.wait(timeout=60) == 75
    finally:
        _stop([master])
    # the drained journal replays cleanly
    state = MasterJournal(journal_dir).replay()
    assert state.counters["done"] == 0
