"""The hybrid comm plane (docs/embedding_planes.md).

Plane parity: the same deepfm batch through PS-only, HBM-only, and
hybrid planes must produce IDENTICAL lookups and dense gradients
(power-law duplicated ids included — the dedup planner's combined row
gradients must equal the dense scatter). Plus the overlap machinery's
abandonment contract (a requeued task's prefetched pull drops exactly
once), the per-table selector, the plane-shared hot-row cache, and the
master-channel shm reply path.
"""

import threading
import time

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from elasticdl_tpu.common.constants import JobType
from elasticdl_tpu.master.checkpoint_service import CheckpointService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.nn.comm_plane import (
    EmbeddingPullPipeline,
    HbmPlane,
    HotRowCache,
    MasterStorePlane,
    PsPlane,
    make_embedding,
    resolve_table_planes,
)
from elasticdl_tpu.ps.parameters import Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.worker.ps_client import PSClient
from elasticdl_tpu.worker.worker import Worker
from tests.in_process_master import InProcessMaster
from tests.test_utils import MODEL_ZOO_PATH

VOCAB, DIM, BATCH = 96, 16, 64
MODEL_DEF = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"


def _powerlaw_batch(seed=11):
    rng = np.random.default_rng(seed)
    pool = rng.permutation(VOCAB)[:24]
    weights = 1.0 / np.arange(1, 25) ** 1.1
    weights /= weights.sum()
    features = {
        "feature": rng.choice(pool, size=(BATCH, 10), p=weights).astype(
            np.int64
        )
    }
    labels = rng.integers(0, 2, size=(BATCH, 1)).astype(np.int32)
    return features, labels


def _servicers(n=2):
    return [
        PserverServicer(
            Parameters(),
            grads_to_wait=1,
            optimizer=optax.sgd(0.1),
            use_async=True,
        )
        for _ in range(n)
    ]


def _make_worker(servicers, zoo_plane, worker_plane, **kwargs):
    return Worker(
        worker_id=1,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=BATCH,
        model_zoo=MODEL_ZOO_PATH,
        model_def=MODEL_DEF,
        model_params="embedding_dim=%d,fc_unit=16,vocab_size=%d,"
        "embedding_plane='%s'" % (DIM, VOCAB, zoo_plane),
        ps_client=PSClient(servicers),
        embedding_plane=worker_plane,
        embedding_prefetch=kwargs.pop("embedding_prefetch", False),
        **kwargs,
    )


# ---------------------------------------------------------------------------
# per-table plane selection
# ---------------------------------------------------------------------------


def test_resolve_table_planes_forms():
    tables = ["embedding", "id_bias"]
    assert resolve_table_planes("ps", tables) == {
        "embedding": "ps",
        "id_bias": "ps",
    }
    assert resolve_table_planes("hbm", tables) == {
        "embedding": "hbm",
        "id_bias": "hbm",
    }
    split = {"embedding": "ps", "id_bias": "hbm"}
    assert resolve_table_planes("hybrid", tables, split) == split
    assert resolve_table_planes("id_bias:hbm", tables) == {
        "embedding": "ps",
        "id_bias": "hbm",
    }
    assert resolve_table_planes(
        "embedding:hbm/id_bias:ps", tables
    ) == {"embedding": "hbm", "id_bias": "ps"}


def test_resolve_table_planes_rejects_bad_specs():
    with pytest.raises(ValueError, match="hybrid"):
        resolve_table_planes("hybrid", ["t"], hybrid_default=None)
    with pytest.raises(ValueError, match="missing tables"):
        resolve_table_planes("hybrid", ["a", "b"], {"a": "ps"})
    with pytest.raises(ValueError, match="unknown table"):
        resolve_table_planes("nope:ps", ["a"])
    with pytest.raises(ValueError, match="bad embedding_plane entry"):
        resolve_table_planes("a=ps", ["a"])


def test_make_embedding_factory():
    from elasticdl_tpu.nn.embedding import Embedding
    from elasticdl_tpu.nn.hbm_embedding import HbmEmbedding

    ps_layer = make_embedding("ps", output_dim=8, name="t")
    assert isinstance(ps_layer, Embedding)
    hbm_layer = make_embedding(
        "hbm", output_dim=8, name="t", vocab_size=32
    )
    assert isinstance(hbm_layer, HbmEmbedding)
    with pytest.raises(ValueError, match="vocab_size"):
        make_embedding("hbm", output_dim=8, name="t")
    with pytest.raises(ValueError, match="unknown embedding plane"):
        make_embedding("redis", output_dim=8, name="t")


def test_zoo_param_shardings_follow_planes():
    from model_zoo.deepfm_edl_embedding import deepfm_edl_embedding as zoo

    legacy = zoo.param_shardings(None)
    assert set(legacy) == {"embedding", "id_bias"}
    hybrid = zoo.param_shardings(None, embedding_plane="hybrid")
    assert set(hybrid) == {"id_bias"}  # the ps table is not a parameter
    assert zoo.param_shardings(None, embedding_plane="ps") == {}


def test_allreduce_worker_refuses_ps_plane_tables():
    """The collective plane cannot pull per-batch rows; the guard must
    fire at worker construction with a pointer to the hybrid trainer,
    not deep inside establish (crash-loop under relaunch)."""
    from elasticdl_tpu.worker.elastic_allreduce_worker import (
        ElasticAllReduceWorker,
    )

    # "embedding:hbm" leaves the UNLISTED id_bias table on its ps
    # default — the guard must resolve the spec, not string-sniff it
    for spec in (
        "ps",
        "hybrid",
        "embedding:ps/id_bias:hbm",
        "embedding:hbm",
    ):
        with pytest.raises(NotImplementedError, match="PS plane"):
            ElasticAllReduceWorker(
                worker_id=1,
                job_type=JobType.TRAINING_ONLY,
                minibatch_size=4,
                model_zoo=MODEL_ZOO_PATH,
                model_def=MODEL_DEF,
                model_params="embedding_plane='%s'" % spec,
            )


def test_hybrid_worker_rejects_serving_only_jobs():
    """Hybrid's local replica is populated BY training; an eval- or
    predict-only hybrid worker would silently score random init."""
    for job_type in (JobType.EVALUATION_ONLY, JobType.PREDICTION_ONLY):
        with pytest.raises(ValueError, match="training job"):
            Worker(
                worker_id=1,
                job_type=job_type,
                minibatch_size=4,
                model_zoo=MODEL_ZOO_PATH,
                model_def=MODEL_DEF,
                ps_client=PSClient(_servicers()),
                embedding_plane="hybrid",
            )


def test_zoo_collective_refuses_ps_tables():
    from model_zoo.deepfm_edl_embedding import deepfm_edl_embedding as zoo

    model = zoo.DeepFMEdl(
        embedding_dim=8,
        fc_unit=8,
        vocab_size=32,
        embedding_plane="hybrid",
        collective=True,
    )
    features = {"feature": np.zeros((2, 10), np.int64)}
    with pytest.raises(ValueError, match="PS plane"):
        model.init(jax.random.PRNGKey(0), features)


# ---------------------------------------------------------------------------
# plane parity: identical lookups + dense gradients across all three
# ---------------------------------------------------------------------------


def test_plane_parity_ps_hbm_hybrid():
    """One batch, one common initialization, three planes: PS-only and
    hybrid through workers against ONE shared store, HBM-only as the
    dense twin with tables seeded from the same store rows.

    PS vs hybrid is BITWISE (same bucket-gather graph for the
    PS-resident table — the bench pre-pass gates on exactly this);
    the HBM-only twin's LOOKUPS are bitwise too, while its logits and
    gradients agree to float tolerance only — its full-table take
    changes downstream XLA fusion, which reassociates the final
    reductions (~1e-8)."""
    features, labels = _powerlaw_batch()
    servicers = _servicers()

    wp = _make_worker(servicers, "ps", "ps")
    wh = _make_worker(servicers, "hybrid", "hybrid")
    wp._run_model_call_before_training(features)
    wh._run_model_call_before_training(features)
    for key in ("Dense_0", "Dense_1"):
        wh._params[key] = wp._params[key]
    all_ids = np.arange(VOCAB)
    bias_rows = np.asarray(
        wp._ps_client.pull_embedding_vectors("id_bias", all_ids),
        np.float32,
    )
    emb_rows = np.asarray(
        wp._ps_client.pull_embedding_vectors("embedding", all_ids),
        np.float32,
    )
    wh._params["id_bias"]["table"] = jnp.asarray(bias_rows)

    # the HBM-only twin: same graph with BOTH tables as parameters
    from model_zoo.deepfm_edl_embedding import deepfm_edl_embedding as zoo
    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import make_grad_fn

    twin = zoo.DeepFMEdl(
        embedding_dim=DIM,
        fc_unit=16,
        vocab_size=VOCAB,
        embedding_plane="hbm",
    )
    t_params, t_state = split_variables(
        init_variables(twin, jax.random.PRNGKey(0), features)
    )
    for key in ("Dense_0", "Dense_1"):
        t_params[key] = wp._params[key]
    t_params["embedding"]["table"] = jnp.asarray(emb_rows)
    t_params["id_bias"]["table"] = jnp.asarray(bias_rows)

    # lookups: the PS plane's gathered rows == the twin's table take,
    # bitwise (power-law duplicate ids and all)
    from elasticdl_tpu.nn.embedding import flatten_collection

    rows_tree, idx_tree, _ = wp._prepare_embedding_batch(features)
    ids = features["feature"].astype(np.int32)
    for name, dim in (("embedding", DIM), ("id_bias", 1)):
        rows = flatten_collection(rows_tree, "rows")[(name,)]
        idx = flatten_collection(idx_tree, "idx")[
            (name, "_CallSlot_0")
        ]
        ps_lookup = rows[idx]
        twin_lookup = np.asarray(
            jnp.take(t_params[name]["table"], ids, axis=0)
        )
        assert np.array_equal(ps_lookup, twin_lookup), name

    fp = wp.forward_process(features)
    fh = wh.forward_process(features)
    twin_out = twin.apply({"params": t_params, **t_state}, features)
    assert np.array_equal(
        np.asarray(fp["logits"]), np.asarray(fh["logits"])
    )
    np.testing.assert_allclose(
        np.asarray(fp["logits"]),
        np.asarray(twin_out["logits"]),
        rtol=1e-6,
        atol=1e-6,
    )

    lp, gp, sp = wp.training_process(features, labels)
    lh, gh, sh = wh.training_process(features, labels)
    rng = jax.random.fold_in(
        jax.random.PRNGKey(0 * 100003 + 1), 1
    )  # the workers' step-1 rng (seed=0, worker_id=1)
    lt, gt, _, _ = make_grad_fn(twin, zoo.loss)(
        t_params, t_state, features, labels, rng
    )

    assert float(lp) == float(lh)
    np.testing.assert_allclose(float(lp), float(lt), rtol=1e-6)
    for key in ("Dense_0", "Dense_1"):
        for leaf in gp[key]:
            a = np.asarray(gp[key][leaf])
            assert np.array_equal(a, np.asarray(gh[key][leaf]))
            np.testing.assert_allclose(
                a, np.asarray(gt[key][leaf]), rtol=1e-5, atol=1e-6
            )

    sp_by = {t.name: t for t in sp}
    sh_by = {t.name: t for t in sh}
    # hybrid pushes only the ps-resident table
    assert sorted(sp_by) == ["embedding", "id_bias"]
    assert sorted(sh_by) == ["embedding"]
    assert np.array_equal(
        sp_by["embedding"].values, sh_by["embedding"].values
    )
    assert np.array_equal(
        sp_by["embedding"].indices, sh_by["embedding"].indices
    )

    # sparse row grads == the dense twin's table grads, scattered
    # (float tolerance vs the twin's differently-fused graph; the
    # hybrid arm's dense bias-table grad matches the PS arm's
    # scattered sparse rows BITWISE — same graph family)
    for name, dim in (("embedding", DIM), ("id_bias", 1)):
        scattered = np.zeros((VOCAB, dim), np.float32)
        t = sp_by[name]
        scattered[np.asarray(t.indices)] = np.asarray(t.values)
        np.testing.assert_allclose(
            scattered,
            np.asarray(gt[name]["table"]),
            rtol=1e-5,
            atol=1e-6,
            err_msg=name,
        )
    bias_scatter = np.zeros((VOCAB, 1), np.float32)
    bias_scatter[np.asarray(sp_by["id_bias"].indices)] = np.asarray(
        sp_by["id_bias"].values
    )
    assert np.array_equal(
        bias_scatter, np.asarray(gh["id_bias"]["table"])
    )
    for worker in (wp, wh):
        worker._ps_client.close()


# ---------------------------------------------------------------------------
# the overlapped pull: staging, consumption, abandonment
# ---------------------------------------------------------------------------


def test_pipeline_consume_returns_staged_pull():
    pipe = EmbeddingPullPipeline()
    key = object()
    pipe.submit(key, "plan", lambda: {"t": np.ones(3)})
    plan, pulled = pipe.consume(key)
    assert plan == "plan" and np.array_equal(pulled["t"], np.ones(3))
    assert pipe.served == 1
    assert pipe.consume(key) is None  # one-shot
    pipe.close()


def test_pipeline_invalidate_drops_exactly_once():
    """The round-abandonment race pin: a requeued task's prefetched
    pull is dropped exactly once — invalidate waits the in-flight pull
    out, a second invalidate (or a consume after it) finds nothing."""
    pipe = EmbeddingPullPipeline()
    release = threading.Event()
    pulled = []

    def slow_pull():
        release.wait(5.0)
        pulled.append(True)
        return {"t": np.zeros(1)}

    key = object()
    pipe.submit(key, "plan", slow_pull)
    dropper = {}

    def invalidate():
        dropper["n"] = pipe.invalidate()

    t = threading.Thread(target=invalidate)
    t.start()
    time.sleep(0.05)
    assert t.is_alive()  # invalidate waits for the in-flight pull
    release.set()
    t.join(5.0)
    assert dropper["n"] == 1
    assert pulled == [True]  # the pull finished (no RPC left mid-air)
    assert pipe.dropped == 1
    assert pipe.invalidate() == 0  # exactly once
    assert pipe.consume(key) is None
    pipe.close()


def test_pipeline_depth_evicts_oldest():
    pipe = EmbeddingPullPipeline(depth=2)
    keys = [object() for _ in range(3)]
    for i, key in enumerate(keys):
        pipe.submit(key, i, lambda i=i: i)
    assert pipe.consume(keys[0]) is None  # evicted (and counted dropped)
    assert pipe.consume(keys[1]) == (1, 1)
    assert pipe.consume(keys[2]) == (2, 2)
    assert pipe.dropped == 1
    pipe.close()


def test_pipeline_failed_pull_surfaces_at_consume():
    pipe = EmbeddingPullPipeline()
    key = object()

    def boom():
        raise RuntimeError("shard died")

    pipe.submit(key, "plan", boom)
    with pytest.raises(RuntimeError, match="shard died"):
        pipe.consume(key)
    pipe.close()


def _run_hybrid_job(tmp_path, fail_on_call=None):
    """A small hybrid training job over in-process PS servicers;
    optionally inject one failing minibatch (task requeues)."""
    from elasticdl_tpu.data.example import encode_example
    from elasticdl_tpu.data.recordio import RecordIOWriter

    records, batch = 64, 16
    rng = np.random.default_rng(5)
    path = str(tmp_path / "hy.edlr")
    with RecordIOWriter(path) as w:
        for _ in range(records):
            w.write(
                encode_example(
                    {
                        "feature": rng.integers(
                            0, VOCAB, size=(10,)
                        ).astype(np.int64),
                        "label": np.array(
                            [rng.integers(0, 2)], np.int64
                        ),
                    }
                )
            )
    servicers = _servicers()
    task_d = TaskDispatcher({path: (0, records)}, {}, {}, batch, 2)
    master = MasterServicer(
        1,
        batch,
        None,
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=True,
    )
    client = PSClient(servicers, push_inflight=1)
    worker = Worker(
        worker_id=1,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=batch,
        model_zoo=MODEL_ZOO_PATH,
        model_def=MODEL_DEF,
        model_params="embedding_dim=%d,fc_unit=8,vocab_size=%d,"
        "embedding_plane='hybrid'" % (DIM, VOCAB),
        ps_client=client,
        embedding_plane="hybrid",
    )
    worker._stub = InProcessMaster(master)
    if fail_on_call is not None:
        orig = worker._run_training_task
        state = {"calls": 0}

        def flaky(features, labels):
            state["calls"] += 1
            if state["calls"] == fail_on_call:
                raise RuntimeError("injected minibatch failure")
            return orig(features, labels)

        worker._run_training_task = flaky
    try:
        worker.run()
        rows = client.pull_embedding_vectors(
            "embedding", np.arange(VOCAB)
        )
    finally:
        client.close()
    return worker, task_d, rows


def test_hybrid_job_end_to_end(tmp_path):
    worker, task_d, rows = _run_hybrid_job(tmp_path)
    assert task_d.finished()
    # the overlapped pull actually served batches
    assert worker._emb_pipeline.served > 0
    assert worker._emb_pipeline.dropped == 0
    # dense half trained locally; sparse table landed on the PS
    assert worker._model_version > 0
    bias = np.asarray(worker._params["id_bias"]["table"])
    assert bias.shape == (VOCAB, 1) and np.abs(bias).sum() > 0
    assert rows.shape == (VOCAB, DIM) and np.isfinite(rows).all()


def test_hybrid_requeued_task_drops_prefetched_pull_once(tmp_path):
    """A failed minibatch requeues its task, and every pull staged at
    that moment — the failed batch's own (it never reached compute)
    and the lookahead batch's — is dropped EXACTLY ONCE: two pending
    entries, two drops, no double-count, nothing served later. The
    job still completes; the requeued records re-run with fresh
    inline pulls."""
    worker, task_d, _ = _run_hybrid_job(tmp_path, fail_on_call=3)
    assert task_d.finished()
    assert worker._emb_pipeline.dropped == 2
    assert worker._emb_pipeline.served > 0
    # nothing left staged after the run (a leak would hold PS rows)
    assert worker._emb_pipeline.invalidate() == 0


# ---------------------------------------------------------------------------
# plane objects + the shared cache
# ---------------------------------------------------------------------------


def test_ps_plane_shares_external_cache():
    from tests.fake_ps import TablePS

    shared = HotRowCache(64, window=2)
    client = PSClient([TablePS(dim=4), TablePS(dim=4)], cache=shared)
    plane = PsPlane(client)
    assert plane.cache is shared
    assert HbmPlane(shared_cache=shared).cache is shared
    # the plane's pull fills the shared cache
    rows = plane.pull({"embedding": np.array([1, 2, 3], np.int64)})
    assert rows["embedding"].shape[0] == 3
    assert len(shared) == 3
    # a second pull through the plane serves from the shared cache
    before = shared.hits
    plane.pull({"embedding": np.array([1, 2, 3], np.int64)})
    assert shared.hits > before
    client.close()


def test_invalidate_table_spares_unrelated_tables():
    """The serving-plane cache fix (ISSUE 15): delta sync's
    whole-table fallback must drop ONLY the named table's stale rows —
    ``invalidate_shard`` was the only reset path before, and it evicts
    every co-sharded table's hot rows plus re-anchors the shard clock
    for what is not a relaunch."""
    cache = HotRowCache(64, window=4)
    for i in range(4):
        cache.put("a", i, 0, 10 + i, np.full(2, i, np.float32))
        cache.put("b", i, 0, 10 + i, np.full(2, 100 + i, np.float32))
    cache.put("a", 9, 1, 3, np.zeros(2, np.float32))  # other shard
    # version-bounded drop: only a's entries below 12 go
    assert cache.invalidate_table("a", below_version=12) == 3  # 10,11,3
    assert cache.get("a", 2) is not None  # tagged 12: kept
    assert cache.get("a", 3) is not None  # tagged 13: kept
    assert cache.get("a", 0) is None
    assert cache.get("a", 9) is None  # cross-shard entries drop too
    # b is untouched — every row still hittable
    assert all(
        r is not None for r in cache.get_rows("b", list(range(4)))
    )
    # and the shard version clock was NOT re-anchored: aging still
    # works off the versions the cache had seen, per entry
    cache.note_version(0, 16)
    assert cache.get("b", 0) is None  # tag 10, lag 6 > window: ages out
    assert cache.get("b", 3) is not None  # tag 13, lag 3: still fresh
    # unbounded form drops everything left of a, and only a
    assert cache.invalidate_table("a") == 2  # the kept 12 and 13
    assert cache.get("b", 3) is not None


def test_master_store_plane_pulls_per_table():
    store = {}

    class Stub:
        def pull_embedding_vectors(self, name, ids):
            store.setdefault(name, 0)
            store[name] += 1
            return np.ones((len(ids), 4), np.float32)

    plane = MasterStorePlane(lambda: Stub())
    out = plane.pull(
        {"a": np.array([1, 2]), "b": np.array([3, 4, 5])}
    )
    assert out["a"].shape == (2, 4) and out["b"].shape == (3, 4)
    assert store == {"a": 1, "b": 1}
    with pytest.raises(NotImplementedError):
        plane.push([], 0)


def test_hbm_plane_is_in_graph_only():
    plane = HbmPlane()
    assert plane.in_graph
    with pytest.raises(RuntimeError, match="jitted step"):
        plane.pull({"t": np.array([1])})
    with pytest.raises(RuntimeError, match="jitted step"):
        plane.push([], 0)
    # the planner is still the shared host-side one
    unique, idxs, bucket = plane.plan_lookup_multi(
        [np.array([5, 5, 7])]
    )
    assert list(unique) == [5, 7] and bucket == 8


# ---------------------------------------------------------------------------
# master-channel shm (get_model replies)
# ---------------------------------------------------------------------------


def _serve_master_with_shm():
    from elasticdl_tpu.master.rpc_service import MasterRpcService
    from elasticdl_tpu.rpc.core import serve
    from elasticdl_tpu.rpc.shm_transport import install_shm_endpoint

    task_d = TaskDispatcher({"f": (0, 16)}, {}, {}, 16, 1)
    master = MasterServicer(
        1,
        16,
        optax.sgd(0.1),
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=True,
    )
    master.report_variable(
        {"w": np.arange(12, dtype=np.float32).reshape(3, 4)}
    )
    methods, registry = install_shm_endpoint(
        MasterRpcService(master).rpc_methods()
    )
    server = serve(methods, 0)
    return server, registry, "localhost:%d" % server._edl_port


def test_master_channel_get_model_rides_shm():
    from elasticdl_tpu.master.rpc_service import MasterClient

    server, registry, addr = _serve_master_with_shm()
    client = MasterClient(addr, shm="auto")
    try:
        version, named = client.get_model(0)
        assert client._shm.state == "on"
        assert client._shm.stats["shm"] == 1
        # retained params were materialized off the recycled slot
        assert named["w"].flags.writeable
        first = named["w"].copy()
        _, named2 = client.get_model(0)  # recycles + reuses the slot
        assert np.array_equal(named["w"], first)
        assert np.array_equal(named2["w"], first)
        # non-model RPCs stay on the bytes path (request-retention
        # safety: the master servicer was never audited for slot reuse)
        client.get_task(1)
        assert client._shm.stats["shm"] == 2
    finally:
        client.close()
        registry.close()
        server.stop(grace=None)


def test_master_channel_shm_cross_host_falls_back(monkeypatch):
    from elasticdl_tpu.master.rpc_service import MasterClient
    from elasticdl_tpu.rpc import shm_transport

    server, registry, addr = _serve_master_with_shm()
    # client advertises a foreign fingerprint: server declines, channel
    # stays on the bytes path forever, results identical
    monkeypatch.setattr(
        shm_transport,
        "host_fingerprint",
        lambda: "elsewhere|not-this-boot",
    )
    client = MasterClient(addr, shm="auto")
    try:
        version, named = client.get_model(0)
        assert client._shm.state == "off"
        assert np.array_equal(
            named["w"], np.arange(12, dtype=np.float32).reshape(3, 4)
        )
    finally:
        client.close()
        registry.close()
        server.stop(grace=None)


def test_bytes_path_get_model_stays_zero_copy():
    from elasticdl_tpu.master.rpc_service import MasterClient

    server, registry, addr = _serve_master_with_shm()
    client = MasterClient(addr, shm="off")
    try:
        _, named = client.get_model(0)
        # the advisory gRPC-bytes arena keeps the zero-copy contract:
        # read-only views pinned to the reply buffer
        assert not named["w"].flags.writeable
    finally:
        client.close()
        registry.close()
        server.stop(grace=None)
