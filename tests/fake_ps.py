"""Fault-injection PS stubs for overlap/deadline tests.

Two layers match the two ways tests drive the PS data plane:

- :class:`FaultyPS` wraps any in-process PS-interface object (a real
  ``PserverServicer`` or a synthetic stub) and injects per-call delay,
  one-shot mid-call kills, and forced rejections — the knobs the
  fan-out determinism / async-drain regression tests need.
- :func:`serve_slow_ps` stands up a REAL loopback gRPC server
  (rpc/core.serve) whose handlers sleep, for exercising the
  deadline/retry path end to end through grpc's own status codes.
"""

import socket
import threading
import time

import numpy as np


class ShardKilledError(RuntimeError):
    """Raised by a FaultyPS whose kill switch is set (simulates the
    transport error a dead pod surfaces once deadlines are bounded)."""


class FaultyPS:
    """In-process PS stub wrapper with injectable faults.

    ``delay_s``: sleep before forwarding every call (per-method filter
    via ``delay_methods``). ``kill_after``: forward that many calls,
    then raise :class:`ShardKilledError` on every later one — "dies
    mid-job". ``reject_pushes``: force ``push_gradient`` responses to
    ``accepted=False`` while still forwarding (models a stale-gradient
    rejection on one shard only). A thread-safe call log records
    ``(method, thread_name, t_start, t_end)`` for concurrency asserts.
    """

    def __init__(
        self,
        inner,
        delay_s=0.0,
        delay_methods=None,
        kill_after=None,
        reject_pushes=False,
    ):
        self._inner = inner
        self.delay_s = delay_s
        self.delay_methods = set(delay_methods or ())
        self.kill_after = kill_after
        self.reject_pushes = reject_pushes
        self.calls = []
        self._mu = threading.Lock()
        self._n_calls = 0

    def max_concurrency(self):
        """Largest number of overlapping logged calls."""
        events = []
        with self._mu:
            spans = [(c[2], c[3]) for c in self.calls]
        for start, end in spans:
            events.append((start, 1))
            events.append((end, -1))
        live = peak = 0
        for _, step in sorted(events):
            live += step
            peak = max(peak, live)
        return peak

    def _forward(self, method, req):
        with self._mu:
            self._n_calls += 1
            n = self._n_calls
        if self.kill_after is not None and n > self.kill_after:
            raise ShardKilledError(
                "injected shard death (call %d > kill_after %d)"
                % (n, self.kill_after)
            )
        t0 = time.monotonic()
        if self.delay_s and (
            not self.delay_methods or method in self.delay_methods
        ):
            time.sleep(self.delay_s)
        resp = getattr(self._inner, method)(req)
        if method == "push_gradient" and self.reject_pushes:
            resp = dict(resp)
            resp["accepted"] = False
        with self._mu:
            self.calls.append(
                (method, threading.current_thread().name, t0, time.monotonic())
            )
        return resp

    def __getattr__(self, method):
        if method.startswith("_"):
            raise AttributeError(method)

        def call(req):
            return self._forward(method, req)

        return call


class TablePS:
    """Minimal synthetic shard: versioned lookup table, no optimizer.

    Rows for id ``i`` are ``i + 1000 * version`` so tests can tell
    exactly which version a row came from; ``push_gradient`` bumps the
    version and returns the standard accepted/version response.
    """

    def __init__(self, dim=4):
        self.dim = dim
        self.version = 0
        self.pulls = 0
        self.pushes = 0

    def pull_variable(self, req):
        return {
            "model_init_status": True,
            "version": self.version,
            "params": [],
        }

    def pull_embedding_vector(self, req):
        self.pulls += 1
        ids = np.asarray(req["ids"], np.int64)
        rows = (
            ids[:, None].astype(np.float32)
            + 1000.0 * self.version
            + np.zeros((1, self.dim), np.float32)
        )
        return {"rows": rows, "version": self.version}

    def push_gradient(self, req):
        self.pushes += 1
        self.version += 1
        return {"accepted": True, "version": self.version}

    def push_model(self, req):
        return {}

    def push_embedding_info(self, req):
        return {}


def free_port():
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def serve_slow_ps(delay_s, dim=4, port=0):
    """Real loopback gRPC PS whose every handler sleeps ``delay_s``.

    Returns ``(server, addr)``; stop with ``server.stop(None)``. Built
    on rpc/core.serve so deadline expiry and UNAVAILABLE surface as the
    genuine grpc.RpcError codes the client-side bounding must handle.
    """
    from elasticdl_tpu.rpc.core import serve

    table = TablePS(dim=dim)

    def slow(fn):
        def handler(req):
            time.sleep(delay_s)
            return fn(req)

        return handler

    methods = {
        name: slow(getattr(table, name))
        for name in (
            "pull_variable",
            "pull_embedding_vector",
            "push_gradient",
            "push_model",
            "push_embedding_info",
        )
    }
    server = serve(methods, port)
    return server, "localhost:%d" % server._edl_port
