"""Micro-batching for the serving plane (docs/serving.md, PR 18).

Coverage map (ISSUE 18):

- the latency-budget cutoff: a lone request never waits for a full
  bucket — it dispatches (padded to its pow2 bucket) within the budget,
- bitwise equivalence: per-request outputs from a coalesced, padded
  forward are identical to unbatched scoring from one common artifact
  (padding REPEATS real rows, so the dedup plan's unique-id set — and
  therefore the PS pull — is unchanged),
- de-multiplex order: concurrent callers each get exactly their own
  rows back,
- admission control: queue-cap and SLO sheds surface as the explicit
  ``{"error": "overloaded"}`` degrade payload through the servicer,
  counted in ``edl_scorer_shed_total`` and
  ``edl_scorer_errors_total{kind="overloaded"}``,
- swap/drain discipline: an in-flight coalesced batch finishes on the
  model version it acquired across a hot swap, and ``stop(drain=True)``
  (the SIGTERM path) answers everything already queued while new
  submits shed ``draining``,
- warm-on-swap: ``Scorer.set_warm_batch_sizes`` makes ``install`` pre-
  trace every registered bucket shape,
- the error-kind counter fix: ``bad_request``/``no_model`` degraded
  paths land in ``edl_scorer_errors_total``.

Runs under EDL_LOCKTRACE=1 in scripts/check.sh (conftest suites): the
dispatcher thread must be daemon and join on stop.
"""

import os
import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.serving.batcher import (
    MicroBatcher,
    Overloaded,
    batch_buckets,
    request_signature,
)
from elasticdl_tpu.serving.scorer import ModelDirectoryWatcher, Scorer
from elasticdl_tpu.serving.server import ScorerServicer
from elasticdl_tpu.utils import profiling
from tests.test_serving import (
    _client,
    _deepfm_params,
    _export,
    _features,
    _ps_shards,
)


class FakeScorer:
    """Echo scorer for queue-discipline tests: returns the ``x``
    feature untouched (so de-multiplexed rows are self-identifying),
    records every forward's row count, and can block on an event."""

    def __init__(self, version=1):
        self.version = version
        self.calls = []
        self.gate = None  # threading.Event the forward waits on
        self.entered = threading.Event()

    def score(self, feats):
        self.calls.append(int(feats["x"].shape[0]))
        self.entered.set()
        if self.gate is not None:
            assert self.gate.wait(10.0)
        return np.asarray(feats["x"]).copy(), self.version

    def latency_p99(self):
        return 0.001


def _req(value, rows=2):
    return {"x": np.full((rows,), float(value), dtype=np.float32)}


def _as_np(out):
    """Model outputs (array or dict of arrays) as numpy."""
    if isinstance(out, dict):
        return {k: np.asarray(v) for k, v in out.items()}
    return np.asarray(out)


def _assert_bitwise_equal(got, want, label):
    got, want = _as_np(got), _as_np(want)
    if isinstance(want, dict):
        assert sorted(got) == sorted(want), (label, got, want)
        for k in want:
            assert np.array_equal(got[k], want[k]), (
                "%s: output %r differs from unbatched" % (label, k)
            )
    else:
        assert np.array_equal(got, want), (
            "%s: batched output differs from unbatched" % label
        )


# ---------------------------------------------------------------------------
# buckets + signatures
# ---------------------------------------------------------------------------


def test_bucket_ladder_and_bucket_for():
    assert batch_buckets(32) == [1, 2, 4, 8, 16, 32]
    assert batch_buckets(48) == [1, 2, 4, 8, 16, 32, 48]
    assert batch_buckets(1) == [1]
    b = MicroBatcher(FakeScorer(), max_batch=8)
    assert b.bucket_for(3) == 4
    assert b.bucket_for(8) == 8
    assert b.bucket_for(9) == 16  # oversize head: next pow2 off-ladder
    b.close()


def test_request_signature_gates_coalescing():
    rows, sig = request_signature(
        {"a": np.zeros((4, 3)), "b": np.zeros((4,), np.int64)}
    )
    assert rows == 4
    assert sig == (("a", "float64", (3,)), ("b", "int64", ()))
    # ragged leading dims, 0-d features, zero rows: inline, not batched
    assert request_signature(
        {"a": np.zeros((4, 3)), "b": np.zeros((2,))}
    ) == (None, None)
    assert request_signature({"a": np.float32(1.0)}) == (None, None)
    assert request_signature({"a": np.zeros((0, 3))}) == (None, None)


# ---------------------------------------------------------------------------
# the cutoff + coalescing + de-multiplexing (fake scorer)
# ---------------------------------------------------------------------------


def test_lone_request_cutoff_fires_within_budget():
    s = FakeScorer()
    b = MicroBatcher(s, max_batch=32, timeout_ms=20.0)
    b.start()
    try:
        t0 = time.monotonic()
        out, version = b.submit(_req(7.0, rows=3))
        waited = time.monotonic() - t0
        assert version == 1
        assert np.array_equal(out, np.full((3,), 7.0, np.float32))
        # the cutoff, not the full bucket, dispatched it: one forward,
        # padded to the 3-row request's pow2 bucket, well within the
        # budget plus scheduling slack
        assert s.calls == [4]
        assert waited < 5.0, waited
    finally:
        b.stop()


def test_concurrent_callers_coalesce_and_demux_in_order():
    s = FakeScorer()
    b = MicroBatcher(s, max_batch=16, timeout_ms=25.0)
    b.start()
    try:
        n = 8
        results = [None] * n
        errs = []

        def call(i):
            try:
                results[i] = b.submit(_req(float(i), rows=2))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append(e)

        threads = [
            threading.Thread(target=call, args=(i,)) for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(10.0)
        assert not errs, errs
        for i, (out, version) in enumerate(results):
            assert version == 1
            assert np.array_equal(
                out, np.full((2,), float(i), np.float32)
            ), "caller %d got someone else's rows: %r" % (i, out)
        # genuinely coalesced: fewer forwards than callers
        assert len(s.calls) < n, s.calls
    finally:
        b.stop()


def test_mixed_signatures_never_share_a_forward():
    s = FakeScorer()
    b = MicroBatcher(s, max_batch=16, timeout_ms=25.0)
    b.start()
    try:
        results = {}

        def call(name, feats):
            results[name] = b.submit(feats)

        a = {"x": np.full((2,), 1.0, np.float32)}
        c = {"x": np.full((2, 3), 2.0, np.float32)}  # different trailing
        ts = [
            threading.Thread(target=call, args=("a", a)),
            threading.Thread(target=call, args=("c", c)),
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10.0)
        assert np.array_equal(results["a"][0], a["x"])
        assert np.array_equal(results["c"][0], c["x"])
        assert len(s.calls) == 2  # one forward per signature
    finally:
        b.stop()


# ---------------------------------------------------------------------------
# admission control + the degrade payload
# ---------------------------------------------------------------------------


def test_shed_path_returns_the_degrade_payload():
    s = FakeScorer()
    s.gate = threading.Event()  # dispatcher parks inside the forward
    b = MicroBatcher(s, max_batch=2, timeout_ms=0.0, queue_rows=2)
    b.start()
    try:
        counting = _CountingScorer(s)
        servicer = ScorerServicer(counting, batcher=b)
        shed_before = b._c_shed.value(reason="queue_full")
        # fill: one batch parked in flight + a provably full queue
        waiters = [
            threading.Thread(
                target=lambda: _swallow(lambda: b.submit(_req(0.0)))
            )
            for _ in range(2)
        ]
        waiters[0].start()
        assert s.entered.wait(10.0)  # batch 1 parked in the forward
        waiters[1].start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and b.queue_depth()[0] < 1:
            time.sleep(0.005)
        assert b.queue_depth()[0] == 1  # queue at its 2-row cap
        reply = servicer.score({"x": np.zeros(2, np.float32)})
        assert reply == {"error": "overloaded", "reason": "queue_full"}
        assert b._c_shed.value(reason="queue_full") > shed_before
        assert counting.kinds == ["overloaded"]
    finally:
        s.gate.set()
        b.stop()
        for t in waiters:
            t.join(10.0)


def test_slo_admission_sheds_past_the_budget():
    """One batch ahead x a way-over-SLO p99 estimate sheds; an IDLE
    plane admits even with the same poisoned estimate (admission
    predicts queue wait, never the request's own forward)."""
    s = FakeScorer()
    s.gate = threading.Event()
    s.latency_p99 = lambda: 10.0  # the histogram says: way over SLO
    b = MicroBatcher(s, max_batch=8, timeout_ms=1.0, p99_slo_ms=50.0)
    b.start()
    waiter = threading.Thread(target=_swallow, args=(lambda: b.submit(_req(1.0)),))
    try:
        waiter.start()
        assert s.entered.wait(10.0)  # batch 1 parked in its forward
        with pytest.raises(Overloaded) as exc:
            b.submit(_req(2.0))  # one batch ahead -> 10 s wait >> 50 ms
        assert exc.value.reason == "slo"
    finally:
        s.gate.set()
        b.stop()
        waiter.join(10.0)


class _CountingScorer:
    """note_error pass-through so the servicer tests can run against
    the FakeScorer (which has no metrics plumbing)."""

    def __init__(self, inner):
        self._inner = inner
        self.kinds = []

    def note_error(self, kind):
        self.kinds.append(kind)

    def score(self, feats):
        return self._inner.score(feats)


def _swallow(fn):
    try:
        fn()
    except Overloaded:
        pass


def test_error_kind_counter_on_degraded_paths(tmp_path):
    """The satellite fix: degraded-path failures land in
    ``edl_scorer_errors_total{kind=...}``, not only the reply payload."""
    scorer = Scorer(ps_client=None)
    try:
        servicer = ScorerServicer(scorer)
        c = scorer._c_errors
        bad_before = c.value(kind="bad_request")
        none_before = c.value(kind="no_model")
        reply = servicer.score({"_sctx": "meta-only"})
        assert "error" in reply
        assert c.value(kind="bad_request") == bad_before + 1
        reply = servicer.score({"x": np.zeros(2, np.float32)})
        assert "error" in reply  # no model installed yet
        assert c.value(kind="no_model") == none_before + 1
    finally:
        scorer.close()


# ---------------------------------------------------------------------------
# bitwise equivalence + swap/drain against the real scoring path
# ---------------------------------------------------------------------------


def _real_scorer(tmp_path):
    """Scorer over in-process PS shards, v1 exported and installed."""
    export_root = str(tmp_path / "exports")
    os.makedirs(export_root, exist_ok=True)
    _, params = _deepfm_params(seed=0)
    _export(export_root, params, 1)
    shards = _ps_shards(1)
    client, _cache = _client(shards)
    scorer = Scorer(ps_client=client, staleness_versions=2)
    scorer._edl_test_client = client  # _close_real joins the fan-out
    watcher = ModelDirectoryWatcher(export_root, scorer)
    assert watcher.poll_once() == 1
    return scorer, watcher, export_root


def _close_real(scorer):
    scorer.close()
    scorer._edl_test_client.close()


def test_batched_outputs_bitwise_equal_unbatched(tmp_path):
    """Per-request outputs from one coalesced, repeat-row-padded
    forward are bitwise identical to scoring each request alone from
    the same artifact — the acceptance-criteria pre-pass, in-process."""
    scorer, _watcher, _root = _real_scorer(tmp_path)
    try:
        requests = [_features(n=n, seed=n) for n in (3, 4, 5)]
        reference = [
            _as_np(scorer.score(f)[0]) for f in requests
        ]  # unbatched, one at a time

        b = MicroBatcher(scorer, max_batch=16, timeout_ms=50.0)
        b.start()
        try:
            batches_before = b._c_batches.value()
            results = [None] * len(requests)

            def call(i):
                results[i] = b.submit(requests[i])

            threads = [
                threading.Thread(target=call, args=(i,))
                for i in range(len(requests))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(30.0)
            # 3+4+5 = 12 rows -> ONE forward in the 16-bucket
            assert b._c_batches.value() == batches_before + 1
            for i, (out, version) in enumerate(results):
                assert version == 1
                _assert_bitwise_equal(out, reference[i], "request %d" % i)
        finally:
            b.stop()
    finally:
        _close_real(scorer)


def test_hot_swap_drains_inflight_batch_on_old_version(tmp_path):
    """An in-flight coalesced batch finishes on the version it
    acquired; the next batch scores the new version; the superseded
    version leaves the ledger once drained."""
    scorer, watcher, export_root = _real_scorer(tmp_path)
    _, params2 = _deepfm_params(seed=1)
    try:
        feats = _features(n=4, seed=0)
        scorer.score(feats)  # prepare + record the template

        v1_model = scorer.model()
        entered = threading.Event()
        proceed = threading.Event()
        real_predict = v1_model.predict

        def slow_predict(*a, **kw):
            entered.set()
            assert proceed.wait(10.0)
            return real_predict(*a, **kw)

        v1_model.predict = slow_predict

        b = MicroBatcher(scorer, max_batch=8, timeout_ms=1.0)
        b.start()
        try:
            first = {}

            def request_a():
                first["out"], first["version"] = b.submit(feats)

            ta = threading.Thread(target=request_a)
            ta.start()
            assert entered.wait(10.0)  # batch A parked inside v1
            _export(export_root, params2, 2)
            v1_model.predict = real_predict  # warm of v2 scores clean
            assert watcher.poll_once() == 2
            assert scorer.model_version == 2
            assert scorer.inflight_versions().get(1) == 1
            second = {}

            def request_b():
                second["out"], second["version"] = b.submit(feats)

            tb = threading.Thread(target=request_b)
            tb.start()
            proceed.set()
            ta.join(10.0)
            tb.join(10.0)
            assert first["version"] == 1  # finished on what it acquired
            assert second["version"] == 2  # next batch: new version
            assert scorer.wait_drained(1, timeout=10.0)
            assert 1 not in scorer.inflight_versions()
        finally:
            proceed.set()
            b.stop()
    finally:
        _close_real(scorer)


def test_stop_drains_queue_and_sheds_new_submits():
    """The SIGTERM discipline: ``stop(drain=True)`` answers everything
    already queued; submits arriving mid-drain shed ``draining``."""
    s = FakeScorer()
    s.gate = threading.Event()
    b = MicroBatcher(s, max_batch=2, timeout_ms=0.0, queue_rows=64)
    b.start()
    results, shed = [], []

    def call(i):
        try:
            results.append(b.submit(_req(float(i))))
        except Overloaded as e:
            shed.append(e.reason)

    callers = [threading.Thread(target=call, args=(i,)) for i in range(4)]
    for t in callers:
        t.start()
    assert s.entered.wait(10.0)  # batch 1 parked; the rest queued

    stopper = threading.Thread(target=lambda: b.stop(drain=True))
    stopper.start()
    deadline = time.monotonic() + 10.0
    late = []

    def late_call():
        try:
            b.submit(_req(99.0))
            late.append("scored")
        except Overloaded as e:
            late.append(e.reason)

    # wait until stop() has latched _stopping, then submit late
    while time.monotonic() < deadline and not b._stopping:
        time.sleep(0.005)
    threading.Thread(target=late_call).start()
    time.sleep(0.05)
    s.gate.set()  # release the parked forward; drain completes
    stopper.join(15.0)
    for t in callers:
        t.join(10.0)
    assert len(results) == 4, (results, shed)  # every queued req answered
    assert not shed
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not late:
        time.sleep(0.01)
    assert late == ["draining"], late


def test_warm_on_swap_pretraces_every_bucket(tmp_path):
    """``set_warm_batch_sizes`` + ``install``: a swap pre-traces every
    registered bucket shape, so no post-swap batch pays a compile."""
    scorer, watcher, export_root = _real_scorer(tmp_path)
    try:
        scorer.set_warm_batch_sizes([2, 4, 8])
        scorer.score(_features(n=4, seed=0))  # record the template

        from elasticdl_tpu.serving import scorer as scorer_mod

        warmed = []
        real_predict = scorer_mod.ScorerModel.predict

        def recording_predict(self, features, **kw):
            warmed.append(int(features["feature"].shape[0]))
            return real_predict(self, features, **kw)

        _, params2 = _deepfm_params(seed=1)
        _export(export_root, params2, 2)
        try:
            scorer_mod.ScorerModel.predict = recording_predict
            assert watcher.poll_once() == 2
        finally:
            scorer_mod.ScorerModel.predict = real_predict
        # every registered bucket warmed on the watcher's install
        assert set(warmed) >= {2, 4, 8}, warmed
    finally:
        _close_real(scorer)


def test_queue_depth_telemetry_collector():
    s = FakeScorer()
    s.gate = threading.Event()
    b = MicroBatcher(s, max_batch=4, timeout_ms=0.0)
    b.start()
    try:
        holder = threading.Thread(
            target=lambda: _swallow(lambda: b.submit(_req(1.0, rows=3)))
        )
        holder.start()
        assert s.entered.wait(10.0)
        samples = {
            name: value for name, _labels, value in b._collect()
        }
        assert samples["edl_scorer_queue_rows"] >= 3
        text = profiling.metrics.prometheus_text()
        assert "edl_scorer_queue_depth" in text
    finally:
        s.gate.set()
        b.stop()
        holder.join(10.0)
