"""ALLREDUCE strategy tests on the virtual 8-device CPU mesh.

Validates the TPU-native gradient plane: a jitted step over a sharded batch
must be numerically equivalent to single-device training (the collective
*is* the grads_to_wait barrier), and a mid-job mesh resize (membership
epoch) must preserve training state.
"""

import flax.linen as nn
import jax
import numpy as np
import optax
import pytest

from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.trainer import AllReduceTrainer
from elasticdl_tpu.training.step import TrainState, make_train_step


class TinyModel(nn.Module):
    @nn.compact
    def __call__(self, x, training=False):
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(1)(x)


def _loss(output, labels):
    return ((output - labels) ** 2).mean()


def _data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 8)).astype(np.float32)
    y = (x.sum(axis=1, keepdims=True) * 0.5).astype(np.float32)
    return x, y


def test_mesh_creation():
    mesh = create_mesh()
    assert mesh.devices.size == len(jax.devices())
    mesh2 = create_mesh({"data": 4}, devices=jax.devices()[:4])
    assert mesh2.devices.size == 4


def test_dp_step_matches_single_device():
    x, y = _data()
    model = TinyModel()
    opt = optax.sgd(0.1)

    trainer = AllReduceTrainer(model, _loss, opt, seed=0)
    assert trainer.num_devices == 8
    for step in range(4):
        trainer.train_step(x, y)

    # single-device replay with identical init and data
    from elasticdl_tpu.nn.model_api import init_variables, split_variables

    variables = init_variables(model, jax.random.PRNGKey(0), x[:1])
    params, state = split_variables(variables)
    ts = TrainState.create(params, state, opt)
    step_fn = make_train_step(model, _loss, opt)
    for step in range(4):
        rng = jax.random.fold_in(jax.random.PRNGKey(0), step + 1)
        ts, loss = step_fn(ts, x, y, rng)

    sharded = trainer.get_host_state()
    ref = jax.tree_util.tree_map(np.asarray, ts)
    flat_a = jax.tree_util.tree_leaves(sharded.params)
    flat_b = jax.tree_util.tree_leaves(ref.params)
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    assert trainer.version == 4


def test_elastic_resize_preserves_state():
    x, y = _data()
    model = TinyModel()
    trainer = AllReduceTrainer(model, _loss, optax.sgd(0.05), seed=1)
    l0 = float(trainer.train_step(x, y))
    trainer.train_step(x, y)
    before = trainer.get_host_state()

    # membership epoch: half the devices "die"
    trainer.resize(jax.devices()[:4])
    assert trainer.num_devices == 4
    after = trainer.get_host_state()
    for a, b in zip(
        jax.tree_util.tree_leaves(before.params),
        jax.tree_util.tree_leaves(after.params),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    l2 = float(trainer.train_step(x, y))
    l3 = float(trainer.train_step(x, y))
    assert np.isfinite(l2) and np.isfinite(l3)
    assert l3 < l0  # still learning after the resize
    assert trainer.version == 4

    # growth: devices come back
    trainer.resize(jax.devices())
    assert trainer.num_devices == 8
    l4 = float(trainer.train_step(x, y))
    assert np.isfinite(l4) and l4 <= l3 + 1e-3
    assert trainer.version == 5


def test_uneven_batch_rejected_or_handled():
    x, y = _data(n=30)  # 30 not divisible by 8
    model = TinyModel()
    trainer = AllReduceTrainer(model, _loss, optax.sgd(0.05))
    with pytest.raises(Exception):
        trainer.train_step(x, y)
