"""PS shard durability: snapshot format, atomicity, cadence, restore.

The write side (ps/snapshot.py) publishes write-to-temp + atomic-rename
snapshot directories with a versioned manifest; the restore side walks
them newest-valid-first. These tests pin the crash-consistency
contracts: a torn write is invisible, a corrupt newest snapshot falls
through to an older complete one, retention never deletes the newest
restorable state, and the store-side captures are lock-consistent
(docs/ps_recovery.md).
"""

import glob
import json
import os
import threading
import time

import numpy as np
import optax
import pytest

from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.ps.parameters import Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.ps.snapshot import (
    ShardSnapshotter,
    mint_shard_epoch,
    read_shard_snapshot,
    write_shard_snapshot,
)


def _store(version=5, rows=4, dim=3):
    p = Parameters()
    p.init_from_model(
        0,
        {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
        [],
    )
    from elasticdl_tpu.ps.parameters import EmbeddingTableInfo

    p.init_embedding_params([EmbeddingTableInfo("emb", dim, "zeros")])
    p.get_embedding_param("emb", np.arange(rows))  # materialize rows
    p.set_embedding_param(
        "emb",
        np.arange(rows),
        np.arange(rows * dim, dtype=np.float32).reshape(rows, dim),
    )
    p.version = version
    return p


def test_snapshot_roundtrip(tmp_path):
    p = _store(version=7)
    state = p.snapshot_state()
    d = write_shard_snapshot(str(tmp_path), state, ps_id=3, shard_epoch=9)
    assert os.path.basename(d) == "snap_v7"
    with open(os.path.join(d, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["version"] == 7
    assert manifest["ps_id"] == 3
    assert manifest["shard_epoch"] == 9

    p2 = Parameters()
    p2.restore_state(read_shard_snapshot(d))
    assert p2.initialized
    assert p2.version == 7
    np.testing.assert_array_equal(
        p2.get_non_embedding_param("w"), p.get_non_embedding_param("w")
    )
    np.testing.assert_array_equal(
        p2.get_embedding_param("emb", [0, 1, 2, 3]),
        p.get_embedding_param("emb", [0, 1, 2, 3]),
    )
    # lazy init of NEW rows still works with the recorded initializer
    fresh = p2.get_embedding_param("emb", [100])
    np.testing.assert_array_equal(fresh, np.zeros((1, 3), np.float32))


def test_restore_skips_torn_and_corrupt_snapshots(tmp_path):
    p = _store(version=4)
    write_shard_snapshot(str(tmp_path), p.snapshot_state())
    p.version = 8
    newest = write_shard_snapshot(str(tmp_path), p.snapshot_state())
    # corrupt the newest snapshot's dense payload
    with open(os.path.join(newest, "dense.npz"), "wb") as f:
        f.write(b"not an npz")
    # and leave a manifest-less torn temp dir lying around
    torn = os.path.join(str(tmp_path), "tmp-snap_v9.123")
    os.makedirs(torn)
    with open(os.path.join(torn, "dense.npz"), "wb") as f:
        f.write(b"torn")

    snap = ShardSnapshotter(str(tmp_path), every_versions=1)
    p2 = Parameters()
    try:
        assert snap.restore_into(p2) == 4
    finally:
        snap.close()
    assert p2.version == 4 and p2.initialized


def test_disabled_snapshotter_never_restores(tmp_path):
    """--ps_snapshot_versions 0 means durability OFF even when the dir
    holds a previous run's snapshots: restoring stale state into a
    durability-off job would silently ignore the worker's model push
    (init is first-write-wins)."""
    p = _store(version=3)
    write_shard_snapshot(str(tmp_path), p.snapshot_state())
    snap = ShardSnapshotter(str(tmp_path), every_versions=0)
    try:
        p2 = Parameters()
        assert snap.restore_into(p2) is None
        assert not p2.initialized
    finally:
        snap.close()


def test_restore_returns_none_on_fresh_dir(tmp_path):
    snap = ShardSnapshotter(str(tmp_path), every_versions=1)
    try:
        p = Parameters()
        assert snap.restore_into(p) is None
        assert not p.initialized
    finally:
        snap.close()


def test_retention_keeps_newest_and_reclaims_temp(tmp_path):
    snap = ShardSnapshotter(str(tmp_path), every_versions=1, keep=2)
    try:
        p = _store(version=0)
        for v in (1, 2, 3, 4):
            p.version = v
            snap.maybe_snapshot(p)
        snap.wait()
        kept = sorted(
            os.path.basename(d)
            for d in glob.glob(os.path.join(str(tmp_path), "snap_v*"))
        )
        assert kept == ["snap_v3", "snap_v4"]
        assert not glob.glob(os.path.join(str(tmp_path), "tmp-*"))
    finally:
        snap.close()


def test_cadence_only_snapshots_multiples(tmp_path):
    snap = ShardSnapshotter(str(tmp_path), every_versions=3, keep=8)
    try:
        p = _store(version=0)
        for v in range(1, 8):
            p.version = v
            snap.maybe_snapshot(p)
        snap.wait()
        kept = sorted(
            int(os.path.basename(d)[len("snap_v"):])
            for d in glob.glob(os.path.join(str(tmp_path), "snap_v*"))
        )
        assert kept == [3, 6]
    finally:
        snap.close()


def test_snapshot_now_republishes_same_version(tmp_path):
    """The SIGTERM drain may re-snapshot a version the cadence already
    published; the atomic replace must win, not error."""
    snap = ShardSnapshotter(str(tmp_path), every_versions=1)
    try:
        p = _store(version=2)
        snap.maybe_snapshot(p)
        snap.wait()
        p.set_embedding_param(
            "emb", [0], np.full((1, 3), 99.0, np.float32)
        )
        d = snap.snapshot_now(p)
        assert os.path.basename(d) == "snap_v2"
        state = read_shard_snapshot(d)
        p2 = Parameters()
        p2.restore_state(state)
        np.testing.assert_array_equal(
            p2.get_embedding_param("emb", [0]),
            np.full((1, 3), 99.0, np.float32),
        )
    finally:
        snap.close()


def test_uninitialized_store_never_snapshots(tmp_path):
    """A drain (or cadence fire) before the worker's first model push
    must publish NOTHING: restoring an empty snapshot as
    initialized=True would make first-write-wins ignore the worker's
    re-push forever."""
    snap = ShardSnapshotter(str(tmp_path), every_versions=1)
    try:
        p = Parameters()  # never initialized
        p.version = 3
        assert snap.snapshot_now(p) is None
        assert snap.maybe_snapshot(p) is False
        snap.wait()
        assert not glob.glob(os.path.join(str(tmp_path), "snap_v*"))
    finally:
        snap.close()


def test_cadence_interval_survives_skipped_marks(tmp_path):
    """Async applies can bump the version twice before either calls
    the hook, so an exact-multiple trigger would skip the mark and
    stretch the rollback bound; the interval trigger cannot skip."""
    snap = ShardSnapshotter(str(tmp_path), every_versions=4, keep=8)
    try:
        p = _store(version=0)
        p.version = 3
        assert snap.maybe_snapshot(p) is False
        # two concurrent applies landed: the hook only ever observes 5
        p.version = 5
        assert snap.maybe_snapshot(p) is True
        snap.wait()
        kept = sorted(
            int(os.path.basename(d)[len("snap_v"):])
            for d in glob.glob(os.path.join(str(tmp_path), "snap_v*"))
        )
        assert kept == [5]
    finally:
        snap.close()


def test_mint_shard_epoch_monotonic(tmp_path):
    e1 = mint_shard_epoch(str(tmp_path))
    e2 = mint_shard_epoch(str(tmp_path))
    e3 = mint_shard_epoch(str(tmp_path))
    assert e1 < e2 < e3
    # dir-less mint still yields a nonzero fresh id
    assert mint_shard_epoch(None) > 0


def test_servicer_snapshots_on_cadence_and_restores(tmp_path):
    """End-to-end through the servicer: async pushes cross the cadence,
    the snapshot publishes OFF the apply path, and a fresh
    servicer+store relaunch restores dense params, embedding rows AND
    optimizer slot tables."""
    p = Parameters()
    snap = ShardSnapshotter(str(tmp_path), every_versions=2)
    s = PserverServicer(
        p, 1, optax.adam(0.05), use_async=True,
        snapshotter=snap, shard_epoch=1,
    )
    s.push_model(
        {
            "version": 0,
            "params": [Tensor("w", np.ones((2, 2), np.float32))],
            "embedding_infos": [{"name": "emb", "dim": 4}],
        }
    )
    for i in range(4):
        s.push_gradient(
            {
                "model_version": i,
                "gradients": [
                    Tensor("w", np.full((2, 2), 0.25, np.float32)),
                    Tensor(
                        "emb",
                        np.ones((2, 4), np.float32),
                        indices=np.array([1, 5]),
                    ),
                ],
            }
        )
    snap.wait()
    snap.close()
    # adam created slot tables alongside the row table
    slot_tables = [
        name for name in p.embedding_params if name.startswith("emb-")
    ]
    assert slot_tables, "adam should have created slot tables"

    p2 = Parameters()
    snap2 = ShardSnapshotter(str(tmp_path), every_versions=2)
    try:
        assert snap2.restore_into(p2) == 4
    finally:
        snap2.close()
    assert sorted(p2.embedding_params) == sorted(p.embedding_params)
    for name in slot_tables:
        np.testing.assert_array_equal(
            p2.embedding_params[name].get([1, 5]),
            p.embedding_params[name].get([1, 5]),
        )
    np.testing.assert_array_equal(
        p2.get_non_embedding_param("w"), p.get_non_embedding_param("w")
    )


def test_to_named_arrays_holds_the_store_lock():
    """The R8 torn-read fix (ISSUE 10 satellite): the dense copy loop
    must run under Parameters._lock, so a concurrent async apply's
    rebind can never interleave with it."""
    p = _store()
    held = {"during": None}

    class RecordingLock:
        def __init__(self, inner):
            self._inner = inner
            self.locked = False

        def __enter__(self):
            self._inner.acquire()
            self.locked = True

        def __exit__(self, *exc):
            self.locked = False
            self._inner.release()

        def acquire(self, *a, **kw):
            out = self._inner.acquire(*a, **kw)
            self.locked = True
            return out

        def release(self):
            self.locked = False
            self._inner.release()

    rec = RecordingLock(threading.Lock())
    p._lock = rec

    class Probe(dict):
        def items(self):
            held["during"] = rec.locked
            return super().items()

    p.non_embedding_params = Probe(p.non_embedding_params)
    p.to_named_arrays()
    assert held["during"] is True

    # snapshot_state's dense capture runs under the same lock
    held["during"] = None
    p.snapshot_state()
    assert held["during"] is True


def test_snapshot_age_gauge_reports(tmp_path):
    from elasticdl_tpu.utils import profiling

    snap = ShardSnapshotter(str(tmp_path), ps_id=7, every_versions=1)
    try:
        p = _store(version=1)
        snap.maybe_snapshot(p)
        snap.wait()
        time.sleep(0.05)
        text = profiling.metrics.prometheus_text()
        lines = [
            ln
            for ln in text.splitlines()
            if ln.startswith("edl_ps_snapshot_age_seconds")
            and 'ps_id="7"' in ln
        ]
        # exactly ONE sample per name+labelset: a registered gauge
        # series alongside the collector would duplicate it (stuck at
        # its last .set value) and fail a strict Prometheus scrape
        assert len(lines) == 1, text
        samples = snap._collect_age()
        assert samples and samples[0][1] == {"ps_id": "7"}
        assert samples[0][2] >= 0.05
    finally:
        snap.close()
