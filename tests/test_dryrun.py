"""The driver's multichip dryrun at larger virtual worlds.

The driver itself runs dryrun_multichip(8); these rungs push the same
five passes (dp, dp x tp x sp, pp x dp + MoE, HBM-sharded embedding,
and combined pp x dp x vocab-sharded embedding) to 16 and 32 virtual
CPU devices — the re-exec path provisions the world in a subprocess."""

import sys

import pytest

sys.path.insert(0, __file__.rsplit("/tests/", 1)[0])


@pytest.mark.slow
@pytest.mark.parametrize("n_devices", [16, 32])
def test_dryrun_multichip_scales(n_devices):
    import __graft_entry__ as entry

    entry.dryrun_multichip(n_devices)
