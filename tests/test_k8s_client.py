"""k8s client + TensorBoard client + image builder against fake SDKs.

The reference gates its k8s tests on a live minikube and never tests the
image builder at all; faking the `kubernetes` and `docker` modules makes
pod/service construction, the label scheme, the tpu resource mapping,
the cluster-spec plugin, TB service exposure, and the docker build/push
flow all hermetically testable (round-1 verdict: ~700 untested lines).
"""

import os
import sys
import types
from types import SimpleNamespace

import pytest


class _KubeObj(SimpleNamespace):
    """Any V1* object: a namespace recording its constructor kwargs."""


class _FakeApiException(Exception):
    pass


class _FakeKubeClient(types.ModuleType):
    def __getattr__(self, name):
        if name == "CoreV1Api":
            return lambda: _CORE[0]
        if name == "rest":
            return SimpleNamespace(ApiException=_FakeApiException)

        def ctor(*args, **kwargs):
            return _KubeObj(_kind=name, **kwargs)

        return ctor


class FakeCoreV1Api:
    def __init__(self):
        self.pods = {}
        self.services = {}

    def create_namespaced_pod(self, namespace, pod):
        self.pods[pod.metadata.name] = pod
        return pod

    def create_namespaced_service(self, namespace, service):
        self.services[service.metadata.name] = service
        return service

    def read_namespaced_pod(self, name, namespace):
        if name not in self.pods:
            raise _FakeApiException(name)
        return self.pods[name]

    def read_namespaced_service(self, name, namespace):
        if name not in self.services:
            raise _FakeApiException(name)
        return self.services[name]

    def delete_namespaced_pod(self, name, namespace, body=None):
        self.pods.pop(name, None)

    def list_namespaced_pod(self, namespace, label_selector=None):
        return SimpleNamespace(items=list(self.pods.values()))


_CORE = [None]


@pytest.fixture
def fake_kube(monkeypatch):
    _CORE[0] = FakeCoreV1Api()
    kube = types.ModuleType("kubernetes")
    kube.client = _FakeKubeClient("kubernetes.client")
    config = types.ModuleType("kubernetes.config")
    config.load_kube_config = lambda: None
    config.load_incluster_config = lambda: None
    kube.config = config
    watch = types.ModuleType("kubernetes.watch")
    kube.watch = watch
    monkeypatch.setitem(sys.modules, "kubernetes", kube)
    monkeypatch.setitem(sys.modules, "kubernetes.client", kube.client)
    monkeypatch.setitem(sys.modules, "kubernetes.config", config)
    monkeypatch.setitem(sys.modules, "kubernetes.watch", watch)
    monkeypatch.delenv("KUBERNETES_SERVICE_HOST", raising=False)
    return _CORE[0]


def _client(**kw):
    from elasticdl_tpu.common.k8s_client import Client

    kw.setdefault("image_name", "img:latest")
    kw.setdefault("namespace", "default")
    kw.setdefault("job_name", "job1")
    return Client(**kw)


def test_watch_stream_stops_and_joins_on_close(fake_kube):
    """close() must stop the pod-event Watch and collect its thread —
    the R4 fix for the previously stop-less fire-and-forget watcher
    (k8s_instance_manager.stop_relaunch_and_remove_all_pods calls it)."""
    import time

    created = []

    class _FakeWatch:
        def __init__(self):
            self.stopped = False
            created.append(self)

        def stream(self, fn, namespace, label_selector=None):
            yield {"type": "ADDED"}
            while not self.stopped:
                time.sleep(0.01)

        def stop(self):
            self.stopped = True

    sys.modules["kubernetes.watch"].Watch = _FakeWatch
    events = []
    c = _client(event_callback=events.append)
    deadline = time.time() + 5.0
    while not events and time.time() < deadline:
        time.sleep(0.01)
    assert events == [{"type": "ADDED"}]
    thread = c._watch_thread
    c.close()
    assert created and created[0].stopped
    assert thread is not None and not thread.is_alive()
    c.close()  # idempotent


def test_worker_pod_labels_resources_and_tpu_mapping(fake_kube):
    c = _client()
    # master pod must exist for owner references
    fake_kube.pods["elasticdl-job1-master"] = _KubeObj(
        kind="Pod",
        api_version="v1",
        metadata=_KubeObj(name="elasticdl-job1-master", uid="u1"),
    )
    pod = c.create_worker(
        worker_id=3,
        resource_requests="cpu=2,memory=1024Mi,tpu=4",
        resource_limits="",
        pod_priority="",
        volume="",
        image_pull_policy="Always",
        command=["python"],
        args=["-m", "x"],
        restart_policy="Never",
        envs=None,
    )
    labels = pod.metadata.labels
    assert labels["elasticdl-job-name"] == "job1"
    assert labels["elasticdl-replica-type"] == "worker"
    assert labels["elasticdl-replica-index"] == "3"
    container = pod.spec.containers[0]
    req = container.resources.requests
    assert req["google.com/tpu"] == "4" and "tpu" not in req
    assert req["cpu"] == "2"
    # owner reference ties the pod to the master for cascade deletion
    assert pod.metadata.owner_references[0].name == "elasticdl-job1-master"


def test_ps_service_has_stable_dns_and_selector(fake_kube):
    c = _client()
    fake_kube.pods["elasticdl-job1-master"] = _KubeObj(
        kind="Pod",
        api_version="v1",
        metadata=_KubeObj(name="elasticdl-job1-master", uid="u1"),
    )
    c.create_ps(
        ps_id=1,
        resource_requests="cpu=1,memory=512Mi",
        resource_limits="",
        pod_priority="",
        volume="",
        image_pull_policy="Always",
        command=["python"],
        args=[],
        restart_policy="Never",
        envs=None,
    )
    c.create_ps_service(1)
    addr = c.get_ps_service_address(1)
    name = addr.split(":")[0].split(".")[0]
    svc = fake_kube.services[name]
    assert svc.spec.selector["elasticdl-replica-type"] == "ps"
    assert svc.spec.selector["elasticdl-replica-index"] == "1"
    # relaunch keeps the same service name (stable DNS)
    assert c.get_ps_service_address(1) == addr


def test_cluster_spec_plugin_rewrites_pods(fake_kube, tmp_path):
    plugin = tmp_path / "cluster_spec.py"
    plugin.write_text(
        "class _C:\n"
        "    def with_pod(self, pod):\n"
        "        pod.metadata.labels['patched'] = 'yes'\n"
        "        return pod\n"
        "    def with_service(self, service):\n"
        "        return service\n"
        "cluster = _C()\n"
    )
    c = _client(cluster_spec=str(plugin))
    fake_kube.pods["elasticdl-job1-master"] = _KubeObj(
        kind="Pod",
        api_version="v1",
        metadata=_KubeObj(name="elasticdl-job1-master", uid="u1"),
    )
    pod = c.create_worker(
        worker_id=0,
        resource_requests="cpu=1,memory=1Mi",
        resource_limits="",
        pod_priority="",
        volume="",
        image_pull_policy="Always",
        command=["python"],
        args=[],
        restart_policy="Never",
        envs=None,
    )
    assert pod.metadata.labels["patched"] == "yes"


def test_tensorboard_service_targets_master(fake_kube):
    from elasticdl_tpu.common.k8s_tensorboard_client import TensorBoardClient

    fake_kube.pods["elasticdl-job1-master"] = _KubeObj(
        kind="Pod",
        api_version="v1",
        metadata=_KubeObj(name="elasticdl-job1-master", uid="u1"),
    )
    tb = TensorBoardClient(
        image_name="img", namespace="default", job_name="job1"
    )
    tb.create_tensorboard_service()
    svc = fake_kube.services["tensorboard-job1"]
    assert svc.spec.type == "LoadBalancer"
    assert svc.spec.selector["elasticdl-replica-type"] == "master"
    assert svc.spec.ports[0].target_port == 6006


# -- image builder -----------------------------------------------------------


class FakeDockerAPIClient:
    instances = []

    def __init__(self, base_url=None, tls=None):
        self.base_url = base_url
        self.built = []
        self.pushed = []
        self.context = None
        FakeDockerAPIClient.instances.append(self)

    def build(self, path=None, tag=None, decode=True, rm=True):
        self.context = sorted(
            os.path.relpath(os.path.join(root, f), path)
            for root, _, files in os.walk(path)
            for f in files
        )
        self.built.append(tag)
        yield {"stream": "Step 1/5 : FROM python\n"}

    def push(self, tag, stream=True, decode=True):
        self.pushed.append(tag)
        yield {"status": "pushed"}


@pytest.fixture
def fake_docker(monkeypatch):
    mod = types.ModuleType("docker")
    mod.APIClient = FakeDockerAPIClient
    mod.tls = types.SimpleNamespace(
        TLSConfig=lambda client_cert: client_cert
    )
    monkeypatch.setitem(sys.modules, "docker", mod)
    FakeDockerAPIClient.instances = []
    return mod


def test_image_build_context_and_push(fake_docker, tmp_path):
    from elasticdl_tpu.image_builder import build_and_push_docker_image

    zoo = tmp_path / "zoo"
    (zoo / "m").mkdir(parents=True)
    (zoo / "m" / "model.py").write_text("x = 1\n")
    image = build_and_push_docker_image(
        model_zoo=str(zoo), docker_image_repository="reg.example.com/team"
    )
    assert image.startswith("reg.example.com/team/elasticdl:")
    client = FakeDockerAPIClient.instances[-1]
    assert client.built == [image] and client.pushed == [image]
    # the build context embeds the framework, the user zoo, a Dockerfile
    assert "Dockerfile" in client.context
    assert "model_zoo/m/model.py" in client.context
    assert any(
        f.startswith("framework/elasticdl_tpu/") for f in client.context
    )


def test_image_build_error_propagates(fake_docker, tmp_path, monkeypatch):
    from elasticdl_tpu.image_builder import build_and_push_docker_image

    def failing_build(self, **kw):
        yield {"error": "no space left"}

    monkeypatch.setattr(FakeDockerAPIClient, "build", failing_build)
    zoo = tmp_path / "zoo"
    zoo.mkdir()
    with pytest.raises(RuntimeError, match="no space left"):
        build_and_push_docker_image(
            model_zoo=str(zoo), docker_image_repository="r"
        )


def test_dockerfile_generation_variants():
    from elasticdl_tpu.image_builder import _generate_dockerfile

    plain = _generate_dockerfile("")
    assert plain.startswith("FROM python:3.11")
    assert "cluster_spec" not in plain
    full = _generate_dockerfile(
        "my/base:1", extra_pypi_index="http://pypi.internal",
        cluster_spec="spec.py",
    )
    assert "FROM my/base:1" in full
    assert "--extra-index-url http://pypi.internal" in full
    assert "COPY cluster_spec /cluster_spec" in full
