"""Pallas flash-attention kernel vs the XLA reference (interpret mode)."""

import jax
import numpy as np
import pytest

from elasticdl_tpu.ops.flash_attention import flash_attention
from elasticdl_tpu.parallel.ring_attention import reference_attention


def _qkv(b=2, l=64, h=2, d=16, seed=0):
    rng = np.random.default_rng(seed)
    shape = (b, l, h, d)
    return tuple(
        rng.standard_normal(shape).astype(np.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    got = np.asarray(
        jax.jit(
            lambda q, k, v: flash_attention(
                q, k, v, causal, 16, 16
            )
        )(q, k, v)
    )
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_flash_gradients():
    q, k, v = _qkv(l=32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, True, 16, 16) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-4
        )


def test_flash_rejects_nondivisible():
    q, k, v = _qkv(l=60)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, False, 16, 16)


def test_flash_backward_never_materializes_dense_scores():
    """The round-1 advisor finding: the old backward re-ran dense
    reference attention, materializing (L, L). The blockwise backward's
    jaxpr must contain no intermediate with two sequence-length dims
    (only (block, block) tiles inside the kernels)."""
    L = 64
    q, k, v = _qkv(l=L)

    def loss(q, k, v):
        return (flash_attention(q, k, v, True, 16, 16) ** 2).sum()

    jaxpr = jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    def no_dense(jx):
        for eqn in jx.eqns:
            for var in eqn.outvars:
                shape = getattr(var.aval, "shape", ())
                assert shape.count(L) < 2, (eqn.primitive, shape)
            for sub in eqn.params.values():
                if hasattr(sub, "jaxpr"):
                    no_dense(sub.jaxpr)

    no_dense(jaxpr.jaxpr)


def test_flash_gradients_bfloat16():
    import jax.numpy as jnp

    q, k, v = (x.astype(jnp.bfloat16) for x in _qkv(l=32))

    def loss_flash(q, k, v):
        return (
            flash_attention(q, k, v, True, 16, 16).astype(jnp.float32) ** 2
        ).sum()

    def loss_ref(q, k, v):
        return (
            reference_attention(q, k, v, causal=True).astype(jnp.float32)
            ** 2
        ).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32),
            np.asarray(b, dtype=np.float32),
            rtol=0.1,
            atol=0.1,
        )


def test_flash_with_lse_merges_like_ring():
    """(out, lse) pairs from two K/V halves merged with the logsumexp
    rule must equal attention over the full K/V — the property ring
    attention's per-block fused path relies on."""
    import jax.numpy as jnp

    from elasticdl_tpu.ops.flash_attention import flash_attention_with_lse

    q, k, v = _qkv(l=32)
    half = 16
    o1, l1 = flash_attention_with_lse(q, k[:, :half], v[:, :half], False, 16, 16)
    o2, l2 = flash_attention_with_lse(q, k[:, half:], v[:, half:], False, 16, 16)
    lse = jnp.logaddexp(l1, l2)  # (B, H, L)
    w1 = jnp.exp(l1 - lse).transpose(0, 2, 1)[..., None]
    w2 = jnp.exp(l2 - lse).transpose(0, 2, 1)[..., None]
    merged = o1 * w1 + o2 * w2
    want = reference_attention(q, k, v)
    np.testing.assert_allclose(
        np.asarray(merged), np.asarray(want), rtol=2e-4, atol=2e-5
    )


def test_flash_lse_cotangent_propagates():
    """A loss that uses the lse output (e.g. a z-loss) must produce the
    same gradients as the dense logsumexp."""
    import jax.numpy as jnp

    from elasticdl_tpu.ops.flash_attention import flash_attention_with_lse

    q, k, v = _qkv(l=32)
    scale = q.shape[-1] ** -0.5

    def loss_flash(q, k, v):
        out, lse = flash_attention_with_lse(q, k, v, False, 16, 16)
        return (out ** 2).sum() + 0.1 * (lse ** 2).sum()

    def loss_ref(q, k, v):
        out = reference_attention(q, k, v)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        return (out ** 2).sum() + 0.1 * (lse ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-4
        )
