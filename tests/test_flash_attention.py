"""Pallas flash-attention kernel vs the XLA reference (interpret mode)."""

import jax
import numpy as np
import pytest

from elasticdl_tpu.ops.flash_attention import flash_attention
from elasticdl_tpu.parallel.ring_attention import reference_attention


def _qkv(b=2, l=64, h=2, d=16, seed=0):
    rng = np.random.default_rng(seed)
    shape = (b, l, h, d)
    return tuple(
        rng.standard_normal(shape).astype(np.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference(causal):
    q, k, v = _qkv()
    got = np.asarray(
        jax.jit(
            lambda q, k, v: flash_attention(
                q, k, v, causal, 16, 16
            )
        )(q, k, v)
    )
    want = np.asarray(reference_attention(q, k, v, causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_flash_gradients():
    q, k, v = _qkv(l=32)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, True, 16, 16) ** 2).sum()

    def loss_ref(q, k, v):
        return (reference_attention(q, k, v, causal=True) ** 2).sum()

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-4
        )


def test_flash_rejects_nondivisible():
    q, k, v = _qkv(l=60)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, False, 16, 16)
