"""Tensor codec tests (parity: reference tests/tensor_test.py)."""

import unittest

import numpy as np

from elasticdl_tpu.common.tensor import (
    Tensor,
    deserialize_tensor,
    deserialize_tensors,
    named_arrays_to_pytree,
    pytree_to_named_arrays,
    serialize_tensor,
    serialize_tensors,
)


class TensorCodecTest(unittest.TestCase):
    def test_dense_roundtrip(self):
        arr = np.random.randn(4, 7).astype(np.float32)
        t = Tensor("w", arr)
        t2 = deserialize_tensor(serialize_tensor(t))
        self.assertEqual(t2.name, "w")
        np.testing.assert_array_equal(t2.values, arr)
        self.assertIsNone(t2.indices)

    def test_sparse_roundtrip(self):
        arr = np.random.randn(3, 5).astype(np.float32)
        idx = np.array([9, 2, 4], dtype=np.int64)
        t2 = deserialize_tensor(serialize_tensor(Tensor("e", arr, idx)))
        self.assertTrue(t2.is_indexed_slices())
        np.testing.assert_array_equal(t2.values, arr)
        np.testing.assert_array_equal(t2.indices, idx)

    def test_dtypes(self):
        for dtype in (np.int32, np.int64, np.float64, np.float16, np.bool_):
            arr = np.ones((2, 2), dtype=dtype)
            t2 = deserialize_tensor(serialize_tensor(Tensor("x", arr)))
            self.assertEqual(t2.values.dtype, np.dtype(dtype))

    def test_bfloat16(self):
        import ml_dtypes

        arr = np.ones((3, 3), dtype=ml_dtypes.bfloat16)
        t2 = deserialize_tensor(serialize_tensor(Tensor("b", arr)))
        self.assertEqual(t2.values.dtype, np.dtype(ml_dtypes.bfloat16))

    def test_add_dense(self):
        a = Tensor("x", np.ones((2, 2), np.float32))
        b = Tensor("x", 2 * np.ones((2, 2), np.float32))
        np.testing.assert_array_equal(
            (a + b).values, 3 * np.ones((2, 2), np.float32)
        )

    def test_add_sparse_concatenates(self):
        a = Tensor("e", np.ones((2, 3), np.float32), np.array([0, 1]))
        b = Tensor("e", np.ones((1, 3), np.float32), np.array([5]))
        c = a + b
        self.assertEqual(c.values.shape, (3, 3))
        np.testing.assert_array_equal(c.indices, [0, 1, 5])

    def test_multi_tensor_stream(self):
        ts = [
            Tensor("a", np.arange(6, dtype=np.float32).reshape(2, 3)),
            Tensor("b", np.arange(4, dtype=np.int64), np.array([1, 3, 5, 7])),
        ]
        out = deserialize_tensors(serialize_tensors(ts))
        self.assertEqual([t.name for t in out], ["a", "b"])
        np.testing.assert_array_equal(out[1].indices, [1, 3, 5, 7])

    def test_pytree_bridge(self):
        tree = {
            "dense": {"kernel": np.ones((3, 4), np.float32), "bias": np.zeros(4, np.float32)},
            "out": {"kernel": np.full((4, 2), 2.0, np.float32)},
        }
        named = pytree_to_named_arrays(tree)
        self.assertIn("dense/kernel", named)
        restored = named_arrays_to_pytree(named, tree)
        np.testing.assert_array_equal(
            restored["out"]["kernel"], tree["out"]["kernel"]
        )


if __name__ == "__main__":
    unittest.main()
