"""Sharded-PS tests over real loopback gRPC + in-process E2E.

Parity: reference tests/worker_ps_interaction_test.py (two ParameterServers
on localhost with real channels, PS restart mid-job) and
pserver_servicer_test.py (push/pull, sync/async gradient paths).
"""

import numpy as np
import pytest

from elasticdl_tpu.common.constants import JobType
from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.ps.parameter_server import ParameterServer
from elasticdl_tpu.ps.parameters import EmbeddingTableInfo, Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.worker.ps_client import BoundPS, PSClient
from elasticdl_tpu.worker.worker import Worker
from tests.test_utils import (
    MODEL_ZOO_PATH,
    DatasetName,
    PserverArgs,
    create_recordio_file,
)


@pytest.fixture
def two_ps_over_grpc():
    servers = []
    addrs = []
    for ps_id in range(2):
        args = PserverArgs(
            grads_to_wait=1,
            use_async=True,
            port=0,
            model_zoo=MODEL_ZOO_PATH,
            model_def="mnist_functional_api.mnist_functional_api.custom_model",
        )
        args.ps_id = ps_id
        args.lr_staleness_modulation = False
        ps = ParameterServer(args)
        ps.prepare()
        servers.append(ps)
        addrs.append("localhost:%d" % ps._server._edl_port)
    yield servers, addrs
    for ps in servers:
        ps.stop()


def test_push_pull_over_real_grpc(two_ps_over_grpc):
    servers, addrs = two_ps_over_grpc
    client = PSClient([BoundPS(a) for a in addrs])

    ok, version, named = client.pull_dense()
    assert not ok  # not initialized yet

    params = {
        "dense/kernel": np.ones((3, 2), np.float32),
        "dense/bias": np.zeros((2,), np.float32),
        "conv/kernel": np.full((2, 2), 2.0, np.float32),
    }
    client.push_model(params, [EmbeddingTableInfo("emb", 4)])

    ok, version, named = client.pull_dense()
    assert ok and version == 0
    assert set(named) == set(params)
    for k in params:
        np.testing.assert_array_equal(named[k], params[k])

    # shards actually partition the variables
    sizes = [len(ps.parameters.non_embedding_params) for ps in servers]
    assert sum(sizes) == 3 and all(s < 3 for s in sizes)

    # sparse rows scatter by id % 2
    rows = client.pull_embedding_vectors("emb", np.array([0, 1, 2, 5]))
    assert rows.shape == (4, 4)
    assert len(servers[0].parameters.embedding_params["emb"]) == 2  # 0, 2
    assert len(servers[1].parameters.embedding_params["emb"]) == 2  # 1, 5

    # gradient push: async applies immediately on each shard
    accepted, version = client.push_gradient(
        {k: np.full_like(v, 0.5) for k, v in params.items()},
        [Tensor("emb", np.ones((2, 4), np.float32), indices=[0, 1])],
        0,
    )
    assert accepted and version == 1
    ok, _, after = client.pull_dense()
    # optimizer(lr=0.1) SGD -> params - 0.05
    np.testing.assert_allclose(
        after["dense/kernel"], params["dense/kernel"] - 0.05, rtol=1e-5
    )
    got = client.pull_embedding_vectors("emb", np.array([0, 1]))
    np.testing.assert_allclose(got, rows[:2] - 0.1, rtol=1e-5)


def test_ps_restart_reinit(two_ps_over_grpc):
    """A relaunched PS re-initializes from the next worker push
    (reference worker_ps_interaction_test.py:84-91)."""
    servers, addrs = two_ps_over_grpc
    client = PSClient([BoundPS(a) for a in addrs])
    params = {"w": np.ones((2,), np.float32)}
    client.push_model(params, [])
    ok, _, _ = client.pull_dense()
    assert ok

    # simulate a PS pod loss + relaunch with the same address semantics
    shard = None
    for i, ps in enumerate(servers):
        if ps.parameters.non_embedding_params:
            shard = i
            break
    servers[shard].parameters = Parameters()
    servers[shard].servicer._parameters = servers[shard].parameters

    ok, _, _ = client.pull_dense()
    assert not ok  # shard lost its state
    client.push_model(params, [])  # worker re-pushes (init-once per shard)
    ok, _, named = client.pull_dense()
    assert ok
    np.testing.assert_array_equal(named["w"], params["w"])


def test_sync_ps_grads_to_wait():
    p = Parameters()
    import optax

    s = PserverServicer(p, grads_to_wait=2, optimizer=optax.sgd(1.0))
    s.push_model(
        {"version": 0, "params": [Tensor("w", np.ones((2,), np.float32))]}
    )
    r1 = s.push_gradient(
        {"model_version": 0, "gradients": [Tensor("w", np.full((2,), 0.5, np.float32))]}
    )
    assert r1["accepted"] and r1["version"] == 0  # accumulated, not applied
    r2 = s.push_gradient(
        {"model_version": 0, "gradients": [Tensor("w", np.full((2,), 1.5, np.float32))]}
    )
    assert r2["accepted"] and r2["version"] == 1
    np.testing.assert_allclose(p.non_embedding_params["w"], 0.0)  # avg=1.0
    # stale push rejected
    r3 = s.push_gradient({"model_version": 0, "gradients": []})
    assert not r3["accepted"]


def test_worker_e2e_with_sharded_ps():
    """Full train/eval job: tasks from the master, params on 2 PS shards."""
    import optax

    from elasticdl_tpu.master.checkpoint_service import CheckpointService
    from elasticdl_tpu.master.evaluation_service import EvaluationService
    from elasticdl_tpu.common.model_utils import (
        get_module_file_path,
        load_module,
    )
    from tests.in_process_master import InProcessMaster

    model_def = "mnist_functional_api.mnist_functional_api.custom_model"
    ps_servicers = [
        PserverServicer(
            Parameters(), grads_to_wait=1, optimizer=optax.sgd(0.01),
            use_async=True,
        )
        for _ in range(2)
    ]

    class InProcessPS:
        def __init__(self, servicer):
            self._s = servicer

        def __getattr__(self, name):
            return getattr(self._s, name)

    worker = Worker(
        worker_id=1,
        job_type=JobType.TRAINING_WITH_EVALUATION,
        minibatch_size=16,
        model_zoo=MODEL_ZOO_PATH,
        model_def=model_def,
        ps_client=PSClient([InProcessPS(s) for s in ps_servicers]),
    )
    f = create_recordio_file(128, DatasetName.IMAGE_DEFAULT, (28, 28))
    shards = {f: (0, 128)}
    task_d = TaskDispatcher(shards, shards, {}, 64, 1)
    module = load_module(
        get_module_file_path(MODEL_ZOO_PATH, model_def)
    ).__dict__
    ckpt = CheckpointService("", 0, 0, True)
    ev = EvaluationService(
        ckpt, None, task_d, 0, 0, 0, False, module["eval_metrics_fn"]
    )
    task_d.set_evaluation_service(ev)
    master = MasterServicer(
        1,
        16,
        None,  # master only dispatches tasks; params live on the PS fleet
        task_d,
        checkpoint_service=ckpt,
        evaluation_service=ev,
        use_async=True,
    )
    worker._stub = InProcessMaster(master)
    worker.run()
    assert task_d.finished()
    # both shards saw dense params and versions advanced on the PS side
    total_vars = sum(
        len(s._parameters.non_embedding_params) for s in ps_servicers
    )
    assert total_vars > 0
    assert all(s._parameters.version > 0 for s in ps_servicers)
