"""Resource/volume/args parser tests.

Parity: reference tests/args_test.py and the parser halves of
k8s_client_test.py that need no cluster.
"""

import pytest

from elasticdl_tpu.common.args import (
    build_arguments_from_parsed_result,
    parse_envs,
    parse_master_args,
    parse_ps_args,
    parse_worker_args,
)
from elasticdl_tpu.common.k8s_resource import parse_resource
from elasticdl_tpu.common.k8s_volume import parse_volume


def test_parse_resource():
    parsed = parse_resource("cpu=1,memory=4096Mi,tpu=8")
    assert parsed == {"cpu": "1", "memory": "4096Mi", "tpu": "8"}
    with pytest.raises(ValueError):
        parse_resource("cpu=1,cpu=2")
    with pytest.raises(ValueError):
        parse_resource("flux_capacitors=2")
    with pytest.raises(ValueError):
        parse_resource("memory=lots")
    assert parse_resource("google.com/tpu=4") == {"google.com/tpu": "4"}


def test_parse_volume():
    volume, mount = parse_volume("claim_name=c1,mount_path=/data")
    assert volume["persistent_volume_claim"]["claim_name"] == "c1"
    assert mount["mount_path"] == "/data"
    volume, mount = parse_volume("host_path=/mnt,mount_path=/data")
    assert volume["host_path"]["path"] == "/mnt"
    with pytest.raises(ValueError):
        parse_volume("claim_name=c1")
    assert parse_volume("") is None


def test_parse_envs():
    assert parse_envs("a=1,b=x") == {"a": "1", "b": "x"}
    assert parse_envs("") == {}


def test_master_args_async_forces_grads_to_wait():
    args = parse_master_args(
        [
            "--job_name", "j", "--model_zoo", "z", "--model_def", "m",
            "--minibatch_size", "4", "--training_data", "d",
            "--use_async", "true", "--grads_to_wait", "8",
        ]
    )
    assert args.use_async and args.grads_to_wait == 1


def test_master_args_sync_forces_get_model_steps():
    args = parse_master_args(
        [
            "--job_name", "j", "--model_zoo", "z", "--model_def", "m",
            "--minibatch_size", "4", "--training_data", "d",
            "--get_model_steps", "5",
        ]
    )
    assert args.get_model_steps == 1


def test_ps_and_worker_args():
    args = parse_ps_args(
        ["--ps_id", "1", "--port", "2222", "--model_zoo", "z",
         "--model_def", "m"]
    )
    assert args.ps_id == 1 and args.port == 2222
    args = parse_worker_args(
        ["--worker_id", "3", "--job_type", "training_only",
         "--model_zoo", "z", "--model_def", "m", "--minibatch_size", "8"]
    )
    assert args.worker_id == 3 and args.distribution_strategy


def test_arg_relay_roundtrip():
    """Master re-serializes args into child-pod argv (reference
    args.py:622-643)."""
    args = parse_master_args(
        [
            "--job_name", "j", "--model_zoo", "z", "--model_def", "m",
            "--minibatch_size", "4", "--training_data", "d",
            "--use_async", "true",
        ]
    )
    argv = build_arguments_from_parsed_result(args)
    assert "--use_async" in argv
    assert argv[argv.index("--use_async") + 1] == "true"
    assert argv[argv.index("--minibatch_size") + 1] == "4"
