"""Master-central embedding tables must survive checkpoint/restore.

The reference never checkpointed embedding tables (they lived in external
Redis; TODO at reference model_handler.py:208-216). Here the store is
in-master, so checkpoints carry the tables (servicer._export/_import)."""

import numpy as np
import optax

from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.master.checkpoint_service import CheckpointService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.ps.parameters import EmbeddingTableInfo


def _dispatcher():
    return TaskDispatcher({"s": (0, 4)}, {}, {}, 4, 1)


def test_embedding_tables_roundtrip_through_checkpoint(tmp_path):
    ckpt = CheckpointService(str(tmp_path), 1, 5, False)
    master = MasterServicer(
        1,
        4,
        optax.sgd(0.5),
        _dispatcher(),
        checkpoint_service=ckpt,
        use_async=True,
    )
    master.report_variable({"w": np.ones((2, 2), np.float32)})
    master.push_embedding_info([EmbeddingTableInfo("emb", 3)])
    rows_before = master.pull_embedding_vectors("emb", [4, 9])
    master.report_gradient(
        [
            Tensor("w", np.zeros((2, 2), np.float32)),
            Tensor(
                "emb",
                np.ones((2, 3), np.float32),
                indices=[4, 9],
            ),
        ],
        0,
    )
    rows_after = master.pull_embedding_vectors("emb", [4, 9])
    np.testing.assert_allclose(rows_after, rows_before - 0.5, rtol=1e-5)

    path = ckpt.get_checkpoint_path(1)
    assert path

    restored = MasterServicer(
        1,
        4,
        optax.sgd(0.5),
        _dispatcher(),
        checkpoint_filename_for_init=path,
        use_async=True,
    )
    assert restored.get_model_version() == 1
    got = restored.pull_embedding_vectors("emb", [4, 9])
    np.testing.assert_allclose(got, rows_after, rtol=1e-6)
    # dense params restored without embedding-export keys leaking in
    _, named = restored.get_model(1)
    assert set(named) == {"w"}


def test_save_model_export_carries_master_kv_tables(tmp_path):
    """The SAVE_MODEL gap: get_model strips embedding-export keys by
    design, so a master-central-storage export artifact must pull the
    tables explicitly (worker._process_save_model_task_if_needed ->
    stub.export_embedding_tables -> export_model extra_named) or every
    table silently vanishes from the artifact."""
    import os

    from elasticdl_tpu.common.export import export_model, load_export

    master = MasterServicer(
        1,
        4,
        optax.sgd(0.5),
        _dispatcher(),
        use_async=True,
    )
    master.report_variable({"w": np.ones((2, 2), np.float32)})
    master.push_embedding_info([EmbeddingTableInfo("emb", 3)])
    master.report_gradient(
        [
            Tensor("w", np.zeros((2, 2), np.float32)),
            Tensor("emb", np.ones((2, 3), np.float32), indices=[4, 9]),
        ],
        0,
    )
    rows = master.pull_embedding_vectors("emb", [4, 9])

    # the worker's SAVE_MODEL path: dense params from get_model (which
    # must NOT carry the tables), tables from the explicit export RPC
    _, dense = master.get_model(master.get_model_version())
    assert set(dense) == {"w"}
    extra = master.export_embedding_tables()
    assert {
        "edl_embedding:emb:ids",
        "edl_embedding:emb:rows",
    } <= set(extra)

    export_dir = str(tmp_path / "exp")
    manifest = export_model(
        export_dir,
        dense,
        version=master.get_model_version(),
        extra_named=extra,
    )
    assert "edl_embedding:emb:rows" in manifest["extra_named"]
    # the orbax/serving params stay dense-only...
    loaded = load_export(export_dir)
    assert set(loaded.params) == {"w"}

    # ...while the legacy checkpoint member re-seeds a fresh master's
    # embedding store through checkpoint_filename_for_init
    restored = MasterServicer(
        1,
        4,
        optax.sgd(0.5),
        _dispatcher(),
        checkpoint_filename_for_init=os.path.join(
            export_dir, "model.chkpt"
        ),
        use_async=True,
    )
    np.testing.assert_allclose(
        restored.pull_embedding_vectors("emb", [4, 9]), rows, rtol=1e-6
    )
