"""ALLREDUCE worker E2E: task dispatch + on-device DP + elastic resize.

The BASELINE 'cifar10_subclass allreduce / elastic allreduce' configs:
training driven by master tasks while parameters stay on the mesh; a
mid-job mesh shrink (half the devices "lost") must not lose progress.
"""

import jax
import numpy as np

from elasticdl_tpu.common.constants import JobType
from elasticdl_tpu.master.checkpoint_service import CheckpointService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.worker.allreduce_worker import AllReduceWorker
from tests.in_process_master import InProcessMaster
from tests.test_utils import MODEL_ZOO_PATH, DatasetName, create_recordio_file


def _job(num_epochs=2):
    f = create_recordio_file(128, DatasetName.IMAGE_DEFAULT, (28, 28))
    shards = {f: (0, 128)}
    task_d = TaskDispatcher(shards, {}, {}, 64, num_epochs)
    master = MasterServicer(
        1,
        16,
        None,  # pure control plane: no parameters on the master
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=True,
    )
    worker = AllReduceWorker(
        worker_id=0,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=16,
        model_zoo=MODEL_ZOO_PATH,
        model_def="mnist_subclass.mnist_subclass.CustomModel",
        stub=InProcessMaster(master),
    )
    return task_d, master, worker


def test_allreduce_worker_completes_job():
    task_d, master, worker = _job()
    losses = worker.run()
    assert task_d.finished()
    # 128 records x 2 epochs / batch 16 = 16 on-device steps
    assert worker.trainer.version == 16
    assert len(losses) == 16
    assert all(np.isfinite(losses))


def test_allreduce_worker_accum_survives_tail_batches():
    """Tail batches must pad to devices x accum_steps, not just devices
    — otherwise the microbatch split rejects every task's last batch and
    the job wedges in a fail-report/requeue loop."""
    f = create_recordio_file(120, DatasetName.IMAGE_DEFAULT, (28, 28))
    task_d = TaskDispatcher({f: (0, 120)}, {}, {}, 64, 1)
    master = MasterServicer(
        1,
        16,
        None,
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=True,
    )
    worker = AllReduceWorker(
        worker_id=0,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=16,
        model_zoo=MODEL_ZOO_PATH,
        model_def="mnist_subclass.mnist_subclass.CustomModel",
        stub=InProcessMaster(master),
        accum_steps=4,
    )
    losses = worker.run()
    assert task_d.finished()
    # 120 records / batch 16 = 8 batches (incl. one 8-row tail)
    assert worker.trainer.version == 8
    assert all(np.isfinite(losses))


def test_allreduce_worker_elastic_resize_mid_job():
    task_d, master, worker = _job(num_epochs=1)
    # consume the first dataset round manually: train a few batches then
    # shrink the mesh, as a membership epoch would
    first = [False]

    original = worker._train_batch

    def train_and_shrink(batch):
        result = original(batch)
        if not first[0]:
            first[0] = True
            worker.trainer.resize(jax.devices()[:4])
        return result

    worker._train_batch = train_and_shrink
    worker.run()
    assert task_d.finished()
    assert worker.trainer.num_devices == 4
    assert worker.trainer.version == 8


def test_allreduce_rejects_eval_and_predict_only_jobs():
    import pytest

    for job_type in (JobType.EVALUATION_ONLY, JobType.PREDICTION_ONLY):
        with pytest.raises(NotImplementedError, match="ParameterServer"):
            AllReduceWorker(
                worker_id=0,
                job_type=job_type,
                minibatch_size=16,
                model_zoo=MODEL_ZOO_PATH,
                model_def="mnist_subclass.mnist_subclass.CustomModel",
                stub=None,
            )


def test_allreduce_worker_resumes_from_sharded_checkpoint(tmp_path):
    """Job 2 on the same checkpoint dir must CONTINUE job 1's version
    counter (restore at first batch), not silently re-initialize and
    overwrite job 1's checkpoint directories."""
    from elasticdl_tpu.common.sharded_checkpoint import (
        ShardedCheckpointManager,
    )

    ckpt_dir = str(tmp_path / "ckpt")

    def run_job():
        f = create_recordio_file(128, DatasetName.IMAGE_DEFAULT, (28, 28))
        task_d = TaskDispatcher({f: (0, 128)}, {}, {}, 64, 1)
        master = MasterServicer(
            1,
            16,
            None,
            task_d,
            checkpoint_service=CheckpointService("", 0, 0, False),
            use_async=True,
        )
        worker = AllReduceWorker(
            worker_id=0,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=16,
            model_zoo=MODEL_ZOO_PATH,
            model_def="mnist_subclass.mnist_subclass.CustomModel",
            stub=InProcessMaster(master),
            checkpoint_dir=ckpt_dir,
            checkpoint_steps=4,
        )
        worker.run()
        assert task_d.finished()
        return worker.trainer.version

    v1 = run_job()
    assert v1 == 8  # 128 records / batch 16
    versions_after_1 = ShardedCheckpointManager(ckpt_dir).versions()
    assert versions_after_1, "job 1 wrote no checkpoints"

    v2 = run_job()
    # job 2 restored job 1's final state: its counter continued
    assert v2 == v1 + 8, (v1, v2)
    versions_after_2 = ShardedCheckpointManager(ckpt_dir).versions()
    assert max(versions_after_2) > max(versions_after_1)
