"""Elastic multi-process allreduce: the north-star behavior for the
collective plane (BASELINE.md config 3).

Rungs here mirror the reference test ladder (SURVEY.md §4.3): unit tests
for the membership epochs and the weighted lockstep step in one process,
then real OS-process jobs over gloo CPU collectives — including killing a
worker mid-job and asserting the job completes with all records
processed, i.e. ``test_elastic_job.py`` but for ALLREDUCE.
"""

import os
import sys
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from elasticdl_tpu.common.args import parse_master_args
from elasticdl_tpu.master.local_instance_manager import LocalInstanceManager
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.master.membership_service import MembershipService
from tests.test_utils import MODEL_ZOO_PATH, DatasetName, create_recordio_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- rung 1: units -----------------------------------------------------------


def _poll_ready(m, worker_id):
    """Poll until the two-phase formation reports ready (bounded)."""
    for _ in range(10):
        w = m.get_world(worker_id)
        if w["ready"]:
            return w
    raise AssertionError("world never became ready for %d" % worker_id)


def test_membership_epochs():
    m = MembershipService(expected_workers=2, form_grace_secs=60)
    assert m.get_world(0)["ready"] is False  # quorum not met
    # two-phase: after the quorum registers, ready only once both
    # members have polled (confirmed) the new epoch
    m.get_world(1)
    m.get_world(0)  # both members confirm the freshly-bumped epoch
    w = _poll_ready(m, 1)
    assert w["num_processes"] == 2
    assert _poll_ready(m, 0)["process_id"] == 0
    assert _poll_ready(m, 1)["process_id"] == 1
    epoch = w["epoch"]

    # death shrinks the world and bumps the epoch
    m.remove(0)
    w1 = _poll_ready(m, 1)
    assert w1["epoch"] > epoch
    assert w1["num_processes"] == 1 and w1["process_id"] == 0

    # a relaunch (higher id) parks in the lobby while the survivor's
    # world is still forming — growth must not strand members in a stale
    # initialize barrier
    m.get_world(2)
    assert m.get_world(2)["ready"] is False
    w_mid = m.get_world(1, awaiting=False)  # survivor trains: formed
    # formation complete -> the parked joiner triggers the growth bump
    assert w_mid["epoch"] > w1["epoch"] or not w_mid.get("ready", True)
    m.get_world(1)  # survivor confirms the grown world
    m.get_world(2)
    w2 = _poll_ready(m, 2)
    assert w2["epoch"] > w1["epoch"]
    assert w2["num_processes"] == 2 and w2["process_id"] == 1
    assert _poll_ready(m, 1)["process_id"] == 0

    # coordinator address rotates with the epoch
    assert _poll_ready(m, 1)["coordinator"] != w["coordinator"]

    # once the grown world is training, a further joiner bumps immediately
    m.get_world(1, awaiting=False)
    m.get_world(2, awaiting=False)
    e2 = m.epoch
    m.get_world(3)
    assert m.epoch > e2


def test_membership_dead_list_only_real_crashes():
    """The ``dead`` list drives the survivors' wedge-escape abort
    probe: ANNOUNCED protocol-clean exits (rc 0 completion after the
    worker's own leave_comm_world, rc 75 after the drain announcement)
    must stay off it, every unannounced exit — whatever the code —
    must land on it, and entries are pruned once no lagging member's
    world can reference them."""
    m = MembershipService(expected_workers=3, form_grace_secs=60)
    for w in (0, 1, 2):
        m.get_world(w)
    assert m.get_world(0)["dead"] == []

    # announced clean completion: worker.main announces after global
    # quiescence, then the watch sees rc 0 — not listed dead
    m.remove(0, departing=True)
    m.remove(0, exit_code=0)
    assert 0 not in m.get_world(1)["dead"]

    # graceful drain announces departing first; the instance manager's
    # later rc-75 watch event must not retroactively mark it dead
    m.remove(1, departing=True)
    m.remove(1, exit_code=75)  # watch sees rc 75
    assert 1 not in m.get_world(2)["dead"]

    # a real crash IS listed (the abort probe keys on exactly this)
    m.remove(2)
    assert 2 in m.get_world(3)["dead"]

    # an UNANNOUNCED rc 0 — user code calling sys.exit(0) mid-step —
    # leaves peers' collectives hanging exactly like a kill: listed
    # (the probe is the ONLY escape; the fencer can't cull pollers)
    m.get_world(19)
    m.remove(19, exit_code=0)
    assert 19 in m.get_world(3)["dead"]

    # an UNANNOUNCED rc-75 hard-leave (the leave RPC never landed)
    # wedges survivors like any crash: listed
    m.get_world(20)
    m.remove(20, exit_code=75)
    assert 20 in m.get_world(3)["dead"]

    # a drained member that segfaults before the consensus pause broke
    # the collective: the earlier announcement does not exempt a
    # non-clean code
    m.get_world(21)
    m.remove(21, departing=True)
    m.remove(21, exit_code=139)  # watch sees a segfault
    assert 21 in m.get_world(3)["dead"]

    # pruning: once epochs advance past the retention window, the stale
    # death drops out of the payload
    for joiner in range(4, 11):
        m.get_world(joiner)
        # drive the two-phase formation to completion so the next
        # registration bumps instead of parking in the lobby
        for _ in range(5):
            members = [w for w, _ in m._world]
            for wid in members:
                m.get_world(wid)
            for wid in members:
                m.get_world(wid, awaiting=False)
    assert 2 not in m.get_world(3)["dead"]


def test_membership_unconfirmed_member_dropped_after_timeout():
    """A member that stops polling (wedged in a stale initialize) must
    not block formation forever: after the confirm timeout the world
    re-forms from the responsive members."""
    m = MembershipService(
        expected_workers=2, form_grace_secs=60, confirm_timeout_secs=0.3
    )
    m.get_world(0)
    m.get_world(1)  # forms epoch 1, world [0, 1], awaiting confirmation
    # only worker 1 keeps polling; 0 goes quiet for > 2 s
    m._last_poll[0] = time.time() - 3.0
    deadline = time.time() + 5
    w = m.get_world(1)
    while not w["ready"]:
        assert time.time() < deadline
        time.sleep(0.05)
        w = m.get_world(1)
    assert w["num_processes"] == 1 and w["process_id"] == 0


def test_membership_grace_forms_partial_world():
    m = MembershipService(expected_workers=3, form_grace_secs=0.2)
    assert m.get_world(0)["ready"] is False
    time.sleep(0.3)
    w = m.get_world(0)
    assert w["ready"] and w["num_processes"] == 1


def test_weighted_step_matches_plain_and_drain_is_noop():
    """Single process, 8 virtual devices: all-weights-1 must equal the
    plain trainer's math (deterministic model — per-shard dropout draws
    can't be expected to reproduce the global-batch draw), and a weight-0
    (drain) step must change nothing."""
    import flax.linen as nn
    import jax
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from elasticdl_tpu.parallel.elastic import (
        broadcast_from_device0,
        host_copy,
        make_elastic_train_step,
    )
    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import TrainState

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, inputs, training=False):
            x = inputs["image"].reshape((inputs["image"].shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(10)(x)

    def loss_fn(output, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            output, labels.reshape(-1)
        ).mean()

    model = MLP()
    rng = np.random.default_rng(0)
    features = {
        "image": rng.random((16, 28, 28), dtype=np.float32),
    }
    labels = rng.integers(0, 10, size=(16, 1)).astype(np.int64)

    variables = init_variables(
        model, jax.random.PRNGKey(0), {"image": features["image"][:1]}
    )
    params, state = split_variables(variables)

    opt = optax.sgd(0.1)
    ts0 = TrainState.create(params, state, opt)

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    ts = broadcast_from_device0(mesh, host_copy(ts0))
    step = make_elastic_train_step(model, loss_fn, opt, mesh)

    def put(tree, spec):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(mesh, spec)), tree
        )

    g_feat = put(features, P("data"))
    g_lab = put(labels, P("data"))
    ones = put(np.ones(8, np.float32), P("data"))
    zeros = put(np.zeros(8, np.float32), P("data"))
    ep = put(np.zeros(8, np.int32), P("data"))
    key = jax.random.PRNGKey(7)

    with mesh:
        ts1, loss, n, _ = step(ts, g_feat, g_lab, ones, ep, key)
    assert int(n) == 8 and np.isfinite(float(loss))
    assert int(host_copy(ts1.version)) == 1

    # plain reference step on the same host state
    from elasticdl_tpu.training.step import make_train_step

    plain = make_train_step(model, loss_fn, opt)
    ts_plain, loss_plain = plain(ts0, features, labels, key)
    np.testing.assert_allclose(float(loss), float(loss_plain), rtol=1e-5)
    h1, hp = host_copy(ts1.params), host_copy(ts_plain.params)
    for a, b in zip(
        jax.tree_util.tree_leaves(h1), jax.tree_util.tree_leaves(hp)
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)

    # drain step: weight 0 everywhere is an exact no-op
    with mesh:
        ts2, _, n0, _ = step(ts1, g_feat, g_lab, zeros, ep, key)
    assert int(n0) == 0
    assert int(host_copy(ts2.version)) == 1
    for a, b in zip(
        jax.tree_util.tree_leaves(host_copy(ts2.params)),
        jax.tree_util.tree_leaves(host_copy(ts1.params)),
    ):
        np.testing.assert_array_equal(a, b)


def test_weighted_step_with_accumulation_matches_plain():
    """accum_steps=2 on the weighted plane must equal the plain
    full-batch step (16 rows -> 8 devices x 2 microbatches of 1)."""
    import flax.linen as nn
    import jax
    import optax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.parallel.elastic import (
        broadcast_from_device0,
        host_copy,
        make_elastic_train_step,
    )
    from elasticdl_tpu.training.step import TrainState, make_train_step

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, inputs, training=False):
            x = inputs["image"].reshape((inputs["image"].shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(10)(x)

    def loss_fn(output, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            output, labels.reshape(-1)
        ).mean()

    model = MLP()
    rng = np.random.default_rng(3)
    features = {"image": rng.random((16, 28, 28), dtype=np.float32)}
    labels = rng.integers(0, 10, size=(16, 1)).astype(np.int64)
    variables = init_variables(
        model, jax.random.PRNGKey(0), {"image": features["image"][:1]}
    )
    params, state = split_variables(variables)
    opt = optax.sgd(0.1)
    ts0 = TrainState.create(params, state, opt)

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))
    ts = broadcast_from_device0(mesh, host_copy(ts0))
    step = make_elastic_train_step(model, loss_fn, opt, mesh, accum_steps=2)

    def put(tree, spec):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(mesh, spec)), tree
        )

    key = jax.random.PRNGKey(7)
    with mesh:
        ts1, loss, n, _ = step(
            ts,
            put(features, P("data")),
            put(labels, P("data")),
            put(np.ones(8, np.float32), P("data")),
            put(np.zeros(8, np.int32), P("data")),
            key,
        )
    assert int(n) == 8

    plain = make_train_step(model, loss_fn, opt)
    ts_plain, loss_plain = plain(ts0, features, labels, key)
    np.testing.assert_allclose(float(loss), float(loss_plain), rtol=1e-5)
    for a, b in zip(
        jax.tree_util.tree_leaves(host_copy(ts1.params)),
        jax.tree_util.tree_leaves(host_copy(ts_plain.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5)


def test_replicated_eval_pins_version_snapshot():
    """Eval rounds pin a version; the replicated plane must score every
    task of round V with version-V params even after training moves on
    (reference pinned-checkpoint semantics), and report the version it
    actually scored when it cannot pin exactly."""
    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.worker.elastic_allreduce_worker import (
        ElasticAllReduceWorker,
    )
    from tests.test_utils import MODEL_ZOO_PATH

    worker = ElasticAllReduceWorker(
        worker_id=0,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=4,
        model_zoo=MODEL_ZOO_PATH,
        model_def="mnist_subclass.mnist_subclass.CustomModel",
        stub=None,
    )

    class FakeTS:
        def __init__(self, tag):
            self.params = {"w": tag}
            self.state = {}

    class FakeTrainer:
        is_sharded = False
        version = 5

        def snapshot(self):
            return FakeTS(self.version)

    worker.trainer = FakeTrainer()
    worker._forward_fn = lambda params, state, x: params["w"]

    # round pinned at the current version: exact
    assert worker._local_forward("x", pinned_version=5) == 5
    assert worker._eval_scored_version == 5

    # training advances; round-5 tasks KEEP scoring the v5 snapshot
    worker.trainer.version = 7
    assert worker._local_forward("x", pinned_version=5) == 5
    assert worker._eval_scored_version == 5

    # a new round at v7 refreshes
    assert worker._local_forward("x", pinned_version=7) == 7
    assert worker._eval_scored_version == 7

    # late grab (round pinned v6 never snapshotted): scores current and
    # reports the true version
    worker.trainer.version = 9
    assert worker._local_forward("x", pinned_version=6) == 9
    assert worker._eval_scored_version == 9


def test_elastic_worker_routes_transformer_configs():
    """The multi-process elastic worker trains transformer_lm REPLICATED
    when no pipeline is requested, and routes pipelined configs to the
    collective (in-step ring) form — the r4 NotImplementedError boundary
    is gone (VERDICT r4 item 1)."""
    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.worker.elastic_allreduce_worker import (
        ElasticAllReduceWorker,
    )
    from tests.test_utils import MODEL_ZOO_PATH

    kwargs = dict(
        worker_id=0,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=4,
        model_zoo=MODEL_ZOO_PATH,
        model_def="transformer_lm.transformer_lm.custom_model",
        stub=None,
    )
    # replicated training: fine
    worker = ElasticAllReduceWorker(
        model_params="vocab_size=64,num_layers=2", **kwargs
    )
    assert not worker.trainer.is_sharded

    # pipelined config: stage params shard over "pipe"; the trainer gets
    # the collective builder + the zoo's mesh-axes layout
    worker = ElasticAllReduceWorker(
        model_params="vocab_size=64,num_layers=2,pipeline_stages=2",
        **kwargs,
    )
    assert worker.trainer.is_sharded
    assert worker.trainer._mesh_axes_fn is not None
    assert worker.trainer._mesh_axes_fn(8) == {"data": 4, "pipe": 2}


def test_evaluation_round_records_scored_versions():
    from elasticdl_tpu.master.evaluation_service import _EvaluationJob

    job = _EvaluationJob(
        {"acc": lambda labels, predictions: np.equal(labels, predictions)},
        model_version=10,
        total_tasks=2,
    )
    assert job.report_evaluation_metrics(
        10, {"output": np.ones(2)}, np.ones(2), scored_version=8
    )
    assert job.scored_versions == {8}
    # wrong pinned version still dropped
    assert not job.report_evaluation_metrics(
        9, {"output": np.ones(2)}, np.ones(2), scored_version=9
    )


# -- rung 2: real OS processes over gloo ------------------------------------


def _count_successes(task_d):
    """Patch task_d.report to collect successful task ids (shared by the
    kill and scale-up rungs)."""
    completed = []
    orig_report = task_d.report

    def counting_report(task_id, success):
        if success:
            completed.append(task_id)
        return orig_report(task_id, success)

    task_d.report = counting_report
    return completed


def _master_for(data_dir, num_workers, num_epochs=2, extra=()):
    args = parse_master_args(
        [
            "--job_name",
            "elastic-ar-test",
            "--model_zoo",
            MODEL_ZOO_PATH,
            "--model_def",
            "mnist_subclass.mnist_subclass.CustomModel",
            "--minibatch_size",
            "16",
            "--num_minibatches_per_task",
            "4",
            "--num_epochs",
            str(num_epochs),
            "--training_data",
            data_dir,
            "--num_workers",
            str(num_workers),
            "--num_ps_pods",
            "0",
            "--port",
            "0",
            "--distribution_strategy",
            "AllreduceStrategy",
        ]
        + list(extra)
    )
    master = Master(args)
    master.prepare()
    return master


def _worker_command_for(master, extra=()):
    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id",
            str(worker_id),
            "--job_type",
            "training_only",
            "--master_addr",
            "localhost:%d" % master.port,
            "--model_zoo",
            MODEL_ZOO_PATH,
            "--model_def",
            "mnist_subclass.mnist_subclass.CustomModel",
            "--minibatch_size",
            "16",
            "--distribution_strategy",
            "AllreduceStrategy",
            "--comm_host",
            "localhost",
        ] + list(extra)

    return worker_command


def _worker_env():
    env = dict(os.environ)
    env.update(
        {
            "PYTHONPATH": REPO,
            "EDL_DIST_PLATFORM": "cpu",
            "EDL_LOCAL_DEVICES": "1",
            "EDL_COMM_HOST": "localhost",
            # init timeout deliberately < the master's 15 s confirm
            # window: a member stuck in a stale formation barrier raises
            # WorldBroken and re-polls before the fencer kills it
            "EDL_WORLD_INIT_TIMEOUT": "10",
            "EDL_HEARTBEAT_TIMEOUT": "10",
            "EDL_SHUTDOWN_TIMEOUT": "5",
            # fenced/wedged workers dump all-thread stacks on SIGABRT
            "PYTHONFAULTHANDLER": "1",
            # shared persistent XLA cache: relaunches/promotions (and
            # repeated test runs) skip recompiling identical HLO
            "JAX_COMPILATION_CACHE_DIR": "/tmp/edl-test-xla-cache",
        }
    )
    # the parent test process pins these for its own virtual mesh; they
    # must not leak a conflicting device count into the workers
    env.pop("XLA_FLAGS", None)
    return env


@pytest.mark.slow
def test_elastic_allreduce_two_process_job(tmp_path):
    create_recordio_file(
        256, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(tmp_path)
    )
    master = _master_for(str(tmp_path), num_workers=2, num_epochs=1)
    manager = LocalInstanceManager(
        master.task_d,
        2,
        _worker_command_for(master),
        env=_worker_env(),
        membership=master.membership,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()
    runner.join(timeout=300)
    assert not runner.is_alive(), "master did not finish"
    assert master.task_d.finished()
    manager.stop_relaunch_and_remove_all_pods()


def run_three_worker_job(tmp_path, kill=True):
    """The 3-worker/2-epoch elastic job, with or without a mid-job
    SIGKILL — the shared harness for the kill rung and for bench.py
    --preemption's same-config clean/killed comparison."""
    create_recordio_file(
        384, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(tmp_path)
    )
    master = _master_for(str(tmp_path), num_workers=3, num_epochs=2)

    completed = _count_successes(master.task_d)

    manager = LocalInstanceManager(
        master.task_d,
        3,
        _worker_command_for(master),
        env=_worker_env(),
        membership=master.membership,
        max_relaunches=10,
        # a pre-warmed spare: the kill's relaunch cost becomes
        # membership-only (the standby already paid its jax import)
        num_standby=1,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()

    if kill:
        # wait for real collective progress, then kill a worker mid-job
        deadline = time.time() + 240
        while len(completed) < 2:
            assert time.time() < deadline, "job made no progress"
            assert runner.is_alive(), "master exited early"
            time.sleep(0.5)
        victims = manager.live_workers()
        assert victims, "no live workers to kill"
        manager.kill_worker(victims[-1])

    runner.join(timeout=420)
    assert not runner.is_alive(), "master did not finish"
    assert master.task_d.finished()
    # every task completed (3 workers, 384*2 records / 64 per task)
    assert len(set(completed)) == 12
    manager.stop_relaunch_and_remove_all_pods()


@pytest.mark.slow
def test_elastic_allreduce_survives_worker_kill(tmp_path):
    run_three_worker_job(tmp_path, kill=True)


@pytest.mark.slow
def test_elastic_allreduce_graceful_preemption_drain(tmp_path):
    """SIGTERM (a cloud preemption notice) must drain gracefully: the
    worker flushes its window and LEAVES the world cleanly (exit 75,
    EX_TEMPFAIL), survivors re-form without a broken collective, a
    replacement launches, and every task completes."""
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        384, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(data_dir)
    )
    log_dir = str(tmp_path / "logs")
    master = _master_for(str(data_dir), num_workers=3, num_epochs=6)
    completed = _count_successes(master.task_d)

    manager = LocalInstanceManager(
        master.task_d,
        3,
        _worker_command_for(master),
        env=_worker_env(),
        membership=master.membership,
        max_relaunches=10,
        log_dir=log_dir,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()

    deadline = time.time() + 240
    while len(completed) < 1:
        assert time.time() < deadline, "job made no progress"
        assert runner.is_alive(), "master exited early"
        time.sleep(0.5)
    victims = manager.live_workers()
    assert victims, "no live workers to terminate"
    victim = victims[-1]
    manager.terminate_worker(victim)

    runner.join(timeout=420)
    assert not runner.is_alive(), "master did not finish after the drain"
    assert master.task_d.finished()
    assert len(set(completed)) == 36
    # the terminated worker exited through the graceful-drain path
    assert manager.exit_codes.get(("worker", victim)) == 75, (
        manager.exit_codes
    )
    manager.stop_relaunch_and_remove_all_pods()
    # the drain's whole point: the victim announced and the world paused
    # at a batch boundary — NO worker ever hit a broken collective (the
    # SIGKILL rung, by contrast, exercises the failed-step path)
    import glob as _glob

    logs = {
        path: open(path, "rb").read().decode("utf-8", "replace")
        for path in _glob.glob(os.path.join(log_dir, "worker-*.log"))
    }
    victim_log = logs.get(os.path.join(log_dir, "worker-%d.log" % victim))
    assert victim_log and "drain announced" in victim_log, (
        "victim never announced its drain"
    )
    offenders = [
        path
        for path, text in logs.items()
        if "collective step failed" in text
    ]
    assert not offenders, (
        "graceful drain still broke a collective: %s" % offenders
    )


@pytest.mark.slow
def test_elastic_allreduce_scales_up_mid_job(tmp_path):
    """Pure growth (no kill): a worker added mid-job parks in the
    joiner lobby until the 2-worker formation is seen training, then a
    growth bump folds it in — the job finishes with all tasks done and
    the world actually reached size 3."""
    create_recordio_file(
        768, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(tmp_path)
    )
    # 8 lazy epochs x 12 tasks: the job must outlive the joiner's cold
    # start (jax import + reader prime) by a wide margin — each worker's
    # shuffle buffer alone swallows 16 tasks (1024 records) at priming,
    # so small jobs drain before a late joiner can ever grab a task
    master = _master_for(str(tmp_path), num_workers=2, num_epochs=8)

    completed = _count_successes(master.task_d)

    # every get_world registers; record the live-set size at each one so
    # a short-lived 3-member world cannot be missed by polling
    live_sizes = []
    orig_register = master.membership.register

    def spy_register(worker_id, host="localhost"):
        result = orig_register(worker_id, host)
        live_sizes.append(len(master.membership._live))
        return result

    master.membership.register = spy_register

    manager = LocalInstanceManager(
        master.task_d,
        2,
        _worker_command_for(master),
        env=_worker_env(),
        membership=master.membership,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()

    # add the third worker the moment the 2-worker world forms (the
    # first completion REPORT lands much later: record counts are held
    # back through the deferred-sync window)
    deadline = time.time() + 240
    while master.membership.epoch < 1:
        assert time.time() < deadline, "initial world never formed"
        assert runner.is_alive(), "master exited early"
        time.sleep(0.2)
    manager._start_worker()

    runner.join(timeout=420)
    assert not runner.is_alive(), "master did not finish"
    assert master.task_d.finished()
    # >= not ==: a fence-and-relaunch race on a loaded host can push
    # the live set past 3 transiently; growth is what matters
    assert max(live_sizes) >= 3, (
        "third worker never joined the live set (max=%d)"
        % max(live_sizes)
    )
    # 768*8 records / 64 per task = 96 tasks, all completed exactly once
    assert len(set(completed)) == 96
    manager.stop_relaunch_and_remove_all_pods()


@pytest.mark.slow
def test_elastic_allreduce_resumes_from_sharded_checkpoint(tmp_path):
    """Job 1 writes sharded checkpoints; job 2 (fresh master + fresh
    workers, same checkpoint dir) must resume from them — its exported
    model version continues past job 1's steps instead of restarting."""
    from elasticdl_tpu.common.model_utils import load_from_checkpoint_file
    from elasticdl_tpu.common.sharded_checkpoint import (
        ShardedCheckpointManager,
    )

    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        256, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(data_dir)
    )
    ckpt_dir = str(tmp_path / "ckpt")
    out_dir = str(tmp_path / "export")

    def run_job():
        master = _master_for(
            str(data_dir),
            num_workers=2,
            num_epochs=1,
            extra=[
                "--checkpoint_dir",
                ckpt_dir,
                "--checkpoint_steps",
                "4",
                "--output",
                out_dir,
            ],
        )
        manager = LocalInstanceManager(
            master.task_d,
            2,
            _worker_command_for(
                master,
                extra=[
                    "--checkpoint_dir",
                    ckpt_dir,
                    "--checkpoint_steps",
                    "4",
                ],
            ),
            env=_worker_env(),
            membership=master.membership,
        )
        master.instance_manager = manager
        manager.start_workers()
        runner = threading.Thread(
            target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
        )
        runner.start()
        runner.join(timeout=300)
        assert not runner.is_alive(), "master did not finish"
        assert master.task_d.finished()
        manager.stop_relaunch_and_remove_all_pods()

    run_job()
    mgr = ShardedCheckpointManager(ckpt_dir)
    v1 = mgr.versions()
    assert v1, "job 1 wrote no sharded checkpoints"

    run_job()
    v2 = mgr.versions()
    # job 2 resumed: its checkpoints continue past job 1's last version
    assert max(v2) > max(v1), (v1, v2)
    # and the exported model's version reflects the resumed counter
    exports = []
    for root, _, files in os.walk(out_dir):
        for f in files:
            if f.endswith(".chkpt"):
                exports.append(os.path.join(root, f))
    assert exports
    versions = [load_from_checkpoint_file(p)[0] for p in exports]
    assert max(versions) > max(v1), (versions, v1)


@pytest.mark.slow
def test_elastic_allreduce_evaluation_interleave(tmp_path, monkeypatch):
    """TRAINING_WITH_EVALUATION on the elastic plane: the coordinating
    master learns versions from worker task reports (it applies no
    gradients), triggers gap-based eval rounds pinning version NUMBERS,
    and workers score them with their own device state."""
    monkeypatch.setenv("EDL_FORM_GRACE_SECS", "120")
    train_dir = tmp_path / "train"
    val_dir = tmp_path / "val"
    train_dir.mkdir()
    val_dir.mkdir()
    create_recordio_file(
        192, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(train_dir)
    )
    create_recordio_file(
        32, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(val_dir)
    )
    master = _master_for(
        str(train_dir),
        num_workers=2,
        num_epochs=2,
        extra=(
            "--validation_data",
            str(val_dir),
            "--evaluation_steps",
            "4",
            "--evaluation_start_delay_secs",
            "0",
        ),
    )
    assert master.evaluation_service is not None

    published = []
    orig_publish = master.evaluation_service._publish_summary

    def capture_publish(round_):
        published.append(
            (round_.model_version, round_.get_evaluation_summary())
        )
        return orig_publish(round_)

    master.evaluation_service._publish_summary = capture_publish

    manager = LocalInstanceManager(
        master.task_d,
        2,
        _worker_command_for(
            master, extra=("--job_type", "training_with_evaluation")
        ),
        env=_worker_env(),
        membership=master.membership,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()
    runner.join(timeout=300)
    assert not runner.is_alive(), "master did not finish"
    assert master.task_d.finished()
    manager.stop_relaunch_and_remove_all_pods()

    assert published, "no evaluation round ever completed"
    for version, metrics in published:
        assert version > 0
        assert metrics, "empty evaluation summary"


def test_membership_world_size_multiple_rounds_down():
    """Pipelined jobs need worlds whose size divides the stage count:
    formation rounds DOWN to the multiple, overflow members poll as
    spares ({"spare": True}), and reaching the multiple folds them in."""
    m = MembershipService(
        expected_workers=4, form_grace_secs=0.01, world_size_multiple=2
    )

    def drive_formation(members):
        # confirm (awaiting=True) then mark trained (awaiting=False) so
        # the two-phase formation completes and lobby joiners fold in
        for _ in range(6):
            for wid in members:
                m.get_world(wid)
            for wid in members:
                m.get_world(wid, awaiting=False)

    m.get_world(0)
    time.sleep(0.05)
    for w in (0, 1, 2):
        m.get_world(w)
    drive_formation([0, 1])
    # 3 live -> world of 2, lowest ids win; 2 polls as a spare
    w2 = m.get_world(2)
    assert not w2["ready"] and w2.get("spare")
    world = _poll_ready(m, 0)
    assert world["num_processes"] == 2
    assert world["members"] == [0, 1]
    # the 4th member arrives -> the next bump forms a full world of 4
    m.get_world(3)
    drive_formation([0, 1, 2, 3])
    world = _poll_ready(m, 2)
    assert world["num_processes"] == 4
    # a death drops 4 -> world of 2 again (3 survivors round down)
    m.remove(1)
    drive_formation([0, 2])
    world = _poll_ready(m, 0)
    assert world["num_processes"] == 2
    assert world["members"] == [0, 2]
    spare = m.get_world(3)
    assert not spare["ready"] and spare.get("spare")


def test_spare_worker_requeues_inflight_tasks():
    """A worker parked as a spare must hand its pulled tasks back (the
    members finish them; a spare holding tasks stalls the job)."""
    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.worker.elastic_allreduce_worker import (
        ElasticAllReduceWorker,
    )
    from tests.test_utils import MODEL_ZOO_PATH

    class SpareStub:
        """Master stub: always answers 'you are a spare'."""

        def __init__(self):
            self.reported = []

        def get_comm_world(self, worker_id, host=None, awaiting=True):
            return {"epoch": 3, "ready": False, "spare": True, "dead": []}

        def report_task_result(self, task_id, err_msg, exec_counters=None):
            self.reported.append((task_id, err_msg))
            return {}

    stub = SpareStub()
    worker = ElasticAllReduceWorker(
        worker_id=5,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=4,
        model_zoo=MODEL_ZOO_PATH,
        model_def="transformer_lm.transformer_lm.custom_model",
        model_params="vocab_size=64,num_layers=2,pipeline_stages=2",
        stub=stub,
    )
    # simulate a primed worker holding one in-flight task
    tds = worker._task_data_service
    task = SimpleNamespace(task_id=9, start=0, end=8, type=None)
    tds._inflight.append(task)
    tds._record_cursor = 4  # half consumed (the primed batch)
    worker._retry_batch = ({"tokens": np.zeros((4, 8), np.int32)},
                           np.zeros((4, 8), np.int32))

    worker._requeue_as_spare()
    assert worker._retry_batch is None
    assert tds.get_current_task() is None
    assert stub.reported and stub.reported[0][0] == 9
    assert "spare" in stub.reported[0][1]
