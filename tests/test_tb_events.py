"""TensorBoard event-file writer: framing, CRC, and a render check with
the real ``tensorboard`` reader when installed.

Parity: reference master/tensorboard_service.py:27-45 writes eval
metrics through tf.summary so ``tensorboard --logdir`` renders them; the
rebuild writes the identical on-disk format without TF
(common/tb_events.py)."""

import pytest

from elasticdl_tpu.common import tb_events


def test_event_file_round_trip(tmp_path):
    w = tb_events.EventFileWriter(str(tmp_path))
    w.add_scalar("loss", 0.5, step=10, wall_time=123.0)
    w.add_scalars(
        [("accuracy", 0.75), ("auc", 0.9)], step=20, wall_time=124.0
    )
    w.close()

    events = tb_events.read_events(w.path)
    # first record is the file-version header (no scalars)
    assert events[0][2] == []
    assert events[1] == (123.0, 10, [("loss", pytest.approx(0.5))])
    wall, step, scalars = events[2]
    assert (wall, step) == (124.0, 20)
    assert scalars == [
        ("accuracy", pytest.approx(0.75)),
        ("auc", pytest.approx(0.9)),
    ]


def test_crc_matches_known_vector():
    # CRC-32C test vector (RFC 3720 B.4): "123456789" -> 0xE3069283
    assert tb_events.crc32c(b"123456789") == 0xE3069283


def test_torn_tail_tolerated(tmp_path):
    w = tb_events.EventFileWriter(str(tmp_path))
    w.add_scalar("loss", 1.0, step=1)
    w.close()
    with open(w.path, "ab") as f:
        f.write(b"\x40\x00\x00")  # truncated next frame
    events = tb_events.read_events(w.path)
    assert len(events) == 2  # header + the complete scalar event


def test_corrupt_record_detected(tmp_path):
    w = tb_events.EventFileWriter(str(tmp_path))
    w.add_scalar("loss", 1.0, step=1)
    w.close()
    with open(w.path, "r+b") as f:
        f.seek(-6, 2)  # inside the last event's payload
        f.write(b"\xff")
    with pytest.raises(ValueError):
        tb_events.read_events(w.path)


def test_real_tensorboard_renders_the_file(tmp_path):
    """The authoritative check: TensorBoard's own event loader (with its
    CRC validation) reads our hand-framed file."""
    accumulator = pytest.importorskip(
        "tensorboard.backend.event_processing.event_accumulator"
    )
    w = tb_events.EventFileWriter(str(tmp_path))
    for step, loss in enumerate([0.9, 0.5, 0.25]):
        w.add_scalar("eval/loss", loss, step=step)
    w.close()

    acc = accumulator.EventAccumulator(str(tmp_path))
    acc.Reload()
    assert "eval/loss" in acc.Tags()["scalars"]
    points = acc.Scalars("eval/loss")
    assert [p.step for p in points] == [0, 1, 2]
    assert [p.value for p in points] == [
        pytest.approx(0.9),
        pytest.approx(0.5),
        pytest.approx(0.25),
    ]


def test_tensorboard_service_writes_both_surfaces(tmp_path):
    from elasticdl_tpu.master.tensorboard_service import (
        TensorboardService,
    )

    svc = TensorboardService(str(tmp_path))
    svc.write_dict_to_summary(
        {"mnist": {"accuracy": 0.9}, "loss": 0.1}, version=7
    )
    svc.close()

    jsonl = (tmp_path / "scalars.jsonl").read_text().splitlines()
    assert len(jsonl) == 2

    event_files = list(tmp_path.glob("events.out.tfevents.*"))
    assert len(event_files) == 1
    events = tb_events.read_events(str(event_files[0]))
    _, step, scalars = events[-1]
    assert step == 7
    assert dict(scalars) == {
        "mnist/accuracy": pytest.approx(0.9),
        "loss": pytest.approx(0.1),
    }
