"""The zero-copy wire contract (docs/wire.md).

Four layers:

- byte identity: the scatter-gather codec (plan + one preallocation +
  one memcpy per payload) must emit EXACTLY the seed join-based
  codec's bytes — mixed-version fleets interoperate — including
  bf16-fused frames (Tensor.wire_dtype vs the seed's eager astype),
  sparse indices, strided sources, and whole packed messages;
- the aliasing/lifetime contract: decoded tensors are READ-ONLY
  frombuffer views pinned to the received buffer (writes raise),
  ``Tensor.materialize()`` is the audited escape hatch, and on the
  bytes path views survive ``release_message`` of their own and of
  OTHER messages (the arena is advisory there — refcounts rule);
- the shared-memory transport: hello negotiation over real loopback
  gRPC, slot round trip + recycle on release, per-call and cross-host
  fallbacks, and orphan reclamation — the server registry unlinks the
  ring of a client SIGKILLed mid-pull whose atexit never ran;
- conftest wires this module into the locktraced suites, so every
  lock the shm slot accounting takes joins the runtime lock-order
  sanitizer and no test may leak a non-daemon thread.
"""

import json
import os
import signal
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

from elasticdl_tpu.common.dtypes import (
    dtype_name_to_numpy,
    dtype_numpy_to_name,
)
from elasticdl_tpu.common.tensor import (
    Tensor,
    WireArena,
    deserialize_tensor,
    deserialize_tensors,
    release_message,
    serialize_tensor,
    serialize_tensors,
)
from elasticdl_tpu.common.tensor import _MAGIC, _VERSION
from elasticdl_tpu.rpc.core import pack_message, unpack_message
from elasticdl_tpu.rpc.shm_transport import (
    ShmChannel,
    ShmEndpointRegistry,
    ShmRing,
    host_fingerprint,
    install_shm_endpoint,
)
from elasticdl_tpu.rpc.wire_compression import (
    compress_tensors,
    decompress_tensors,
)

BF16 = dtype_name_to_numpy("bfloat16")


# ---------------------------------------------------------------------------
# the seed codec, replicated verbatim (the copy chain PR 8 removed) —
# the byte-layout oracle the zero-copy writers must match exactly
# ---------------------------------------------------------------------------


def seed_serialize_tensor(t):
    values = np.ascontiguousarray(t.values)
    header = {
        "name": t.name,
        "dtype": dtype_numpy_to_name(values.dtype),
        "shape": list(values.shape),
    }
    parts = [values.tobytes()]
    if t.indices is not None:
        idx = np.ascontiguousarray(t.indices, dtype=np.int64)
        header["num_indices"] = int(idx.shape[0])
        parts.append(idx.tobytes())
    hdr = json.dumps(header).encode("utf-8")
    return b"".join(
        [_MAGIC, struct.pack("<BI", _VERSION, len(hdr)), hdr] + parts
    )


def seed_serialize_tensors(tensors):
    out = []
    for t in tensors:
        b = seed_serialize_tensor(t)
        out.append(struct.pack("<Q", len(b)))
        out.append(b)
    return b"".join(out)


def seed_pack_message(msg):
    header = {}
    segments = []

    def add_segment(data):
        segments.append(data)
        return len(segments) - 1

    for key, value in msg.items():
        if isinstance(value, Tensor):
            header[key] = {
                "t": "tensor",
                "i": add_segment(seed_serialize_tensor(value)),
            }
        elif isinstance(value, np.ndarray):
            header[key] = {
                "t": "array",
                "i": add_segment(seed_serialize_tensor(Tensor(key, value))),
            }
        elif (
            isinstance(value, (list, tuple))
            and value
            and isinstance(value[0], Tensor)
        ):
            idxs = [add_segment(seed_serialize_tensor(t)) for t in value]
            header[key] = {"t": "tensors", "i": idxs}
        elif isinstance(value, (bytes, bytearray)):
            header[key] = {"t": "bytes", "i": add_segment(bytes(value))}
        else:
            header[key] = {"t": "json", "v": value}
    hdr = json.dumps(header).encode("utf-8")
    out = [struct.pack("<I", len(hdr)), hdr, struct.pack("<I", len(segments))]
    for seg in segments:
        out.append(struct.pack("<Q", len(seg)))
        out.append(seg)
    return b"".join(out)


def _rng():
    return np.random.default_rng(8)


def _sparse():
    return Tensor(
        "emb",
        _rng().standard_normal((3, 4)).astype(np.float32),
        indices=np.array([7, 1, 30], dtype=np.int64),
    )


# ---------------------------------------------------------------------------
# byte identity vs the seed codec
# ---------------------------------------------------------------------------


def test_frame_bytes_identical_to_seed_codec():
    dense = Tensor("w", _rng().standard_normal((5, 3)).astype(np.float32))
    ints = Tensor("steps", np.arange(6, dtype=np.int64).reshape(2, 3))
    empty = Tensor("z", np.zeros((0, 4), np.float32))
    for t in (dense, _sparse(), ints, empty):
        assert bytes(serialize_tensor(t)) == seed_serialize_tensor(t)
    assert bytes(
        serialize_tensors([dense, _sparse(), ints])
    ) == seed_serialize_tensors([dense, _sparse(), ints])


def test_strided_source_serializes_like_seed():
    # the seed staged through ascontiguousarray; the scatter-gather
    # writer lets np.copyto walk the strides during its one memcpy
    base = _rng().standard_normal((8, 6)).astype(np.float32)
    strided = base[::2, ::3]
    assert not strided.flags.c_contiguous
    t = Tensor("s", strided)
    assert bytes(serialize_tensor(t)) == seed_serialize_tensor(t)


def test_bf16_fused_frame_identical_to_seed_eager_downcast():
    dense = Tensor("w", _rng().standard_normal((4, 4)).astype(np.float32))
    sparse = _sparse()
    marked, names = compress_tensors([dense, sparse], "bfloat16")
    assert names == ["w", "emb"]
    # the mark is allocation-free: payloads still alias the caller's
    assert marked[0].values is dense.values
    # the seed protocol downcast eagerly, then serialized the bf16 copy
    seed = seed_serialize_tensor(
        Tensor("w", dense.values.astype(BF16), None)
    )
    assert bytes(serialize_tensor(marked[0])) == seed
    seed_sp = seed_serialize_tensor(
        Tensor("emb", sparse.values.astype(BF16), sparse.indices)
    )
    assert bytes(serialize_tensor(marked[1])) == seed_sp
    # and the receiver upcast restores f32 within bf16 tolerance
    back = decompress_tensors(
        [deserialize_tensor(bytes(serialize_tensor(m))) for m in marked],
        names,
    )
    assert back[0].values.dtype == np.float32
    np.testing.assert_allclose(
        back[0].values, dense.values, rtol=1e-2, atol=1e-2
    )
    np.testing.assert_array_equal(back[1].indices, sparse.indices)


def test_packed_message_identical_to_seed_packer():
    msg = {
        "t": Tensor("w", _rng().standard_normal((3, 3)).astype(np.float32)),
        "arr": np.arange(5, dtype=np.float32),
        "many": [_sparse(), Tensor("b", np.ones((2,), np.float32))],
        "blob": b"\x00raw\xff",
        "version": 41,
        "name": "shard-0",
    }
    assert bytes(pack_message(msg)) == seed_pack_message(msg)
    # and "_wire_arena" is a decode-side handle, never a wire field
    decoded = unpack_message(
        bytes(pack_message(msg)), arena=WireArena(b"")
    )
    assert bytes(pack_message(decoded)) == seed_pack_message(msg)


# ---------------------------------------------------------------------------
# the aliasing/lifetime contract
# ---------------------------------------------------------------------------


def test_decoded_views_are_readonly_and_zero_copy():
    t = _sparse()
    buf = bytearray(serialize_tensor(t))  # writable backing store
    got = deserialize_tensor(buf)
    for arr in (got.values, got.indices):
        assert not arr.flags.writeable
        with pytest.raises((ValueError, RuntimeError)):
            arr[0] = 0
    # views alias the frame buffer: an in-place poke to the backing
    # bytearray is visible through the decoded arrays (zero copy,
    # indices included — the in-process master path reads in place)
    before_v = got.values.copy()
    before_i = got.indices.copy()
    buf[-1] ^= 0xFF  # last byte of the indices payload
    assert not np.array_equal(got.indices, before_i)
    buf[-1] ^= 0xFF
    np.testing.assert_array_equal(got.values, before_v)
    np.testing.assert_array_equal(got.indices, before_i)


def test_materialize_is_the_escape_hatch_and_free_for_owned():
    got = deserialize_tensor(bytes(serialize_tensor(_sparse())))
    owned = got.materialize()
    assert owned is not got
    assert owned.values.flags.writeable and owned.indices.flags.writeable
    np.testing.assert_array_equal(owned.values, got.values)
    owned.values[0, 0] = 7.0  # safe: no longer aliases the wire buffer
    # already-owned tensors pass through untouched (the call is free
    # everywhere but the decode edge)
    local = _sparse()
    assert local.materialize() is local
    assert owned.materialize() is owned


def test_views_survive_arena_release_of_other_messages():
    msgs = []
    for k in range(3):
        wire = bytes(
            pack_message(
                {"t": Tensor("w", np.full((64,), float(k), np.float32))}
            )
        )
        msgs.append(unpack_message(wire, arena=WireArena(wire)))
    release_message(msgs[0])
    release_message(msgs[0])  # idempotent, and a no-op without an arena
    for k in (1, 2):
        np.testing.assert_array_equal(
            msgs[k]["t"].values, np.full((64,), float(k), np.float32)
        )
    # on the bytes path even the RELEASED message's views stay valid:
    # numpy refcounts the buffer, the arena is advisory
    np.testing.assert_array_equal(
        msgs[0]["t"].values, np.zeros((64,), np.float32)
    )


def test_arena_release_callback_fires_once_even_via_del():
    fired = []
    arena = WireArena(b"x", on_release=lambda: fired.append(1))
    msg = {"_wire_arena": arena}
    release_message(msg)
    assert "_wire_arena" not in msg
    release_message(msg)
    arena.release()
    arena.__del__()
    assert fired == [1]


# ---------------------------------------------------------------------------
# the shared-memory transport
# ---------------------------------------------------------------------------


def _dense(n=2048):
    return np.arange(n, dtype=np.float32)


@pytest.fixture
def shm_fleet():
    """Real loopback gRPC server with the shm endpoint installed, plus
    a negotiated ShmChannel client. Closes everything on teardown."""
    grpc = pytest.importorskip("grpc")  # noqa: F841 — transport dep
    from elasticdl_tpu.rpc.core import Client, serve

    calls = {"n": 0}

    def pull_dense(req):
        calls["n"] += 1
        return {
            "version": calls["n"],
            "params": [Tensor("w", _dense() * req.get("scale", 1.0))],
        }

    def push_gradient(req):
        # the audited-retention shape: accumulate outlives the request,
        # so the handler materializes before the slot recycles
        g = req["grad"].materialize()
        return {"accepted": True, "sum": float(g.values.sum())}

    methods, registry = install_shm_endpoint(
        {"pull_dense": pull_dense, "push_gradient": push_gradient}
    )
    server = serve(methods, 0)
    client = Client("localhost:%d" % server._edl_port)
    chan = ShmChannel(client, n_slots=2, slot_mb=1)
    try:
        yield chan, registry, calls
    finally:
        chan.close()
        client.close()
        server.stop(None)
        registry.close()


def test_shm_round_trip_and_slot_recycle(shm_fleet):
    chan, _registry, _calls = shm_fleet
    resp = chan.call("pull_dense", scale=2.0)
    assert chan.state == "on"
    assert chan.stats["shm"] == 1 and chan.stats["inline"] == 0
    got = resp["params"][0]
    assert not got.values.flags.writeable
    np.testing.assert_array_equal(got.values, _dense() * 2.0)
    # retention contract: materialize BEFORE releasing the message —
    # release recycles the slot on this transport
    kept = got.materialize().values
    release_message(resp)
    with chan._mu:
        assert sorted(chan._free) == [0, 1]  # slot back in the pool
    np.testing.assert_array_equal(kept, _dense() * 2.0)
    # push direction: request payload rides the slot too
    resp2 = chan.call(
        "push_gradient", grad=Tensor("g", np.ones((8,), np.float32))
    )
    assert resp2["accepted"] and resp2["sum"] == 8.0
    release_message(resp2)
    assert chan.stats["shm"] == 2


def test_shm_oversized_payload_falls_back_per_call(shm_fleet):
    chan, _registry, _calls = shm_fleet
    big = Tensor("g", np.zeros((1 << 19,), np.float32))  # 2 MiB > 1 MiB slot
    resp = chan.call("push_gradient", grad=big)
    assert resp["accepted"]
    assert chan.stats["inline"] == 1
    assert chan.state == "on"  # per-call fallback, channel stays on
    resp2 = chan.call("pull_dense")
    np.testing.assert_array_equal(resp2["params"][0].values, _dense())
    release_message(resp2)
    assert chan.stats["shm"] == 1


def test_shm_declined_cross_host_uses_bytes_path():
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from elasticdl_tpu.rpc.core import Client, serve

    registry = ShmEndpointRegistry()
    registry._fingerprint = "other-host|far-away"  # cross-host server
    methods = {
        "pull_dense": lambda req: {"params": [Tensor("w", _dense())]}
    }
    wrapped = {n: registry.wrap(f) for n, f in methods.items()}
    wrapped["transport_hello"] = registry.hello
    server = serve(wrapped, 0)
    client = Client("localhost:%d" % server._edl_port)
    chan = ShmChannel(client, n_slots=2, slot_mb=1)
    try:
        resp = chan.call("pull_dense")
        np.testing.assert_array_equal(resp["params"][0].values, _dense())
        assert chan.state == "off"
        assert chan.stats["inline"] == 1 and chan.stats["shm"] == 0
        release_message(resp)  # advisory on the bytes path
    finally:
        chan.close()
        client.close()
        server.stop(None)
        registry.close()


def test_shm_hello_validates_geometry_and_name():
    registry = ShmEndpointRegistry()
    fp = host_fingerprint()
    base = {"n_slots": 2, "slot_size": 1 << 20, "host": fp}
    assert not registry.hello(dict(base, name="not-ours"))["accepted"]
    assert not registry.hello(
        dict(base, name="edlw-x", n_slots=10_000)
    )["accepted"]
    assert not registry.hello(
        dict(base, name="edlw-x", host="elsewhere|")
    )["accepted"]
    # a well-formed hello for a segment that does not exist fails at
    # attach, not with a crash
    resp = registry.hello(dict(base, name="edlw-nonexistent"))
    assert not resp["accepted"] and "attach" in resp["reason"]
    registry.close()


def test_shm_ring_reclaimed_after_client_sigkilled_mid_pull():
    """The orphan path (docs/wire.md): a client creates a ring, the
    server attaches via hello, the client is SIGKILLed mid-pull — its
    atexit unlink never runs (and the pod-kill case loses the resource
    tracker too, which the child simulates by unregistering) — and the
    server registry's close() is what reclaims the segment name."""
    child = subprocess.Popen(
        [
            sys.executable,
            "-c",
            "import sys, time\n"
            "from multiprocessing import resource_tracker\n"
            "from elasticdl_tpu.rpc.shm_transport import ShmRing\n"
            "ring = ShmRing(2, 1 << 16)\n"
            "resource_tracker.unregister(ring._shm._name, 'shared_memory')\n"
            "print(ring.name, flush=True)\n"
            "time.sleep(120)\n",  # parked "mid-pull" until the SIGKILL
        ],
        stdout=subprocess.PIPE,
        text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    try:
        name = child.stdout.readline().strip()
        assert name.startswith("edlw-")
        registry = ShmEndpointRegistry()
        accepted = registry.hello(
            {
                "name": name,
                "n_slots": 2,
                "slot_size": 1 << 16,
                "host": host_fingerprint(),
            }
        )
        assert accepted["accepted"]
        child.kill()  # SIGKILL: no atexit, no tracker cleanup
        child.wait(timeout=30)
        # the name leaked past the client's death...
        from multiprocessing import shared_memory

        probe = shared_memory.SharedMemory(name=name)
        probe.close()
        try:
            from multiprocessing import resource_tracker

            resource_tracker.unregister("/" + name, "shared_memory")
        except (KeyError, ValueError, OSError):
            pass
        # ...until the server registry reclaims every attached ring
        registry.close()
        with pytest.raises(FileNotFoundError):
            shared_memory.SharedMemory(name=name)
    finally:
        if child.poll() is None:
            child.kill()
            child.wait(timeout=30)
        if child.stdout:
            child.stdout.close()


def test_shm_server_restart_disables_channel_and_resends_inline():
    """A restarted PS lost its ring attachments: the server answers
    _shm_error BEFORE dispatch, the client resends inline exactly once
    and stops offering shm on the channel."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from elasticdl_tpu.rpc.core import Client, serve

    methods, registry = install_shm_endpoint(
        {"pull_dense": lambda req: {"params": [Tensor("w", _dense())]}}
    )
    server = serve(methods, 0)
    client = Client("localhost:%d" % server._edl_port)
    chan = ShmChannel(client, n_slots=2, slot_mb=1)
    try:
        resp = chan.call("pull_dense")
        release_message(resp)
        assert chan.state == "on"
        registry.close()  # the "restart": attachments gone, server up
        resp = chan.call("pull_dense")  # _shm_error -> inline resend
        np.testing.assert_array_equal(resp["params"][0].values, _dense())
        assert chan.state == "off"
        assert chan.stats["inline"] == 1
    finally:
        chan.close()
        client.close()
        server.stop(None)
        registry.close()


def test_shm_ring_unlink_is_idempotent_and_attach_checks_size():
    ring = ShmRing(2, 1 << 12)
    attached = ShmRing(2, 1 << 12, name=ring.name)
    with pytest.raises(ValueError):
        ShmRing(64, 1 << 20, name=ring.name)  # advertised > actual
    with pytest.raises(ValueError):
        ShmRing(2, 1 << 12, name="unprefixed-segment")
    attached.destroy()  # attacher: close only, no unlink
    ring.destroy()
    ring.destroy()  # idempotent
    from multiprocessing import shared_memory

    with pytest.raises(FileNotFoundError):
        shared_memory.SharedMemory(name=ring.name)


def test_memoryview_field_sizes_in_bytes_not_elements():
    # plan_message accepts memoryview payloads; a typed view's len()
    # counts elements, and sizing the frame with it would corrupt the
    # length prefix — the packer must count bytes
    arr = np.arange(4, dtype=np.float32)
    msg = unpack_message(bytes(pack_message({"b": memoryview(arr)})))
    assert msg["b"] == arr.tobytes()


def test_disable_defers_ring_destroy_until_inflight_drain():
    """A peer _shm_error (or close()) racing a fan-out sibling's
    in-flight call must not close the shared mapping under it: the
    sibling degrades to the bytes path, the ring dies with the last
    user out."""
    chan = ShmChannel(client=None, n_slots=2, slot_mb=1)
    ring = ShmRing(2, 1 << 12)
    with chan._mu:
        chan._state = "on"
        chan._ring = ring
    claim = chan._acquire()  # a call is now between acquire and leave
    assert claim is not None and claim[0] is ring
    chan._disable()
    assert chan.state == "off"
    assert not ring._destroyed  # deferred: the in-flight call owns it
    ring.read_header(claim[1])  # mapping still usable mid-call
    assert chan._acquire() is None  # but no NEW claims after disable
    chan._leave()
    assert ring._destroyed  # last user out destroyed the retired ring
    chan.close()


def test_release_under_load_returns_every_slot(shm_fleet):
    """A fan-out-shaped burst: more calls than slots, interleaved
    releases — every slot must come home and no call may fail."""
    chan, _registry, _calls = shm_fleet
    for _round in range(3):
        resps = [chan.call("pull_dense") for _ in range(4)]
        for resp in resps:
            np.testing.assert_array_equal(
                resp["params"][0].values, _dense()
            )
            release_message(resp)
    with chan._mu:
        assert sorted(chan._free) == [0, 1]
    # 2 slots, 4 concurrent-ish calls per round: the pool bounds shm
    # use, the spill rides inline, nothing errors
    assert chan.stats["shm"] + chan.stats["inline"] == 12
