"""Transformer LM: dp training, tp sharding rules, dp x tp x sp step.

Exercises the full TPU-native parallelism stack on the virtual 8-device
mesh: data-parallel training through AllReduceTrainer, parameter placement
by the tensor-parallel rules, and a fused train step over a 2x2x2
dp/model/seq mesh with ring attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.common.tensor import pytree_to_named_arrays
from elasticdl_tpu.nn.model_api import init_variables, split_variables
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.sharding import (
    param_spec,
    shard_batch_dp_sp,
    shard_params,
)
from elasticdl_tpu.parallel.trainer import AllReduceTrainer
from elasticdl_tpu.training.step import make_train_step
from model_zoo.transformer_lm import transformer_lm as zoo


def _tokens(b=8, l=32, vocab=128, seed=0):
    rng = np.random.default_rng(seed)
    # a learnable pattern: token t follows (t*3+1) % vocab
    start = rng.integers(0, vocab, size=(b, 1))
    seq = [start]
    for _ in range(l - 1):
        seq.append((seq[-1] * 3 + 1) % vocab)
    return np.concatenate(seq, axis=1).astype(np.int32)


def test_transformer_dp_training_learns():
    model = zoo.custom_model(vocab_size=128, num_layers=2)
    trainer = AllReduceTrainer(model, zoo.loss, zoo.optimizer(1e-2))
    tokens = _tokens()
    batch = {"tokens": tokens}
    losses = [float(trainer.train_step(batch, tokens)) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_tp_param_specs_match_rules():
    mesh = create_mesh(
        {"data": 2, "model": 2, "seq": 2},
        axis_names=("data", "model", "seq"),
    )
    model = zoo.custom_model(vocab_size=64)
    variables = init_variables(
        model, jax.random.PRNGKey(0), {"tokens": np.zeros((1, 8), np.int32)}
    )
    params, _ = split_variables(variables)
    named = pytree_to_named_arrays(params)
    qspec = param_spec("block_0/query/kernel", mesh)
    assert "model" in qspec
    assert param_spec("embed/embedding", mesh)[0] == "model"
    assert param_spec("block_0/RMSNorm_0/scale", mesh) == ()
    # placement works for the real parameter tree
    sharded = shard_params(mesh, params)
    leaf = sharded["block_0"]["query"]["kernel"]
    assert "model" in str(leaf.sharding.spec)


def test_dp_tp_sp_fused_step():
    """One full train step over a 2x2x2 mesh with ring attention."""
    mesh = create_mesh(
        {"data": 2, "model": 2, "seq": 2},
        axis_names=("data", "model", "seq"),
    )
    model = zoo.custom_model(
        vocab_size=64,
        num_layers=1,
        mesh=mesh,
        seq_axis="seq",
    )
    tokens = _tokens(b=4, l=16, vocab=64)
    variables = init_variables(
        model, jax.random.PRNGKey(0), {"tokens": tokens}
    )
    params, state = split_variables(variables)
    opt = optax.sgd(0.01)
    from elasticdl_tpu.training.step import TrainState

    ts = TrainState.create(params, state, opt)
    ts = jax.tree_util.tree_map(np.asarray, ts)
    # place: params by tp rules, batch over data+seq
    ts = ts.replace(params=shard_params(mesh, ts.params))
    batch = shard_batch_dp_sp(
        mesh, {"tokens": tokens}, seq_sharded=True
    )
    labels = shard_batch_dp_sp(mesh, tokens, seq_sharded=True)
    step = make_train_step(model, zoo.loss, opt)
    with mesh:
        ts2, loss = step(ts, batch, labels, jax.random.PRNGKey(1))
    assert np.isfinite(float(loss))
    assert int(ts2.version) == 1

    # numerics match an unsharded single-device step
    model_1dev = zoo.custom_model(vocab_size=64, num_layers=1)
    ts_ref = TrainState.create(params, state, opt)
    step_ref = make_train_step(model_1dev, zoo.loss, opt)
    _, loss_ref = step_ref(ts_ref, {"tokens": tokens}, tokens, jax.random.PRNGKey(1))
    np.testing.assert_allclose(
        float(loss), float(loss_ref), rtol=2e-4
    )
