"""Dispatcher lifecycle tests (parity: reference tests/task_dispatcher_test.py)."""

import unittest

from elasticdl_tpu.common.constants import TaskType
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher


class TaskDispatcherTest(unittest.TestCase):
    def test_create_tasks_with_zero_start_ind(self):
        task_d = TaskDispatcher({"f1": (0, 10), "f2": (0, 10)}, {}, {}, 3, 1)

        all_tasks = [
            ("f1", 0, 3, TaskType.TRAINING, -1),
            ("f1", 3, 6, TaskType.TRAINING, -1),
            ("f1", 6, 9, TaskType.TRAINING, -1),
            ("f1", 9, 10, TaskType.TRAINING, -1),
            ("f2", 0, 3, TaskType.TRAINING, -1),
            ("f2", 3, 6, TaskType.TRAINING, -1),
            ("f2", 6, 9, TaskType.TRAINING, -1),
            ("f2", 9, 10, TaskType.TRAINING, -1),
        ]

        got_tasks = [task_d.get(i // 2) for i in range(8)]
        self.assertEqual(list(range(1, 9)), [k for k, _ in got_tasks])
        self.assertEqual(sorted(v._info() for _, v in got_tasks), all_tasks)

        # drained
        self.assertEqual((-1, None), task_d.get(10))

        for t in (1, 3, 5, 7, 2, 8):
            task_d.report(t, True)
        self.assertEqual(2, len(task_d._doing))

        # failure requeues
        task_d.report(next(iter(task_d._doing)), False)
        self.assertEqual(1, len(task_d._doing))

        # dead-worker recovery requeues in-flight tasks
        task_d.recover_tasks(next(iter(task_d._doing.values()))[0])
        self.assertEqual(0, len(task_d._doing))
        self.assertEqual(2, len(task_d._todo))

        id1, _ = task_d.get(11)
        id2, _ = task_d.get(12)
        task_d.report(id1, True)
        task_d.report(id2, True)
        self.assertTrue(task_d.finished())

    def test_create_tasks_with_non_zero_start_ind(self):
        task_d = TaskDispatcher({"f1": (0, 10), "f2": (10, 10)}, {}, {}, 3, 1)
        all_tasks = [
            ("f1", 0, 3, TaskType.TRAINING, -1),
            ("f1", 3, 6, TaskType.TRAINING, -1),
            ("f1", 6, 9, TaskType.TRAINING, -1),
            ("f1", 9, 10, TaskType.TRAINING, -1),
            ("f2", 10, 13, TaskType.TRAINING, -1),
            ("f2", 13, 16, TaskType.TRAINING, -1),
            ("f2", 16, 19, TaskType.TRAINING, -1),
            ("f2", 19, 20, TaskType.TRAINING, -1),
        ]
        got_tasks = [task_d.get(i // 2) for i in range(8)]
        self.assertEqual(list(range(1, 9)), [k for k, _ in got_tasks])
        self.assertEqual(sorted(v._info() for _, v in got_tasks), all_tasks)

    def test_epoch_rollover(self):
        task_d = TaskDispatcher({"f1": (0, 10), "f2": (0, 10)}, {}, {}, 3, 2)
        epoch_tasks = [
            ("f1", 0, 3, TaskType.TRAINING, -1),
            ("f1", 3, 6, TaskType.TRAINING, -1),
            ("f1", 6, 9, TaskType.TRAINING, -1),
            ("f1", 9, 10, TaskType.TRAINING, -1),
            ("f2", 0, 3, TaskType.TRAINING, -1),
            ("f2", 3, 6, TaskType.TRAINING, -1),
            ("f2", 6, 9, TaskType.TRAINING, -1),
            ("f2", 9, 10, TaskType.TRAINING, -1),
        ]
        for _ in range(2):
            got_tasks = [task_d.get(i // 2) for i in range(8)]
            self.assertEqual(
                sorted(v._info() for _, v in got_tasks), epoch_tasks
            )

    def test_invoke_save_model_callback(self):
        task_d = TaskDispatcher({"f1": (0, 10), "f2": (0, 10)}, {}, {}, 3, 1)
        task_d.add_deferred_callback_create_save_model_task("/saved_models/")
        task_d._todo.clear()
        task_d.invoke_deferred_callback()
        self.assertEqual(len(task_d._todo), 1)
        self.assertEqual(task_d._todo[0].type, TaskType.SAVE_MODEL)

    def test_eval_tasks(self):
        task_d = TaskDispatcher({}, {"e1": (0, 6)}, {}, 3, 1)
        tid, task = task_d.get_eval_task(0)
        self.assertEqual(task.type, TaskType.EVALUATION)
        task_d.report(tid, False)  # failed eval goes back on eval queue
        self.assertEqual(2, len(task_d._eval_todo))
        ids = []
        for _ in range(2):
            tid, task = task_d.get_eval_task(0)
            ids.append(tid)
        self.assertEqual((-1, None), task_d.get_eval_task(0))
        for tid in ids:
            task_d.report(tid, True)
        self.assertTrue(task_d.finished())


if __name__ == "__main__":
    unittest.main()
