"""ODPS reader/writer against a fake SDK (reference odps_io_test.py is
gated on live credentials; the fake makes the parallel slice pipeline,
cache-batch heuristic, retry, and writer testable hermetically)."""

import sys
import threading
import types

import numpy as np
import pytest


class FakeRecord:
    def __init__(self, values):
        self.values = values


class FakeReader:
    def __init__(self, rows, fail_first=None):
        self._rows = rows
        self.count = len(rows)
        self._fail_first = fail_first

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def read(self, start=0, count=None, columns=None):
        if self._fail_first is not None and self._fail_first[0] > 0:
            self._fail_first[0] -= 1
            raise IOError("transient odps failure")
        for row in self._rows[start : start + count]:
            if columns is not None:
                yield FakeRecord([row[c] for c in columns])
            else:
                yield FakeRecord(list(row.values()))


class FakeTable:
    def __init__(self, rows, fail_first=None):
        self._rows = rows
        self._fail_first = fail_first
        cols = [types.SimpleNamespace(name=c) for c in rows[0]]
        self.table_schema = types.SimpleNamespace(columns=cols)
        self.open_calls = 0
        self.lock = threading.Lock()

    def open_reader(self, partition=None):
        with self.lock:
            self.open_calls += 1
        return FakeReader(self._rows, self._fail_first)

    def open_writer(self):
        table = self

        class W:
            def __enter__(self):
                table.written = []
                return self

            def __exit__(self, *a):
                return False

            def write(self, row):
                table.written.append(row)

        return W()


class FakeODPS:
    tables = {}

    def __init__(self, access_id=None, secret_access_key=None, project=None,
                 endpoint=None):
        pass

    def get_table(self, name):
        return FakeODPS.tables[name]

    def exist_table(self, name):
        return name in FakeODPS.tables

    def create_table(self, name, schema, if_not_exists=False):
        cols = [c.split()[0] for c in schema.split(",")]
        FakeODPS.tables[name] = FakeTable([{c: 0 for c in cols}])


@pytest.fixture
def fake_odps(monkeypatch):
    mod = types.ModuleType("odps")
    mod.ODPS = FakeODPS
    monkeypatch.setitem(sys.modules, "odps", mod)
    FakeODPS.tables = {}
    return FakeODPS


def _table(n=100, fail_first=None):
    rows = [{"a": i, "b": float(i) * 2} for i in range(n)]
    t = FakeTable(rows, fail_first=fail_first)
    FakeODPS.tables["t1"] = t
    return t


def _reader(**kw):
    from elasticdl_tpu.data.odps_io import ODPSReader

    return ODPSReader("proj", "id", "key", "t1", **kw)


def test_to_iterator_covers_table_in_order_batches(fake_odps):
    _table(100)
    r = _reader()
    batches = list(
        r.to_iterator(1, 0, batch_size=16, cache_batch_count=2)
    )
    got = [row[0] for b in batches for row in b]
    assert sorted(got) == list(range(100))
    assert max(len(b) for b in batches) <= 16


def test_to_iterator_partitions_across_workers(fake_odps):
    _table(96)
    r = _reader()
    seen = []
    for w in range(3):
        for b in r.to_iterator(3, w, batch_size=8, cache_batch_count=1):
            seen.extend(row[0] for row in b)
    assert sorted(seen) == list(range(96))


def test_to_iterator_epochs_and_worker_bounds(fake_odps):
    _table(20)
    r = _reader()
    rows = [
        row
        for b in r.to_iterator(1, 0, batch_size=5, epochs=3,
                               cache_batch_count=1)
        for row in b
    ]
    assert len(rows) == 60
    with pytest.raises(ValueError):
        list(r.to_iterator(2, 2, batch_size=5))
    with pytest.raises(ValueError):
        list(r.to_iterator(1, 0, batch_size=0))


def test_cache_batch_heuristic_bounds(fake_odps):
    _table(1000)
    r = _reader()
    est = r._estimate_cache_batch_count(["a", "b"], 1000, 16)
    assert 1 <= est <= 50
    # tiny tables skip sampling entirely
    assert r._estimate_cache_batch_count(["a"], 5, 16) == 1


def test_parallel_downloads_overlap(fake_odps):
    t = _table(256)
    r = _reader(num_processes=4)
    list(r.to_iterator(1, 0, batch_size=8, cache_batch_count=2))
    # 16 slices of 16 rows -> at least that many reader opens (pipelined)
    assert t.open_calls >= 16


def test_read_retries_transient_failures(fake_odps):
    _table(10, fail_first=[2])
    from elasticdl_tpu.data import odps_io

    odps_io._RETRY_DELAY_SECS = 0
    r = _reader()
    rows = list(r.read_batch(0, 10))
    assert len(rows) == 10


def test_writer_creates_table_and_writes(fake_odps):
    from elasticdl_tpu.data.odps_io import ODPSWriter

    w = ODPSWriter(
        "proj", "id", "key", "t_new",
        columns=["x", "y"], column_types=["bigint", "double"],
    )
    w.from_iterator(iter([(1, 2.0), (3, 4.0)]))
    assert FakeODPS.tables["t_new"].written == [[1, 2.0], [3, 4.0]]


def test_missing_sdk_raises_clearly(monkeypatch):
    import builtins

    real_import = builtins.__import__

    def no_odps(name, *a, **k):
        if name == "odps":
            raise ImportError("No module named 'odps'")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_odps)
    monkeypatch.delitem(sys.modules, "odps", raising=False)
    from elasticdl_tpu.data.odps_io import ODPSReader

    with pytest.raises(ImportError, match="pyodps"):
        ODPSReader("p", "i", "k", "t")


def test_fallback_split_is_disjoint(fake_odps):
    """table smaller than num_workers x slice: slices shrink but stay
    disjoint — no row is ever read twice across workers."""
    _table(20)
    r = _reader()
    seen = []
    for w in range(3):
        for b in r.to_iterator(3, w, batch_size=5, cache_batch_count=2):
            seen.extend(row[0] for row in b)
    assert sorted(seen) == list(range(20))


def test_shuffle_reshuffles_each_epoch(fake_odps):
    import random as _random

    _table(64)
    r = _reader()
    _random.seed(123)
    orders = []
    batches = list(
        r.to_iterator(
            1, 0, batch_size=4, epochs=4, shuffle=True, cache_batch_count=1
        )
    )
    per_epoch = len(batches) // 4
    for e in range(4):
        orders.append(
            tuple(b[0][0] for b in batches[e * per_epoch : (e + 1) * per_epoch])
        )
    assert len(set(orders)) > 1, "epochs replayed the identical order"


def test_read_batch_streams_in_chunks(fake_odps):
    from elasticdl_tpu.data import odps_io

    t = _table(100)
    r = _reader()
    old = odps_io._STREAM_CHUNK_ROWS
    odps_io._STREAM_CHUNK_ROWS = 16
    try:
        calls_before = t.open_calls
        it = r.read_batch(0, 100)
        first = next(it)
        assert first[0] == 0
        # only the first chunk has been fetched so far
        assert t.open_calls == calls_before + 1
        rest = list(it)
        assert len(rest) == 99
        assert t.open_calls == calls_before + 7  # ceil(100/16) chunks
    finally:
        odps_io._STREAM_CHUNK_ROWS = old

