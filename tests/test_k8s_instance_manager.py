"""k8s elasticity brain against a fake client (no cluster needed).

The reference could only test its instance manager against a live
minikube (reference tests/k8s_instance_manager_test.py, gated on
K8S_TESTS); here the decision core is pure and the client is injected, so
the full event matrix — worker deleted -> recover + fresh-id relaunch,
PS deleted -> same-id relaunch, Succeeded -> no relaunch, relaunch budget
exhaustion, membership epoch bumps — runs in-process.
"""

from types import SimpleNamespace

from elasticdl_tpu.master.k8s_instance_manager import (
    PS,
    WORKER,
    InstanceManager,
    decide_on_exit,
)
from elasticdl_tpu.master.membership_service import MembershipService


class FakeK8sClient:
    """Records pod creations and lets tests fire watch events."""

    def __init__(self):
        self.created = []  # (kind, id, args)
        self.deleted = []
        self.services = []
        self.labels = {}
        self.closed = False

    def close(self):
        # mirrors K8sClient.close(): stop_relaunch_and_remove_all_pods
        # shuts the pod-event watch down once relaunch is off
        self.closed = True

    def _pod(self, name):
        return SimpleNamespace(
            kind="Pod",
            metadata=SimpleNamespace(name=name),
            status=SimpleNamespace(phase="Pending"),
        )

    def create_worker(self, worker_id, args=None, **_):
        self.created.append((WORKER, worker_id, args or []))
        return self._pod("worker-%d" % worker_id)

    def create_ps(self, ps_id, args=None, **_):
        self.created.append((PS, ps_id, args or []))
        return self._pod("ps-%d" % ps_id)

    def create_ps_service(self, ps_id):
        self.services.append(ps_id)

    def get_ps_service_address(self, ps_id):
        return "ps-svc-%d:3333" % ps_id

    def get_master_pod_name(self):
        return "the-master"

    def patch_labels_to_pod(self, pod_name, labels_dict):
        self.labels.setdefault(pod_name, {}).update(labels_dict)

    def delete_worker(self, worker_id):
        self.deleted.append((WORKER, worker_id))

    def delete_ps(self, ps_id):
        self.deleted.append((PS, ps_id))


class FakeDispatcher:
    def __init__(self):
        self.recovered = []

    def recover_tasks(self, worker_id):
        self.recovered.append(worker_id)


def _event(pod_name, phase, evt_type):
    return {
        "type": evt_type,
        "object": SimpleNamespace(
            kind="Pod",
            metadata=SimpleNamespace(name=pod_name),
            status=SimpleNamespace(phase=phase),
        ),
    }


def _manager(num_workers=3, num_ps=2, membership=None, **kw):
    client = FakeK8sClient()
    task_d = FakeDispatcher()
    manager = InstanceManager(
        task_d,
        num_workers=num_workers,
        num_ps=num_ps,
        worker_command=["python"],
        ps_command=["python"],
        membership=membership,
        k8s_client=client,
        **kw,
    )
    return manager, client, task_d


def test_decide_on_exit_matrix():
    d = decide_on_exit(WORKER, "Failed", True, 5)
    assert d.recover and d.relaunch and d.new_id
    # a worker that Succeeded is done, not dead
    d = decide_on_exit(WORKER, "Succeeded", True, 5)
    assert d.recover and not d.relaunch
    # budget spent / relaunch disabled
    assert not decide_on_exit(WORKER, "Failed", True, 0).relaunch
    assert not decide_on_exit(WORKER, "Failed", False, 5).relaunch
    # PS keeps its id and recovers nothing
    d = decide_on_exit(PS, "Failed", True, 5)
    assert not d.recover and d.relaunch and not d.new_id


def test_worker_deleted_recovers_and_relaunches_fresh_id():
    manager, client, task_d = _manager()
    manager.start_all_ps()
    manager.start_workers()
    assert [c[:2] for c in client.created] == [
        (PS, 0),
        (PS, 1),
        (WORKER, 0),
        (WORKER, 1),
        (WORKER, 2),
    ]
    assert client.services == [0, 1]
    # workers get the PS addresses on their command line
    assert "ps-svc-0:3333,ps-svc-1:3333" in client.created[2][2]

    manager.handle_pod_event(_event("worker-1", "Failed", "DELETED"))
    assert task_d.recovered == [1]
    kind, new_id, _ = client.created[-1]
    assert kind == WORKER and new_id == 3  # fresh id, not a reuse


def test_ps_deleted_relaunches_same_id():
    manager, client, task_d = _manager()
    manager.start_all_ps()
    manager.handle_pod_event(_event("ps-1", "Failed", "DELETED"))
    assert task_d.recovered == []  # nothing to recover for PS
    assert client.created[-1][:2] == (PS, 1)
    # the replacement keeps the stable service (created once at launch +
    # once on relaunch is fine; the DNS name is identical)
    assert client.services.count(1) >= 1


def test_succeeded_worker_not_relaunched():
    manager, client, task_d = _manager()
    manager.start_workers()
    n = len(client.created)
    manager.handle_pod_event(_event("worker-2", "Succeeded", "DELETED"))
    assert task_d.recovered == [2]  # recover is harmless and uniform
    assert len(client.created) == n  # no replacement


def test_relaunch_budget_exhausts():
    manager, client, _ = _manager(num_workers=1, max_relaunches=2)
    manager.start_workers()
    for wid in (0, 1, 2):
        manager.handle_pod_event(
            _event("worker-%d" % wid, "Failed", "DELETED")
        )
    # initial launch + 2 relaunches, then the budget is gone
    worker_launches = [c for c in client.created if c[0] == WORKER]
    assert len(worker_launches) == 3


def test_stop_relaunch_and_remove_disables_replacements():
    manager, client, _ = _manager()
    manager.start_all_ps()
    manager.start_workers()
    manager.stop_relaunch_and_remove_all_pods()
    assert (WORKER, 0) in client.deleted and (PS, 1) in client.deleted
    n = len(client.created)
    manager.handle_pod_event(_event("worker-0", "Failed", "DELETED"))
    manager.handle_pod_event(_event("ps-0", "Failed", "DELETED"))
    assert len(client.created) == n


def test_worker_death_bumps_membership_epoch():
    membership = MembershipService(expected_workers=2)
    manager, client, _ = _manager(num_workers=2, membership=membership)
    manager.start_workers()
    membership.register(0)
    membership.register(1)
    epoch = membership.epoch
    manager.handle_pod_event(_event("worker-0", "Failed", "DELETED"))
    assert membership.epoch > epoch
    w = membership.get_world(1)
    assert w["num_processes"] == 1 and w["process_id"] == 0


def test_phase_observation_and_status_label():
    manager, client, _ = _manager(num_workers=2)
    manager.start_workers()
    manager.handle_pod_event(_event("worker-0", "Running", "MODIFIED"))
    counter = manager.get_worker_counter()
    assert counter["Running"] == 1
    manager.update_status("Finished")
    assert client.labels["the-master"] == {"status": "Finished"}


def test_unknown_pod_event_ignored():
    manager, client, task_d = _manager()
    manager.start_workers()
    n = len(client.created)
    manager.handle_pod_event(_event("interloper-pod", "Failed", "DELETED"))
    manager.handle_pod_event(_event("the-master", "Running", "MODIFIED"))
    assert task_d.recovered == [] and len(client.created) == n


def test_unresponsive_member_gets_fenced():
    """A membership drop must delete the wedged worker's pod so its
    tasks recover through the ordinary DELETED path."""
    import time

    membership = MembershipService(
        expected_workers=2, confirm_timeout_secs=0.2
    )
    manager, client, _ = _manager(num_workers=2, membership=membership)
    manager.start_workers()
    membership.get_world(0)
    membership.get_world(1)  # world [0, 1] formed, awaiting confirms
    membership._last_poll[0] = time.time() - 3.0  # 0 goes quiet
    deadline = time.time() + 5
    while (WORKER, 0) not in client.deleted:
        w = membership.get_world(1)
        if w["ready"]:
            break
        assert time.time() < deadline
        time.sleep(0.05)
    assert (WORKER, 0) in client.deleted


def test_standby_pool_semantics():
    from elasticdl_tpu.master.membership_service import StandbyPool

    pool = StandbyPool()
    # activation before any standby warmed: nothing to promote
    assert pool.activate(7) is None
    # a standby registers by polling; unactivated polls return None
    assert pool.poll(100) is None
    assert pool.parked_count() == 1
    token = pool.activate(7)
    assert token == 100
    assert pool.poll(100) == 7  # the parked process picks up its id
    assert pool.parked_count() == 0
    # a dead standby is forgotten
    assert pool.poll(101) is None
    pool.forget(101)
    assert pool.activate(8) is None


def test_local_manager_promotes_warmed_standby(tmp_path):
    """A worker death promotes a warmed standby (re-keyed under its new
    worker id, pool refilled) instead of cold-relaunching."""
    import sys
    import time

    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )
    from elasticdl_tpu.master.membership_service import StandbyPool

    class FakeMembership:
        def __init__(self):
            self.standby = StandbyPool()
            self.removed = []

        def set_fencer(self, fn):
            pass

        def remove(
            self,
            worker_id,
            departing=False,
            defer_bump_secs=0,
            exit_code=None,
        ):
            self.removed.append(worker_id)

    class FakeDispatcher:
        def __init__(self):
            self.recovered = []

        def recover_tasks(self, worker_id):
            self.recovered.append(worker_id)

    membership = FakeMembership()
    task_d = FakeDispatcher()

    def worker_command(worker_id):
        # inert stand-in processes; the real --standby loop is exercised
        # by the slow kill rung
        return [sys.executable, "-c", "import time; time.sleep(120)"]

    manager = LocalInstanceManager(
        task_d,
        1,
        worker_command,
        env=None,
        membership=membership,
        num_standby=1,
        restart_policy="Always",
    )
    manager.start_workers()
    try:
        assert set(manager._procs) == {("worker", 0), ("standby", 1)}
        # the standby warms up (its first poll registers it)
        assert membership.standby.poll(1) is None

        manager.kill_worker(0)
        deadline = time.time() + 20
        while ("worker", 2) not in manager._procs:
            assert time.time() < deadline, manager._procs
            time.sleep(0.1)
        # the standby process became worker 2 (same pid), the dead
        # worker's tasks recovered, and a fresh standby refilled
        assert membership.standby.poll(1) == 2
        assert task_d.recovered == [0]
        assert membership.removed == [0]
        deadline = time.time() + 20
        while not any(k[0] == "standby" for k in manager._procs):
            assert time.time() < deadline, manager._procs
            time.sleep(0.1)
    finally:
        manager.stop_relaunch_and_remove_all_pods()


def test_container_exit_code_prefers_worker_over_sidecar():
    """An injected sidecar (istio-proxy) exiting 0 must not mask a
    crashed worker container: the status matching the pod name wins,
    and with no name match any nonzero code wins."""
    from elasticdl_tpu.master.k8s_instance_manager import (
        container_exit_code,
    )

    def status(name, code):
        return SimpleNamespace(
            name=name,
            state=SimpleNamespace(
                terminated=SimpleNamespace(exit_code=code)
            ),
        )

    def pod(name, statuses):
        return SimpleNamespace(
            metadata=SimpleNamespace(name=name),
            status=SimpleNamespace(container_statuses=statuses),
        )

    # sidecar listed first with rc 0, worker (named after pod) rc 139
    p = pod(
        "worker-1", [status("istio-proxy", 0), status("worker-1", 139)]
    )
    assert container_exit_code(p) == 139
    # no name match at all: prefer the nonzero code
    p = pod("worker-2", [status("sidecar-a", 0), status("sidecar-b", 1)])
    assert container_exit_code(p) == 1
    # all rc 0, no name match: 0 (clean)
    p = pod("worker-3", [status("sidecar-a", 0)])
    assert container_exit_code(p) == 0
    # still-running containers / missing statuses: None
    p = pod("worker-4", [SimpleNamespace(name="w", state=None)])
    assert container_exit_code(p) is None
    assert container_exit_code(SimpleNamespace(metadata=None)) is None
