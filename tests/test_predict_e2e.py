"""Predict-job E2E through the in-process master (reference CI runs a
real `elasticdl predict` job, scripts/client_test.sh; round-1 verdict
flagged this path as untested beyond unit plumbing)."""

import numpy as np

from elasticdl_tpu.common.constants import JobType
from elasticdl_tpu.master.checkpoint_service import CheckpointService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.worker.prediction_outputs_processor import (
    BasePredictionOutputsProcessor,
)
from elasticdl_tpu.worker.worker import Worker
from tests.in_process_master import InProcessMaster
from tests.test_utils import MODEL_ZOO_PATH, DatasetName, create_recordio_file


class CapturingProcessor(BasePredictionOutputsProcessor):
    def __init__(self):
        self.chunks = []

    def process(self, predictions, worker_id):
        self.chunks.append((worker_id, np.asarray(predictions)))


def test_prediction_only_job_e2e():
    records = 96
    f = create_recordio_file(records, DatasetName.IMAGE_DEFAULT, (28, 28))
    task_d = TaskDispatcher({}, {}, {f: (0, records)}, 32, 1)
    master = MasterServicer(
        1,
        16,
        None,
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=True,
    )
    worker = Worker(
        worker_id=7,
        job_type=JobType.PREDICTION_ONLY,
        minibatch_size=16,
        model_zoo=MODEL_ZOO_PATH,
        model_def="mnist_subclass.mnist_subclass.CustomModel",
    )
    processor = CapturingProcessor()
    worker._prediction_outputs_processor = processor
    worker._stub = InProcessMaster(master)
    worker.run()

    assert task_d.finished()
    total = sum(chunk.shape[0] for _, chunk in processor.chunks)
    assert total == records
    for worker_id, chunk in processor.chunks:
        assert worker_id == 7
        assert chunk.shape[1:] == (10,)  # mnist class logits
        assert np.isfinite(chunk).all()
