"""HBM-sharded parameters on the multi-process elastic plane
(BASELINE.json north star: row-partitioned tables in pod HBM + resizable
process group).

The elastic weighted step scales the loss by w/psum(w) inside the
differentiated function so a2a-routed table gradients carry their
device's weight at the source; sharded leaves enter/leave the step as
local shards with no psum. These pin that math against the dense twin,
then run the real 2-OS-process job.
"""

import glob
import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from elasticdl_tpu.nn.model_api import init_variables, split_variables
from elasticdl_tpu.parallel.elastic import (
    build_state_specs,
    collect_sharded_paths,
    host_copy,
    make_elastic_train_step,
    place_from_host_specs,
)
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.training.step import TrainState, make_train_step
from model_zoo.deepfm_edl_embedding import deepfm_edl_embedding as zoo

VOCAB = 64


def _batches(n_steps, batch=16, length=10, seed=7):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_steps):
        ids = rng.integers(0, VOCAB, size=(batch, length)).astype(np.int64)
        labels = rng.integers(0, 2, size=(batch, 1)).astype(np.int64)
        out.append(({"feature": ids}, labels))
    return out


def _init_state(model, batch, opt):
    variables = init_variables(model, jax.random.PRNGKey(0), batch)
    params, state = split_variables(variables)
    return TrainState.create(params, state, opt)


def _sharded_setup(mesh, opt, example):
    model = zoo.DeepFMEdl(
        embedding_dim=8,
        fc_unit=8,
        vocab_size=VOCAB,
        collective=True,
        table_axis="data",
    )
    ts_host = _init_state(model, example, opt)
    sharded = collect_sharded_paths(zoo.param_shardings(mesh))
    specs = build_state_specs(ts_host, sharded)
    ts = place_from_host_specs(mesh, ts_host, specs)
    return model, ts, specs


def test_sharded_elastic_step_matches_dense_training():
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    opt = optax.sgd(0.05)
    batches = _batches(6)
    model, ts, specs = _sharded_setup(mesh, opt, batches[0][0])

    step = make_elastic_train_step(
        model, zoo.loss, opt, mesh, state_specs=specs
    )

    def put_batch(tree):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, P(*(("data",) + (None,) * (x.ndim - 1))))
            ),
            tree,
        )

    ones = jax.device_put(
        np.ones(8, np.float32), NamedSharding(mesh, P("data"))
    )
    ep = jax.device_put(
        np.zeros(8, np.int32), NamedSharding(mesh, P("data"))
    )
    key = jax.random.PRNGKey(5)
    losses = []
    with mesh:
        for features, labels in batches:
            ts, loss, n, _ = step(
                ts, put_batch(features), put_batch(labels), ones, ep, key
            )
            assert int(n) == 8
            losses.append(float(loss))

    # dense twin: same init, plain full-batch steps
    dense_model = zoo.DeepFMEdl(
        embedding_dim=8, fc_unit=8, vocab_size=VOCAB, force_hbm=True
    )
    ts_d = _init_state(dense_model, batches[0][0], opt)
    dense_step = make_train_step(dense_model, zoo.loss, opt)
    dense_losses = []
    for features, labels in batches:
        ts_d, loss_d = dense_step(ts_d, features, labels, key)
        dense_losses.append(float(loss_d))

    np.testing.assert_allclose(losses, dense_losses, rtol=2e-4, atol=1e-5)
    # the trained table shards reassemble to the dense table
    got = np.asarray(
        jax.device_get(ts.params["embedding"]["table"])
    )
    want = np.asarray(ts_d.params["embedding"]["table"])
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


def test_sharded_elastic_drain_is_exact_noop():
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    opt = optax.sgd(0.05)
    batches = _batches(2, seed=9)
    model, ts, specs = _sharded_setup(mesh, opt, batches[0][0])
    step = make_elastic_train_step(
        model, zoo.loss, opt, mesh, state_specs=specs
    )

    def put_batch(tree):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, P(*(("data",) + (None,) * (x.ndim - 1))))
            ),
            tree,
        )

    zeros = jax.device_put(
        np.zeros(8, np.float32), NamedSharding(mesh, P("data"))
    )
    ep = jax.device_put(
        np.zeros(8, np.int32), NamedSharding(mesh, P("data"))
    )
    key = jax.random.PRNGKey(3)
    with mesh:
        ts2, _, n, _ = step(
            ts,
            put_batch(batches[0][0]),
            put_batch(batches[0][1]),
            zeros,
            ep,
            key,
        )
    assert int(n) == 0
    assert int(host_copy(ts2.version)) == int(host_copy(ts.version))
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(ts2.params)),
        jax.tree_util.tree_leaves(jax.device_get(ts.params)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sharded_elastic_partial_weights_downweight_dead_devices():
    """Weight-0 devices' examples must not move the table: train with
    half the devices at weight 0 == dense training on only the live
    devices' examples (each live example at weight 1/denom)."""
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    opt = optax.sgd(0.05)
    batches = _batches(3, seed=11)
    model, ts, specs = _sharded_setup(mesh, opt, batches[0][0])
    step = make_elastic_train_step(
        model, zoo.loss, opt, mesh, state_specs=specs
    )

    def put_batch(tree):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(
                x, NamedSharding(mesh, P(*(("data",) + (None,) * (x.ndim - 1))))
            ),
            tree,
        )

    w = np.array([1, 1, 1, 1, 0, 0, 0, 0], np.float32)
    weights = jax.device_put(w, NamedSharding(mesh, P("data")))
    ep = jax.device_put(
        np.zeros(8, np.int32), NamedSharding(mesh, P("data"))
    )
    key = jax.random.PRNGKey(4)
    with mesh:
        for features, labels in batches:
            ts, loss, n, _ = step(
                ts, put_batch(features), put_batch(labels), weights, ep, key
            )
            assert int(n) == 4

    # dense twin on the live half only (rows 0..7 of each 16-row batch)
    dense_model = zoo.DeepFMEdl(
        embedding_dim=8, fc_unit=8, vocab_size=VOCAB, force_hbm=True
    )
    ts_d = _init_state(dense_model, batches[0][0], opt)
    dense_step = make_train_step(dense_model, zoo.loss, opt)
    for features, labels in batches:
        half = (
            {"feature": features["feature"][:8]},
            labels[:8],
        )
        ts_d, _ = dense_step(ts_d, half[0], half[1], key)

    got = np.asarray(jax.device_get(ts.params["embedding"]["table"]))
    want = np.asarray(ts_d.params["embedding"]["table"])
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


@pytest.mark.slow
def test_two_process_sharded_elastic_job(tmp_path, monkeypatch):
    """Real 2-OS-process elastic job, deepfm tables sharded over the
    2-device world, checkpoints written by BOTH ranks, export assembles
    the full model."""
    # cold worker start (jax import) can straggle past the default
    # 30 s form grace on a loaded CI host; the tiny job would then run
    # to completion on a partial world before the straggler registers
    monkeypatch.setenv("EDL_FORM_GRACE_SECS", "120")
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.common.model_utils import load_from_checkpoint_file
    from elasticdl_tpu.common.sharded_checkpoint import (
        load_sharded_to_host,
    )
    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )
    from elasticdl_tpu.master.master import Master
    from tests.test_elastic_allreduce import _worker_env
    from tests.test_utils import (
        MODEL_ZOO_PATH,
        DatasetName,
        create_recordio_file,
    )

    create_recordio_file(
        128, DatasetName.FRAPPE, 10, temp_dir=str(tmp_path)
    )
    ckpt_dir = str(tmp_path / "ckpt")
    export_dir = str(tmp_path / "export")
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    args = parse_master_args(
        [
            "--job_name", "elastic-sharded-test",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", "embedding_dim=8,fc_unit=8,vocab_size=96",
            "--minibatch_size", "16",
            "--num_minibatches_per_task", "1",
            "--num_epochs", "1",
            "--training_data", str(tmp_path),
            "--num_workers", "2",
            "--num_ps_pods", "0",
            "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
            "--output", export_dir,
        ]
    )
    master = Master(args)
    master.prepare()

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id", str(worker_id),
            "--job_type", "training_only",
            "--master_addr", "localhost:%d" % master.port,
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", "embedding_dim=8,fc_unit=8,vocab_size=96",
            "--minibatch_size", "16",
            "--distribution_strategy", "AllreduceStrategy",
            "--comm_host", "localhost",
            "--checkpoint_dir", ckpt_dir,
            "--checkpoint_steps", "2",
        ]

    manager = LocalInstanceManager(
        master.task_d,
        2,
        worker_command,
        env=_worker_env(),
        membership=master.membership,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()
    runner.join(timeout=300)
    assert not runner.is_alive(), "master did not finish"
    assert master.task_d.finished()
    manager.stop_relaunch_and_remove_all_pods()

    # both ranks wrote their shard manifests
    dirs = sorted(glob.glob(os.path.join(ckpt_dir, "ckpt_v*")))
    assert dirs, "no sharded checkpoints written"
    latest = dirs[-1]
    manifests = sorted(
        os.path.basename(p)
        for p in glob.glob(os.path.join(latest, "manifest-*.json"))
    )
    assert manifests == ["manifest-0.json", "manifest-1.json"], manifests

    # the checkpoint assembles to the full model: both table shards
    version, tree = load_sharded_to_host(latest)
    table = tree["params"]["embedding"]["table"]
    assert table.shape == (96, 8)
    assert version > 0

    # the export task assembled a full host model.chkpt
    exports = glob.glob(os.path.join(export_dir, "*", "model.chkpt"))
    assert exports, "no exported model"
    export_version, named = load_from_checkpoint_file(exports[0])
    assert named["embedding/table"].shape == (96, 8)


@pytest.mark.slow
def test_sharded_elastic_job_survives_worker_kill(tmp_path, monkeypatch):
    """SIGKILL one of 3 workers mid-job: survivors re-form a 2-device
    world, the 3-way-sharded tables restore from the last complete
    checkpoint ONTO THE NEW MESH (cross-mesh re-slice), and the job
    completes with every task accounted."""
    import time

    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )
    from elasticdl_tpu.master.master import Master
    from tests.test_elastic_allreduce import _worker_env
    from tests.test_utils import (
        MODEL_ZOO_PATH,
        DatasetName,
        create_recordio_file,
    )

    monkeypatch.setenv("EDL_FORM_GRACE_SECS", "120")
    create_recordio_file(
        192, DatasetName.FRAPPE, 10, temp_dir=str(tmp_path)
    )
    ckpt_dir = str(tmp_path / "ckpt")
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    model_params = "embedding_dim=8,fc_unit=8,vocab_size=96"
    args = parse_master_args(
        [
            "--job_name", "elastic-sharded-kill",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--num_minibatches_per_task", "1",
            "--num_epochs", "2",
            "--training_data", str(tmp_path),
            "--num_workers", "3",
            "--num_ps_pods", "0",
            "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
        ]
    )
    master = Master(args)
    master.prepare()

    completed = []
    orig_report = master.task_d.report

    def counting_report(task_id, success):
        if success:
            completed.append(task_id)
        return orig_report(task_id, success)

    master.task_d.report = counting_report

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id", str(worker_id),
            "--job_type", "training_only",
            "--master_addr", "localhost:%d" % master.port,
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--distribution_strategy", "AllreduceStrategy",
            "--comm_host", "localhost",
            "--checkpoint_dir", ckpt_dir,
            "--checkpoint_steps", "2",
        ]

    manager = LocalInstanceManager(
        master.task_d,
        3,
        worker_command,
        env=_worker_env(),
        membership=master.membership,
        max_relaunches=10,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()

    deadline = time.time() + 240
    while len(completed) < 2:
        assert time.time() < deadline, "job made no progress"
        assert runner.is_alive(), "master exited early"
        time.sleep(0.5)
    victims = manager.live_workers()
    assert victims, "no live workers to kill"
    manager.kill_worker(victims[-1])

    runner.join(timeout=420)
    assert not runner.is_alive(), "master did not finish after the kill"
    assert master.task_d.finished()
    # 192*2 records / 16 records-per-task = 24 training tasks
    assert len(set(completed)) == 24
    manager.stop_relaunch_and_remove_all_pods()

    # the final checkpoint assembles the full tables regardless of the
    # world size changes along the way
    from elasticdl_tpu.common.sharded_checkpoint import (
        load_sharded_to_host,
    )

    dirs = {
        int(os.path.basename(d)[len("ckpt_v"):]): d
        for d in glob.glob(os.path.join(ckpt_dir, "ckpt_v*"))
    }
    assert dirs, "no checkpoints written"
    table = None
    for v in sorted(dirs, reverse=True):
        try:
            _, tree = load_sharded_to_host(dirs[v])
            table = tree["params"]["embedding"]["table"]
            break
        except Exception:
            continue
    assert table is not None and table.shape == (96, 8)


def test_cross_leaf_optimizer_rejected_for_sharded_jobs(monkeypatch):
    """optax.clip_by_global_norm folds each rank's different local shard
    gradients into a per-rank scale — the trainer must refuse it at
    build time for sharded jobs (advisor finding), and accept per-leaf
    optimizers and replicated jobs unchanged."""
    from elasticdl_tpu.parallel.elastic import (
        ElasticDPTrainer,
        optimizer_couples_leaves,
    )

    coupled = optax.chain(
        optax.clip_by_global_norm(1.0), optax.sgd(0.1)
    )
    assert optimizer_couples_leaves(coupled)
    for ok in (optax.sgd(0.1), optax.adam(1e-3), optax.adagrad(0.1),
               optax.chain(optax.clip(1.0), optax.sgd(0.1))):
        assert not optimizer_couples_leaves(ok)

    def model():
        import flax.linen as nn

        return nn.Dense(2)

    # the gate runs at establish (after ensure_world — probing earlier
    # would initialize the XLA backend and break world formation); here
    # the internal check is driven directly with sharded paths present
    trainer = ElasticDPTrainer(model(), lambda o, l: o.sum(), coupled)
    trainer._sharded_paths = {("table",): P("data", None)}
    with pytest.raises(ValueError, match="couples gradients"):
        trainer._check_optimizer_coupling()

    # escape hatch
    monkeypatch.setenv("EDL_ALLOW_CROSS_LEAF_OPT", "1")
    trainer2 = ElasticDPTrainer(model(), lambda o, l: o.sum(), coupled)
    trainer2._sharded_paths = {("table",): P("data", None)}
    trainer2._check_optimizer_coupling()
    monkeypatch.delenv("EDL_ALLOW_CROSS_LEAF_OPT")

    # replicated jobs (no sharded paths) keep accepting global-norm
    # clipping: every rank sees identical gradients there
    trainer3 = ElasticDPTrainer(model(), lambda o, l: o.sum(), coupled)
    trainer3._check_optimizer_coupling()


def test_host_model_matches_collective_param_structure():
    """build_host_model must accept the collective model's params
    verbatim (eval/export assemble checkpoints written by it)."""
    example = _batches(1)[0][0]
    collective = zoo.build_collective_model(
        embedding_dim=8, fc_unit=8, vocab_size=VOCAB
    )
    host = zoo.build_host_model(
        embedding_dim=8, fc_unit=8, vocab_size=VOCAB
    )
    v_c = init_variables(collective, jax.random.PRNGKey(0), example)
    v_h = init_variables(host, jax.random.PRNGKey(0), example)
    assert jax.tree_util.tree_structure(
        v_c["params"]
    ) == jax.tree_util.tree_structure(v_h["params"])
    # dense forward over the collective model's params works
    out = host.apply({"params": v_c["params"]}, example, training=False)
    assert np.isfinite(np.asarray(out["logits"])).all()


def test_sharded_forward_assembles_eval_params_from_checkpoint(tmp_path):
    """ElasticAllReduceWorker._sharded_forward: full tables come from
    the newest complete checkpoint; output equals the dense twin run
    directly on that state."""
    from elasticdl_tpu.common.sharded_checkpoint import save_sharded
    from elasticdl_tpu.worker.elastic_allreduce_worker import (
        ElasticAllReduceWorker,
    )

    opt = optax.sgd(0.05)
    batches = _batches(2, seed=21)
    model = zoo.DeepFMEdl(
        embedding_dim=8, fc_unit=8, vocab_size=VOCAB, force_hbm=True
    )
    ts = _init_state(model, batches[0][0], opt)
    step = make_train_step(model, zoo.loss, opt)
    for features, labels in batches:
        ts, _ = step(ts, features, labels, jax.random.PRNGKey(0))
    ckpt_dir = str(tmp_path / "ckpt_v2")
    save_sharded(ckpt_dir, jax.tree_util.tree_map(np.asarray, ts), 2)

    class _Stub:
        _forward_fn = None
        _eval_params = None
        _eval_params_version = None

        class trainer:
            is_sharded = True

        def _host_model_factory(self):
            return zoo.build_host_model(
                embedding_dim=8, fc_unit=8, vocab_size=VOCAB
            )

        def _ckpt_dirs_newest_first(self):
            return [ckpt_dir]

    stub = _Stub()
    features = batches[0][0]
    out = ElasticAllReduceWorker._sharded_forward(stub, features)
    want = model.apply(
        {"params": ts.params}, features, training=False
    )
    np.testing.assert_allclose(
        np.asarray(out["logits"]),
        np.asarray(want["logits"]),
        rtol=1e-5,
        atol=1e-6,
    )
    # a second call reuses the cached assembly
    assert stub._eval_params_version == ckpt_dir


@pytest.mark.slow
def test_sharded_elastic_evaluation_interleave(tmp_path, monkeypatch):
    """TRAINING_WITH_EVALUATION on the sharded elastic plane: eval
    rounds trigger off worker-reported versions and score IN-PLANE
    (lockstep collective forwards at aligned sync points — since r5 no
    host twin or checkpoint is in the eval path; this config keeps
    checkpoints on to prove the cadence and eval compose)."""
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )
    from elasticdl_tpu.master.master import Master
    from tests.test_elastic_allreduce import _worker_env
    from tests.test_utils import (
        MODEL_ZOO_PATH,
        DatasetName,
        create_recordio_file,
    )

    monkeypatch.setenv("EDL_FORM_GRACE_SECS", "120")
    train_dir = tmp_path / "train"
    val_dir = tmp_path / "val"
    train_dir.mkdir()
    val_dir.mkdir()
    create_recordio_file(128, DatasetName.FRAPPE, 10, temp_dir=str(train_dir))
    create_recordio_file(32, DatasetName.FRAPPE, 10, temp_dir=str(val_dir))
    ckpt_dir = str(tmp_path / "ckpt")
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    model_params = "embedding_dim=8,fc_unit=8,vocab_size=96"
    args = parse_master_args(
        [
            "--job_name", "elastic-sharded-eval",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--num_minibatches_per_task", "1",
            "--num_epochs", "2",
            "--training_data", str(train_dir),
            "--validation_data", str(val_dir),
            "--evaluation_steps", "3",
            "--evaluation_start_delay_secs", "0",
            "--num_workers", "2",
            "--num_ps_pods", "0",
            "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
        ]
    )
    master = Master(args)
    master.prepare()
    assert master.evaluation_service is not None

    published = []
    orig_publish = master.evaluation_service._publish_summary

    def capture_publish(round_):
        published.append(
            (round_.model_version, round_.get_evaluation_summary())
        )
        return orig_publish(round_)

    master.evaluation_service._publish_summary = capture_publish

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id", str(worker_id),
            "--job_type", "training_with_evaluation",
            "--master_addr", "localhost:%d" % master.port,
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--distribution_strategy", "AllreduceStrategy",
            "--comm_host", "localhost",
            "--checkpoint_dir", ckpt_dir,
            "--checkpoint_steps", "2",
        ]

    manager = LocalInstanceManager(
        master.task_d,
        2,
        worker_command,
        env=_worker_env(),
        membership=master.membership,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()
    runner.join(timeout=300)
    assert not runner.is_alive(), "master did not finish"
    assert master.task_d.finished()
    manager.stop_relaunch_and_remove_all_pods()

    assert published, "no evaluation round completed"
    for version, metrics in published:
        assert version > 0
        assert metrics and "auc" in str(metrics), metrics


# -- in-memory replica plane (no-disk recovery) -----------------------------


def _flat_blocks(n_old, total=12):
    """1-axis equal-block helper: {path: fn(pid) -> (lo, hi)}."""
    rows = total // n_old
    return {("t",): lambda pid, r=rows: (pid * r, pid * r + r)}


def _plan(info, n_old, total=12, **kw):
    from elasticdl_tpu.parallel.elastic import plan_mirror_ranges

    return plan_mirror_ranges(
        info, _flat_blocks(n_old, total), {("t",): total}, **kw
    )


def test_plan_mirror_ranges_decisions():
    # all three old ranks alive in a 3-world: everyone serves their own
    plan = _plan([(1, 10, 3, 0), (1, 10, 3, 1), (1, 10, 3, 2)], 3)
    assert plan == (
        10, 3, {("t",): [(0, 4, 0, 0), (4, 8, 1, 0), (8, 12, 2, 0)]}
    )

    # rank owning block 1 died; rows 4:8 covered by the replica on the
    # rank whose old pid was 2 (its left neighbor was 1)
    plan = _plan([(1, 10, 3, 0), (0, 0, 0, 0), (1, 10, 3, 2)], 3)
    assert plan == (
        10, 3, {("t",): [(0, 4, 0, 0), (4, 8, 2, 1), (8, 12, 2, 0)]}
    )

    # adjacent double death: rows 4:8 unrecoverable
    assert _plan([(1, 10, 3, 0), (0, 0, 0, 0), (0, 0, 0, 0)], 3) is None

    # wraparound: old pid 2's rows live as pid 0's replica... no — the
    # replica of pid 0 IS pid 2's block ((0 - 1) % 3), so rows 8:12
    # come from rank 0's replica
    plan = _plan([(1, 10, 3, 0), (1, 10, 3, 1), (0, 0, 0, 0)], 3)
    assert plan == (
        10, 3, {("t",): [(0, 4, 0, 0), (4, 8, 1, 0), (8, 12, 0, 1)]}
    )

    # no mirrors at all (first establish)
    assert _plan([(0, 0, 0, 0)] * 3, 3) is None

    # stale vs checkpoint floor
    info = [(1, 10, 2, 0), (1, 10, 2, 1)]
    assert _plan(info, 2, floor=12, allow_stale=False) is None
    assert _plan(info, 2, floor=12, allow_stale=True) == (
        10, 2, {("t",): [(0, 6, 0, 0), (6, 12, 1, 0)]}
    )

    # a rank that missed the newest refresh is excluded from the plan —
    # but its rows are still covered through the fresh replica on its
    # right neighbor (new rank 2, old pid 0, holds pid 1's v10 copy...
    # here old pid 0's replica is pid 1's block)
    info = [(1, 10, 2, 0), (1, 8, 2, 1), (1, 10, 2, 0)]
    plan = _plan(info, 2)
    assert plan == (
        10, 2, {("t",): [(0, 6, 0, 0), (6, 12, 0, 1)]}
    )
    # duplicates keep the lowest rank
    info = [(1, 10, 2, 0), (1, 10, 2, 1), (1, 10, 2, 0)]
    assert _plan(info, 2) == (
        10, 2, {("t",): [(0, 6, 0, 0), (6, 12, 1, 0)]}
    )


def test_plan_mirror_ranges_pp_dp_replication():
    """On a data x pipe old world, stage shards repeat across data
    groups: losing a WHOLE pipe column (both members of one stage...
    both deaths in one data group) is still recoverable from the other
    data group's own shards — coverage the block-indexed planner could
    not express."""
    from elasticdl_tpu.parallel.elastic import (
        plan_mirror_ranges,
        process_dim0_block,
    )
    from jax.sharding import PartitionSpec as P

    old_axes = {"data": 2, "pipe": 2}  # procs 0,1 = data 0; 2,3 = data 1
    spec = P("pipe")
    blocks = {
        ("stages",): lambda pid: process_dim0_block(
            old_axes, spec, 4, 1, pid
        )
    }
    # pid -> stage block: 0->(0,2), 1->(2,4), 2->(0,2), 3->(2,4)
    assert blocks[("stages",)](0) == (0, 2)
    assert blocks[("stages",)](1) == (2, 4)
    assert blocks[("stages",)](2) == (0, 2)
    assert blocks[("stages",)](3) == (2, 4)

    # data group 0 (pids 0 and 1) died entirely; survivors are new
    # ranks holding old pids 2 and 3 — full coverage from their OWN
    # shards (the replicas aren't even needed)
    info = [(1, 7, 4, 2), (1, 7, 4, 3)]
    plan = plan_mirror_ranges(info, blocks, {("stages",): 4})
    assert plan == (
        7, 4, {("stages",): [(0, 2, 0, 0), (2, 4, 1, 0)]}
    )

    # vocab leaf sharded over BOTH axes alongside: blocks differ per pid
    vocab_spec = P(("data", "pipe"), None)
    vblocks = lambda pid: process_dim0_block(  # noqa: E731
        old_axes, vocab_spec, 8, 1, pid
    )
    assert [vblocks(p) for p in range(4)] == [
        (0, 2), (2, 4), (4, 6), (6, 8),
    ]
    both = {
        ("stages",): blocks[("stages",)],
        ("emb",): vblocks,
    }
    # same double death: stages recover, but vocab rows 0:4 lived only
    # in data group 0 (own) with replicas on pids 1 (of 0) and 2 (of 1)
    # -> pid 1's rows (2:4) survive via pid 2's replica; pid 0's rows
    # (0:2) had their replica on dead pid 1 -> unrecoverable
    plan = plan_mirror_ranges(
        info, both, {("stages",): 4, ("emb",): 8}
    )
    assert plan is None


def test_process_dim0_block_layouts():
    from elasticdl_tpu.parallel.elastic import process_dim0_block
    from jax.sharding import PartitionSpec as P

    # unsharded dim 0: every process holds everything
    assert process_dim0_block({"data": 4}, P(), 12, 1, 2) == (0, 12)
    # 1-axis equal blocks, multi-device processes
    assert process_dim0_block(
        {"data": 8}, P("data", None), 16, 2, 1
    ) == (4, 8)
    # trailing-axis sharding repeats across the leading axis
    assert process_dim0_block(
        {"data": 2, "pipe": 2}, P("pipe"), 6, 1, 3
    ) == (3, 6)
    # a 2-device process spanning both pipe stages holds the whole leaf
    assert process_dim0_block(
        {"data": 2, "pipe": 2}, P("pipe"), 6, 2, 1
    ) == (0, 6)


def test_mirror_refresh_and_assembly_round_trip():
    """Single-process world on the 8-device mesh: refresh captures the
    sharded plane, and assembly rebuilds the exact TrainState from the
    mirror alone (no checkpoint dir anywhere)."""
    from elasticdl_tpu.parallel.distributed import WorldSpec
    from elasticdl_tpu.parallel.elastic import ElasticDPTrainer

    def builder(mesh):
        model = zoo.DeepFMEdl(
            embedding_dim=8,
            fc_unit=8,
            vocab_size=VOCAB,
            collective=True,
            table_axis="data",
        )
        return model, zoo.param_shardings(mesh)

    trainer = ElasticDPTrainer(
        zoo.DeepFMEdl(embedding_dim=8, fc_unit=8, vocab_size=VOCAB),
        zoo.loss,
        optax.adam(0.01),
        distributed_builder=builder,
    )
    trainer.mirror_steps = 2

    spec = WorldSpec(
        coordinator="", num_processes=1, process_id=0, epoch=0
    )
    batches = _batches(3)
    # bypass ensure_world (no jax.distributed in-process)
    import elasticdl_tpu.parallel.distributed as dist_mod

    orig = dist_mod.ensure_world
    dist_mod.ensure_world = lambda s, **k: None
    try:
        trainer.establish(spec, example_batch=batches[0])
        for features, labels in batches:
            trainer.train_step(features, labels, 16, sync=True)
        trainer.refresh_mirror()
        assert trainer._mirror is not None
        v_mirror = trainer._mirror.version
        want = host_copy(trainer._ts)

        # clobber the live state; assembly must rebuild it from the
        # mirror with NO disk (restore_provider stays None)
        trainer._ts = None
        abstract = trainer._abstract_ts(batches[0])
        ok = trainer._try_assemble_from_mirrors(
            abstract, floor=0, allow_stale=False
        )
        assert ok, "mirror assembly failed"
        got = host_copy(trainer._ts)
        assert int(got.version) == v_mirror
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(want),
            jax.tree_util.tree_leaves_with_path(got),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=0, atol=0, err_msg=str(pa)
            )
    finally:
        dist_mod.ensure_world = orig


def test_mirror_round_trip_pp_dp_mesh():
    """Same round trip on a ("data", "pipe") mesh with the collective
    pipelined transformer: the range-based capture/assembly handles
    stage subtrees sharded over the trailing axis (replicated across
    data groups) — the generalization VERDICT r4 item 1 asked for."""
    from elasticdl_tpu.parallel.distributed import WorldSpec
    from elasticdl_tpu.parallel.elastic import ElasticDPTrainer
    from model_zoo.transformer_lm import transformer_lm as tzoo

    kw = dict(
        vocab_size=32,
        num_layers=2,
        num_heads=2,
        head_dim=8,
        embed_dim=16,
        mlp_dim=32,
        use_flash=False,
    )

    def builder(mesh):
        return (
            tzoo.build_collective_model(pipeline_stages=2, **kw),
            tzoo.param_shardings(mesh, pipeline_stages=2),
        )

    trainer = ElasticDPTrainer(
        tzoo.custom_model(**kw),
        tzoo.loss,
        optax.adam(0.01),
        distributed_builder=builder,
        mesh_axes_fn=lambda n: tzoo.mesh_axes(n, pipeline_stages=2),
    )
    trainer.mirror_steps = 2

    spec = WorldSpec(
        coordinator="", num_processes=1, process_id=0, epoch=0
    )
    rng = np.random.default_rng(3)
    batches = [
        (
            {"tokens": rng.integers(0, 32, (16, 8)).astype(np.int32)},
            rng.integers(0, 32, (16, 8)).astype(np.int32),
        )
        for _ in range(3)
    ]
    import elasticdl_tpu.parallel.distributed as dist_mod

    orig = dist_mod.ensure_world
    dist_mod.ensure_world = lambda s, **k: None
    try:
        trainer.establish(spec, example_batch=batches[0])
        assert trainer.mesh.axis_names == ("data", "pipe")
        for features, labels in batches:
            trainer.train_step(features, labels, 16, sync=True)
        trainer.refresh_mirror()
        assert trainer._mirror is not None
        assert any("stages" in p for p in trainer._mirror.own)
        v_mirror = trainer._mirror.version
        want = host_copy(trainer._ts)

        trainer._ts = None
        abstract = trainer._abstract_ts(batches[0])
        ok = trainer._try_assemble_from_mirrors(
            abstract, floor=0, allow_stale=False
        )
        assert ok, "pp x dp mirror assembly failed"
        got = host_copy(trainer._ts)
        assert int(got.version) == v_mirror
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(want),
            jax.tree_util.tree_leaves_with_path(got),
        ):
            np.testing.assert_allclose(
                np.asarray(a),
                np.asarray(b),
                rtol=0,
                atol=0,
                err_msg=str(pa),
            )
    finally:
        dist_mod.ensure_world = orig


@pytest.mark.slow
def test_sharded_kill_recovers_from_replica_no_disk(tmp_path, monkeypatch):
    """SIGKILL one of 3 workers on a sharded job with NO checkpoint dir:
    survivors reassemble the full state (tables + adam slots) from the
    in-HBM replica plane — bounded staleness, zero disk in the recovery
    path — and the job completes. Beats the reference's unbuilt
    embedding-replica design (docs/designs/parameter_server.md:109-131)."""
    import time

    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )
    from elasticdl_tpu.master.master import Master
    from tests.test_elastic_allreduce import _worker_env
    from tests.test_utils import (
        MODEL_ZOO_PATH,
        DatasetName,
        create_recordio_file,
    )

    monkeypatch.setenv("EDL_FORM_GRACE_SECS", "120")
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        192, DatasetName.FRAPPE, 10, temp_dir=str(data_dir)
    )
    log_dir = str(tmp_path / "logs")
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    model_params = "embedding_dim=8,fc_unit=8,vocab_size=96"
    args = parse_master_args(
        [
            "--job_name", "replica-kill",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--num_minibatches_per_task", "1",
            "--num_epochs", "6",
            "--training_data", str(data_dir),
            "--num_workers", "3",
            "--num_ps_pods", "0",
            "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
        ]
    )
    master = Master(args)
    master.prepare()

    completed = []
    orig_report = master.task_d.report

    def counting_report(task_id, success):
        if success:
            completed.append(task_id)
        return orig_report(task_id, success)

    master.task_d.report = counting_report

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id", str(worker_id),
            "--job_type", "training_only",
            "--master_addr", "localhost:%d" % master.port,
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--distribution_strategy", "AllreduceStrategy",
            "--comm_host", "localhost",
            # NO --checkpoint_dir: the replica plane is the only
            # recovery source
            "--replica_refresh_steps", "2",
        ]

    manager = LocalInstanceManager(
        master.task_d,
        3,
        worker_command,
        env=_worker_env(),
        membership=master.membership,
        max_relaunches=10,
        log_dir=log_dir,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()

    deadline = time.time() + 240
    while len(completed) < 1:
        assert time.time() < deadline, "job made no progress"
        assert runner.is_alive(), "master exited early"
        time.sleep(0.2)
    victims = manager.live_workers()
    assert victims, "no live workers to kill"
    manager.kill_worker(victims[-1])

    runner.join(timeout=420)
    assert not runner.is_alive(), "master did not finish after the kill"
    assert master.task_d.finished()
    assert len(set(completed)) == 72  # 192*6 / 16 records-per-task
    manager.stop_relaunch_and_remove_all_pods()

    import glob as _glob

    logs = ""
    for path in _glob.glob(os.path.join(log_dir, "worker-*.log")):
        with open(path, "rb") as f:
            logs += f.read().decode("utf-8", "replace")
    # recovery went through the replica plane, never disk, never re-init
    assert "reassembled from the replica plane" in logs, logs[-4000:]
    assert "RE-INITIALIZED" not in logs
    assert "restored at v" not in logs  # the checkpoint-restore log line


@pytest.mark.slow
def test_sharded_graceful_drain_reshards_no_disk(tmp_path, monkeypatch):
    """SIGTERM one of 3 workers on a sharded job with NO checkpoint dir:
    the world pauses at the consensus sync, every member (victim
    included) runs the pause-point replica refresh, and survivors
    reshard device-to-device — graceful scale-down without disk."""
    import time

    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )
    from elasticdl_tpu.master.master import Master
    from tests.test_elastic_allreduce import _worker_env
    from tests.test_utils import (
        MODEL_ZOO_PATH,
        DatasetName,
        create_recordio_file,
    )

    monkeypatch.setenv("EDL_FORM_GRACE_SECS", "120")
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        192, DatasetName.FRAPPE, 10, temp_dir=str(data_dir)
    )
    log_dir = str(tmp_path / "logs")
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    model_params = "embedding_dim=8,fc_unit=8,vocab_size=96"
    args = parse_master_args(
        [
            "--job_name", "replica-drain",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--num_minibatches_per_task", "1",
            "--num_epochs", "6",
            "--training_data", str(data_dir),
            "--num_workers", "3",
            "--num_ps_pods", "0",
            "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
        ]
    )
    master = Master(args)
    master.prepare()

    completed = []
    orig_report = master.task_d.report

    def counting_report(task_id, success):
        if success:
            completed.append(task_id)
        return orig_report(task_id, success)

    master.task_d.report = counting_report

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id", str(worker_id),
            "--job_type", "training_only",
            "--master_addr", "localhost:%d" % master.port,
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--distribution_strategy", "AllreduceStrategy",
            "--comm_host", "localhost",
            "--replica_refresh_steps", "2",
        ]

    manager = LocalInstanceManager(
        master.task_d,
        3,
        worker_command,
        env=_worker_env(),
        membership=master.membership,
        max_relaunches=10,
        log_dir=log_dir,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()

    deadline = time.time() + 240
    while len(completed) < 1:
        assert time.time() < deadline, "job made no progress"
        assert runner.is_alive(), "master exited early"
        time.sleep(0.2)
    victims = manager.live_workers()
    assert victims, "no live workers to drain"
    manager.terminate_worker(victims[-1])

    runner.join(timeout=420)
    assert not runner.is_alive(), "master did not finish after the drain"
    assert master.task_d.finished()
    assert len(set(completed)) == 72
    manager.stop_relaunch_and_remove_all_pods()

    import glob as _glob

    logs = ""
    for path in _glob.glob(os.path.join(log_dir, "worker-*.log")):
        with open(path, "rb") as f:
            logs += f.read().decode("utf-8", "replace")
    assert "reassembled from the replica plane" in logs, logs[-4000:]
    assert "RE-INITIALIZED" not in logs
    assert "restored at v" not in logs
    # the victim drained through the consensus pause, not a broken step
    assert "drain announced" in logs

@pytest.mark.slow
def test_pp_dp_kill_recovers_from_replica_no_disk(tmp_path, monkeypatch):
    """SIGKILL one of 4 workers mid-pp(2) x dp(2) transformer job with
    NO checkpoint dir: the world rounds down to 2 (one survivor parks
    as a spare and requeues its tasks), survivors reassemble the stage
    subtree + adam slots from the in-HBM replica plane (range-based
    assembly over the trailing pipe axis), the relaunch re-grows the
    world to 4, and the job completes — elasticity composing with
    pipeline parallelism, the reference's kill-anywhere premise
    (reference master/task_dispatcher.py:247-255) on a topology the
    reference never had."""
    import time

    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.data.example import encode_example
    from elasticdl_tpu.data.recordio import RecordIOWriter
    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )
    from elasticdl_tpu.master.master import Master
    from tests.test_elastic_allreduce import _worker_env
    from tests.test_utils import MODEL_ZOO_PATH

    monkeypatch.setenv("EDL_FORM_GRACE_SECS", "120")
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    rng = np.random.default_rng(0)
    with RecordIOWriter(str(data_dir / "tokens.edlr")) as f:
        for _ in range(192):
            f.write(
                encode_example(
                    {
                        "tokens": rng.integers(
                            0, 64, size=(64,), dtype=np.int64
                        )
                    }
                )
            )
    log_dir = str(tmp_path / "logs")
    model_def = "transformer_lm.transformer_lm.custom_model"
    model_params = (
        "pipeline_stages=2,vocab_size=64,num_layers=2,num_heads=2,"
        "head_dim=8,embed_dim=32,mlp_dim=64,use_flash=False"
    )
    args = parse_master_args(
        [
            "--job_name", "ppdp-replica-kill",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--num_minibatches_per_task", "1",
            "--num_epochs", "4",
            "--training_data", str(data_dir),
            "--num_workers", "4",
            "--num_ps_pods", "0",
            "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
        ]
    )
    master = Master(args)
    master.prepare()
    assert master.membership._world_multiple == 2

    completed = []
    orig_report = master.task_d.report

    def counting_report(task_id, success):
        if success:
            completed.append(task_id)
        return orig_report(task_id, success)

    master.task_d.report = counting_report

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id", str(worker_id),
            "--job_type", "training_only",
            "--master_addr", "localhost:%d" % master.port,
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--distribution_strategy", "AllreduceStrategy",
            "--comm_host", "localhost",
            # NO --checkpoint_dir: the replica plane is the only
            # recovery source
            "--replica_refresh_steps", "2",
        ]

    manager = LocalInstanceManager(
        master.task_d,
        4,
        worker_command,
        env=_worker_env(),
        membership=master.membership,
        max_relaunches=10,
        log_dir=log_dir,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()

    deadline = time.time() + 300
    while len(completed) < 2:
        assert time.time() < deadline, "job made no progress"
        assert runner.is_alive(), "master exited early"
        time.sleep(0.2)
    victims = manager.live_workers()
    assert victims, "no live workers to kill"
    manager.kill_worker(victims[-1])

    runner.join(timeout=420)
    assert not runner.is_alive(), "master did not finish after the kill"
    assert master.task_d.finished()
    assert len(set(completed)) == 48  # 192*4 / 16 records-per-task
    manager.stop_relaunch_and_remove_all_pods()

    import glob as _glob

    logs = ""
    for path in _glob.glob(os.path.join(log_dir, "worker-*.log")):
        with open(path, "rb") as f:
            logs += f.read().decode("utf-8", "replace")
    # recovery went through the replica plane, never disk, never re-init
    assert "reassembled from the replica plane" in logs, logs[-4000:]
    assert "RE-INITIALIZED" not in logs
    assert "restored at v" not in logs  # the checkpoint-restore log line

def test_mirror_rejects_non_leading_dim_shards_at_establish():
    """The replica plane's capture/assembly is leading-dim only: a zoo
    spec sharding a later dim (tensor-parallel style) with the mirror
    enabled must fail LOUDLY at establish — silently mis-capturing it
    would turn a no-disk recovery into a RE-INITIALIZE."""
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.parallel.distributed import WorldSpec
    from elasticdl_tpu.parallel.elastic import ElasticDPTrainer

    def builder(mesh):
        model = zoo.DeepFMEdl(
            embedding_dim=8,
            fc_unit=8,
            vocab_size=VOCAB,
            collective=True,
            table_axis="data",
        )
        # WRONG on purpose: shard the embedding dim, not the rows
        return model, {"embedding": {"table": P(None, "data")}}

    trainer = ElasticDPTrainer(
        zoo.DeepFMEdl(embedding_dim=8, fc_unit=8, vocab_size=VOCAB),
        zoo.loss,
        optax.sgd(0.05),
        distributed_builder=builder,
    )
    trainer.mirror_steps = 2
    import elasticdl_tpu.parallel.distributed as dist_mod

    orig = dist_mod.ensure_world
    dist_mod.ensure_world = lambda s, **k: None
    try:
        with pytest.raises(ValueError, match="leading-dim"):
            trainer.establish(
                WorldSpec(
                    coordinator="",
                    num_processes=1,
                    process_id=0,
                    epoch=0,
                ),
                example_batch=_batches(1)[0],
            )
    finally:
        dist_mod.ensure_world = orig

@pytest.mark.slow
def test_pp_dp_evaluation_interleave_no_twin_no_disk(tmp_path, monkeypatch):
    """TRAINING_WITH_EVALUATION on the pp x dp elastic plane with NO
    checkpoint dir and NO build_host_model: eval rounds score on the
    collective plane itself (lockstep in-plane forwards at aligned sync
    points) — the r4 host-twin requirement is gone, and the stage
    parameters never materialize in one host's RAM (the reference's
    evaluate-on-the-training-plane semantics,
    reference worker/worker.py:659-693)."""
    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.data.example import encode_example
    from elasticdl_tpu.data.recordio import RecordIOWriter
    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )
    from elasticdl_tpu.master.master import Master
    from tests.test_elastic_allreduce import _worker_env
    from tests.test_utils import MODEL_ZOO_PATH

    monkeypatch.setenv("EDL_FORM_GRACE_SECS", "120")
    train_dir = tmp_path / "train"
    val_dir = tmp_path / "val"
    train_dir.mkdir()
    val_dir.mkdir()
    rng = np.random.default_rng(0)
    for directory, n in ((train_dir, 128), (val_dir, 32)):
        with RecordIOWriter(str(directory / "tokens.edlr")) as f:
            for _ in range(n):
                f.write(
                    encode_example(
                        {
                            "tokens": rng.integers(
                                0, 64, size=(64,), dtype=np.int64
                            )
                        }
                    )
                )
    model_def = "transformer_lm.transformer_lm.custom_model"
    model_params = (
        "pipeline_stages=2,vocab_size=64,num_layers=2,num_heads=2,"
        "head_dim=8,embed_dim=32,mlp_dim=64,use_flash=False"
    )
    args = parse_master_args(
        [
            "--job_name", "ppdp-inplane-eval",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--num_minibatches_per_task", "1",
            "--num_epochs", "2",
            "--training_data", str(train_dir),
            "--validation_data", str(val_dir),
            "--evaluation_steps", "3",
            "--evaluation_start_delay_secs", "0",
            "--num_workers", "2",
            "--num_ps_pods", "0",
            "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
        ]
    )
    master = Master(args)
    master.prepare()
    assert master.evaluation_service is not None

    published = []
    orig_publish = master.evaluation_service._publish_summary

    def capture_publish(round_):
        published.append(
            (round_.model_version, round_.get_evaluation_summary())
        )
        return orig_publish(round_)

    master.evaluation_service._publish_summary = capture_publish

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id", str(worker_id),
            "--job_type", "training_with_evaluation",
            "--master_addr", "localhost:%d" % master.port,
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--distribution_strategy", "AllreduceStrategy",
            "--comm_host", "localhost",
            # NO --checkpoint_dir and the zoo has NO build_host_model:
            # the in-plane eval needs neither
        ]

    manager = LocalInstanceManager(
        master.task_d,
        2,
        worker_command,
        env=_worker_env(),
        membership=master.membership,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()
    runner.join(timeout=300)
    assert not runner.is_alive(), "master did not finish"
    assert master.task_d.finished()
    manager.stop_relaunch_and_remove_all_pods()

    assert published, "no evaluation round completed"
    for version, metrics in published:
        assert version > 0
        assert metrics and "token_accuracy" in str(metrics), metrics

def test_padded_table_step_matches_dense_training():
    """A PadDim0-marked table whose vocab does NOT divide the mesh (30
    rows on 8 devices -> padded to 32) must train EXACTLY like the
    dense model: the pad rows are never addressed, so losses and the
    logical table rows match bit-for-bit (within fp tolerance)."""
    vocab = 30
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    opt = optax.sgd(0.05)
    rng = np.random.default_rng(7)
    batches = []
    for _ in range(4):
        ids = rng.integers(0, vocab, size=(16, 10)).astype(np.int64)
        labels = rng.integers(0, 2, size=(16, 1)).astype(np.int64)
        batches.append(({"feature": ids}, labels))

    from elasticdl_tpu.parallel.distributed import WorldSpec
    from elasticdl_tpu.parallel.elastic import ElasticDPTrainer

    def builder(mesh_):
        model = zoo.DeepFMEdl(
            embedding_dim=8,
            fc_unit=8,
            vocab_size=vocab,
            collective=True,
            table_axis="data",
        )
        return model, zoo.param_shardings(mesh_)

    trainer = ElasticDPTrainer(
        zoo.DeepFMEdl(embedding_dim=8, fc_unit=8, vocab_size=vocab),
        zoo.loss,
        opt,
        distributed_builder=builder,
    )
    import elasticdl_tpu.parallel.distributed as dist_mod

    orig = dist_mod.ensure_world
    dist_mod.ensure_world = lambda s, **k: None
    try:
        trainer.establish(
            WorldSpec(
                coordinator="", num_processes=1, process_id=0, epoch=0
            ),
            example_batch=batches[0],
        )
        # the table placed PADDED: 30 -> 32 over 8 shards
        assert (
            trainer._ts.params["embedding"]["table"].shape[0] == 32
        )
        assert trainer._logical_dim0  # padding recorded
        losses = []
        for features, labels in batches:
            loss, n, _ = trainer.train_step(features, labels, 16)
            losses.append(loss)
            assert n == 8

        dense_model = zoo.DeepFMEdl(
            embedding_dim=8, fc_unit=8, vocab_size=vocab, force_hbm=True
        )
        ts_d = _init_state(dense_model, batches[0][0], opt)
        from elasticdl_tpu.training.step import make_train_step

        dense_step = make_train_step(dense_model, zoo.loss, opt)
        key = jax.random.PRNGKey(5)
        dense_losses = []
        for features, labels in batches:
            ts_d, loss_d = dense_step(ts_d, features, labels, key)
            dense_losses.append(float(loss_d))
        np.testing.assert_allclose(
            losses, dense_losses, rtol=2e-4, atol=1e-5
        )
        got = np.asarray(
            jax.device_get(trainer._ts.params["embedding"]["table"])
        )
        want = np.asarray(ts_d.params["embedding"]["table"])
        np.testing.assert_allclose(
            got[:vocab], want, rtol=2e-4, atol=1e-5
        )
        # the pad rows never moved
        np.testing.assert_array_equal(got[vocab:], 0.0)

        # mirror round trip in padded space: capture, clobber, rebuild
        trainer.mirror_steps = 2
        trainer.refresh_mirror()
        want_ts = host_copy(trainer._ts)
        trainer._ts = None
        ok = trainer._try_assemble_from_mirrors(
            trainer._abstract_ts(batches[0]), floor=0, allow_stale=False
        )
        assert ok, "padded mirror assembly failed"
        got_ts = host_copy(trainer._ts)
        for a, b in zip(
            jax.tree_util.tree_leaves(want_ts),
            jax.tree_util.tree_leaves(got_ts),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    finally:
        dist_mod.ensure_world = orig


def test_padded_checkpoint_restores_across_paddings(tmp_path):
    """A checkpoint written in one world's padded space restores into a
    DIFFERENT padded space: stored pad rows drop, missing tail rows
    zero-fill, logical rows round-trip exactly; host-side restores clip
    to the logical rows via the manifest."""
    from jax.sharding import NamedSharding
    from jax.sharding import PartitionSpec as P

    from elasticdl_tpu.common.sharded_checkpoint import (
        _snapshot_entries,
        load_sharded,
        load_sharded_to_host,
        write_snapshot,
    )
    from elasticdl_tpu.parallel.mesh import create_mesh

    vocab, dim = 30, 4
    rng = np.random.default_rng(1)
    logical = rng.standard_normal((vocab, dim)).astype(np.float32)

    # world A: 8 shards -> padded to 32
    mesh8 = create_mesh({"data": 8}, axis_names=("data",))
    padded_a = np.zeros((32, dim), np.float32)
    padded_a[:vocab] = logical
    arr_a = jax.device_put(
        padded_a, NamedSharding(mesh8, P("data", None))
    )
    d = str(tmp_path / "ckpt")
    write_snapshot(
        d,
        _snapshot_entries({"table": arr_a}),
        version=7,
        logical_dim0={"table": vocab},
    )

    # restore into world B's padding: 4 shards -> padded to 32... use a
    # different target: 6 shards -> padded to 36 (bigger than stored)
    mesh6 = create_mesh(
        {"data": 6},
        axis_names=("data",),
        devices=jax.devices()[:6],
    )
    version, tree = load_sharded(
        d,
        {"table": NamedSharding(mesh6, P("data", None))},
        target_shapes={"table": (36, dim)},
    )
    assert version == 7
    got = np.asarray(jax.device_get(tree["table"]))
    assert got.shape == (36, dim)
    np.testing.assert_array_equal(got[:vocab], logical)
    np.testing.assert_array_equal(got[vocab:], 0.0)

    # smaller target than stored: 2 shards -> padded to 30 == logical
    mesh2 = create_mesh(
        {"data": 2},
        axis_names=("data",),
        devices=jax.devices()[:2],
    )
    version, tree = load_sharded(
        d,
        {"table": NamedSharding(mesh2, P("data", None))},
        target_shapes={"table": (30, dim)},
    )
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(tree["table"])), logical
    )

    # host-side restore clips to logical automatically
    version, host = load_sharded_to_host(d)
    np.testing.assert_array_equal(host["table"], logical)

@pytest.mark.slow
def test_sharded_kill_prime_vocab_reshards_no_disk(tmp_path, monkeypatch):
    """VERDICT r4 item 6's bar: SIGKILL one of 3 workers on a sharded
    job whose vocab (97, prime) divides NEITHER the old nor the
    survivor world. PadDim0 placement pads per world (97 -> 99 on 3
    procs, 98 on 2), the range-based replica assembly bridges the two
    paddings through the logical rows, and the job completes with no
    disk restore and no re-init."""
    import time

    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )
    from elasticdl_tpu.master.master import Master
    from tests.test_elastic_allreduce import _worker_env
    from tests.test_utils import (
        MODEL_ZOO_PATH,
        DatasetName,
        create_recordio_file,
    )

    monkeypatch.setenv("EDL_FORM_GRACE_SECS", "120")
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        192, DatasetName.FRAPPE, 10, temp_dir=str(data_dir)
    )
    log_dir = str(tmp_path / "logs")
    model_def = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
    model_params = "embedding_dim=8,fc_unit=8,vocab_size=97"
    args = parse_master_args(
        [
            "--job_name", "prime-vocab-kill",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--num_minibatches_per_task", "1",
            "--num_epochs", "6",
            "--training_data", str(data_dir),
            "--num_workers", "3",
            "--num_ps_pods", "0",
            "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
        ]
    )
    master = Master(args)
    master.prepare()

    completed = []
    orig_report = master.task_d.report

    def counting_report(task_id, success):
        if success:
            completed.append(task_id)
        return orig_report(task_id, success)

    master.task_d.report = counting_report

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id", str(worker_id),
            "--job_type", "training_only",
            "--master_addr", "localhost:%d" % master.port,
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--distribution_strategy", "AllreduceStrategy",
            "--comm_host", "localhost",
            # NO --checkpoint_dir: the replica plane is the only
            # recovery source, across two different paddings
            "--replica_refresh_steps", "2",
        ]

    manager = LocalInstanceManager(
        master.task_d,
        3,
        worker_command,
        env=_worker_env(),
        membership=master.membership,
        max_relaunches=10,
        log_dir=log_dir,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()

    deadline = time.time() + 240
    while len(completed) < 1:
        assert time.time() < deadline, "job made no progress"
        assert runner.is_alive(), "master exited early"
        time.sleep(0.2)
    victims = manager.live_workers()
    assert victims, "no live workers to kill"
    manager.kill_worker(victims[-1])

    runner.join(timeout=420)
    assert not runner.is_alive(), "master did not finish after the kill"
    assert master.task_d.finished()
    assert len(set(completed)) == 72  # 192*6 / 16 records-per-task
    manager.stop_relaunch_and_remove_all_pods()

    logs = ""
    for path in glob.glob(os.path.join(log_dir, "worker-*.log")):
        with open(path, "rb") as f:
            logs += f.read().decode("utf-8", "replace")
    assert "reassembled from the replica plane" in logs, logs[-4000:]
    assert "RE-INITIALIZED" not in logs
    assert "restored at v" not in logs

@pytest.mark.slow
@pytest.mark.skipif(
    not os.environ.get("EDL_HEAVY_TESTS"),
    reason="6 concurrent jax processes (4 workers + standby + master) "
    "exceed the 2-vCPU CI box's reliable capacity — formation windows "
    "blow under load and the rung flakes; set EDL_HEAVY_TESTS=1 on a "
    "host with >=4 cores (it passes there: 104.7 s measured)",
)
def test_pp_dp_kill_promotes_standby(tmp_path, monkeypatch):
    """The standby plane composes with pipeline parallelism: a SIGKILL
    in a pp(2) x dp(2) job promotes the pre-warmed spare into the
    pipelined world (deferred death bump -> one N->N formation), and
    the job completes with replica-plane recovery."""
    import time

    from elasticdl_tpu.common.args import parse_master_args
    from elasticdl_tpu.data.example import encode_example
    from elasticdl_tpu.data.recordio import RecordIOWriter
    from elasticdl_tpu.master.local_instance_manager import (
        LocalInstanceManager,
    )
    from elasticdl_tpu.master.master import Master
    from tests.test_elastic_allreduce import _worker_env
    from tests.test_utils import MODEL_ZOO_PATH

    monkeypatch.setenv("EDL_FORM_GRACE_SECS", "120")
    # the heaviest rung in the suite: 4 workers + 1 standby + master on
    # a 2-vCPU CI box — formation latency inflates past the default
    # 10 s init window under that contention, so widen the whole
    # init<confirm<fence chain (master and workers both read this env)
    monkeypatch.setenv("EDL_WORLD_INIT_TIMEOUT", "25")
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    rng = np.random.default_rng(0)
    with RecordIOWriter(str(data_dir / "tokens.edlr")) as f:
        for _ in range(192):
            f.write(
                encode_example(
                    {
                        "tokens": rng.integers(
                            0, 64, size=(64,), dtype=np.int64
                        )
                    }
                )
            )
    log_dir = str(tmp_path / "logs")
    model_def = "transformer_lm.transformer_lm.custom_model"
    model_params = (
        "pipeline_stages=2,vocab_size=64,num_layers=2,num_heads=2,"
        "head_dim=8,embed_dim=32,mlp_dim=64,use_flash=False"
    )
    args = parse_master_args(
        [
            "--job_name", "ppdp-standby-kill",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--num_minibatches_per_task", "1",
            "--num_epochs", "4",
            "--training_data", str(data_dir),
            "--num_workers", "4",
            "--num_ps_pods", "0",
            "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
        ]
    )
    master = Master(args)
    master.prepare()

    completed = []
    orig_report = master.task_d.report

    def counting_report(task_id, success):
        if success:
            completed.append(task_id)
        return orig_report(task_id, success)

    master.task_d.report = counting_report

    def worker_command(worker_id):
        return [
            sys.executable,
            "-m",
            "elasticdl_tpu.worker.main",
            "--worker_id", str(worker_id),
            "--job_type", "training_only",
            "--master_addr", "localhost:%d" % master.port,
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", model_def,
            "--model_params", model_params,
            "--minibatch_size", "16",
            "--distribution_strategy", "AllreduceStrategy",
            "--comm_host", "localhost",
            "--replica_refresh_steps", "2",
        ]

    env = _worker_env()
    env["EDL_WORLD_INIT_TIMEOUT"] = "25"  # see the master-side setenv
    manager = LocalInstanceManager(
        master.task_d,
        4,
        worker_command,
        env=env,
        membership=master.membership,
        max_relaunches=10,
        num_standby=1,
        log_dir=log_dir,
    )
    master.instance_manager = manager
    manager.start_workers()
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.5}, daemon=True
    )
    runner.start()

    deadline = time.time() + 300
    while len(completed) < 2:
        assert time.time() < deadline, "job made no progress"
        assert runner.is_alive(), "master exited early"
        time.sleep(0.2)
    victims = manager.live_workers()
    assert victims, "no live workers to kill"
    manager.kill_worker(victims[-1])

    runner.join(timeout=420)
    assert not runner.is_alive(), "master did not finish after the kill"
    assert master.task_d.finished()
    assert len(set(completed)) == 48
    manager.stop_relaunch_and_remove_all_pods()

    logs = standby_logs = ""
    for path in glob.glob(os.path.join(log_dir, "*.log")):
        with open(path, "rb") as f:
            text = f.read().decode("utf-8", "replace")
        logs += text
        if os.path.basename(path).startswith("standby-"):
            standby_logs += text
    assert "promoted to worker" in standby_logs, "standby never promoted"
    # recovery went through the replica plane (the promoted joiner logs
    # to standby-N.log, so scan everything): no disk, no re-init
    assert "reassembled from the replica plane" in logs, logs[-4000:]
    assert "RE-INITIALIZED" not in logs
    assert "restored at v" not in logs
