"""The serving plane (docs/serving.md): scorer fleet + delta sync +
streaming train->export->serve loop.

Coverage map (ISSUE 15):

- the delta feed: DeltaLog floor/prune semantics, the servicer's
  ``serving_status``/``pull_embedding_delta`` pair (in-process AND over
  real gRPC), and the staleness bound holding under live training
  churn — with the unrelated-table retention pin (a version advance on
  one table must not evict the other's hot rows),
- the scorer: end-to-end deepfm scoring read-through from in-process
  PS shards, cache-hit determinism, hot swap draining in-flight
  requests on the superseded version, the directory watcher's
  newest-complete-manifest discipline,
- the loop: the streaming task dispatcher rolling epochs until
  stopped, and the worker's version-cadence export writing complete
  retention-bounded artifacts,
- the fleet: shm vs gRPC scorer parity over a real ScorerServer, and
  a scorer surviving a real PS shard SIGKILL/relaunch with the
  shard-selective cache invalidation (the PR-10 reconnect protocol).
"""

import json
import os
import signal
import threading
import time

import numpy as np
import optax
import pytest

from elasticdl_tpu.common.export import export_model, export_provenance
from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.nn.comm_plane import HotRowCache
from elasticdl_tpu.ps.delta_log import DeltaLog
from elasticdl_tpu.ps.parameters import EmbeddingTableInfo, Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.serving.delta_sync import EmbeddingDeltaSync
from elasticdl_tpu.serving.scorer import (
    ModelDirectoryWatcher,
    Scorer,
    ScorerModel,
)
from elasticdl_tpu.serving.server import ScorerServer
from elasticdl_tpu.utils import profiling
from elasticdl_tpu.worker.ps_client import PSClient
from tests.test_utils import MODEL_ZOO_PATH

MODEL_DEF = "deepfm_edl_embedding.deepfm_edl_embedding.custom_model"
MODEL_PARAMS = "embedding_dim=8,fc_unit=8,vocab_size=100"


def _features(n=4, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "feature": rng.integers(1, 100, size=(n, 10)).astype(np.int64)
    }


def _deepfm_params(seed=0):
    import jax

    from elasticdl_tpu.common.model_utils import get_model_spec
    from elasticdl_tpu.nn.embedding import IDX_COLLECTION, ROWS_COLLECTION
    from elasticdl_tpu.nn.model_api import init_variables, split_variables

    spec = get_model_spec(
        model_zoo=MODEL_ZOO_PATH,
        model_def=MODEL_DEF,
        model_params=MODEL_PARAMS,
    )
    variables = init_variables(
        spec.model, jax.random.PRNGKey(seed), _features()
    )
    params, state = split_variables(variables)
    state.pop(ROWS_COLLECTION, None)
    state.pop(IDX_COLLECTION, None)
    return spec, params


def _export(export_root, params, version):
    path = os.path.join(export_root, "v%010d" % version)
    export_model(
        path,
        params,
        version,
        metadata=export_provenance(MODEL_ZOO_PATH, MODEL_DEF, MODEL_PARAMS),
    )
    return path


def _ps_shards(n=2, use_async=True):
    shards = []
    for _ in range(n):
        shards.append(
            PserverServicer(
                Parameters(), 1, optax.sgd(0.1), use_async=use_async
            )
        )
    return shards


INFOS = [
    EmbeddingTableInfo("embedding", 8, "uniform"),
    EmbeddingTableInfo("id_bias", 1, "uniform"),
]


def _client(shards, window=2, rows=4096):
    cache = HotRowCache(rows, window=window)
    client = PSClient(shards, cache=cache)
    client.push_model({}, INFOS, version=0)
    return client, cache


def _push_sparse(client, table, ids, dim, scale=0.1, seed=None):
    rng = np.random.default_rng(seed)
    grads = rng.normal(0, scale, size=(len(ids), dim)).astype(np.float32)
    client.push_gradient(
        {}, [Tensor(table, grads, indices=np.asarray(ids, np.int64))], 0
    )


# ---------------------------------------------------------------------------
# the delta feed
# ---------------------------------------------------------------------------


def test_delta_log_since_floor_and_prune():
    log = DeltaLog(base_version=0, keep_versions=3)
    log.note("t", [1, 2, 3], 1)
    log.note("t", [2, 4], 2)
    ids, covered, complete = log.since("t", 1)
    assert complete and covered == 2
    assert list(ids) == [2, 4]
    ids, covered, complete = log.since("t", 0)
    assert complete and sorted(ids) == [1, 2, 3, 4]
    # nothing moved: empty, complete, covered == since
    ids, covered, complete = log.since("t", 2)
    assert complete and covered == 2 and ids.size == 0
    log.note("t", [5], 3)
    log.note("t", [6], 4)  # prunes the v1 entry -> floor rises to 1
    ids, covered, complete = log.since("t", 0)
    assert not complete and covered == 4
    assert log.floors()["t"] == 1
    ids, covered, complete = log.since("t", 1)
    assert complete and sorted(ids) == [2, 4, 5, 6]
    # an unknown table is empty-complete at or above base
    ids, covered, complete = log.since("u", 0)
    assert complete and ids.size == 0
    assert log.table_versions() == {"t": 4}


def test_refresh_table_drops_changed_retags_unchanged_dropping_stale():
    cache = HotRowCache(64, window=2)
    for i in range(4):
        cache.put("t", i, 0, 10, np.full(2, i, np.float32))
    cache.put("t", 9, 0, 5, np.full(2, 9.0, np.float32))  # below since
    cache.put("u", 1, 0, 10, np.ones(2, np.float32))  # other table
    dropped, retagged = cache.refresh_table(
        "t", 0, 14, changed_ids=[1, 3], since=10
    )
    assert sorted(dropped) == [1, 3, 9]
    assert retagged == 2
    # retagged entries serve at version 14 (lag 0)
    assert cache.get("t", 0) is not None
    assert cache.get("t", 2) is not None
    assert cache.get("t", 1) is None
    # the refresh bumped the shard clock to 14; "u"'s entry (still
    # tagged 10, lag 4 > window) now ages out — exactly why EVERY
    # table needs its own refresh round, which the delta sync provides
    assert cache.get("u", 1) is None
    assert cache.max_live_lag() <= 2


def test_servicer_serving_status_and_delta_in_process():
    shards = _ps_shards(1)
    client, cache = _client(shards)
    status = client.serving_status(0)
    assert status["initialized"]
    assert set(status["tables"]) == {"embedding", "id_bias"}
    base = status["tables"]["embedding"]
    _push_sparse(client, "embedding", [3, 5, 7], 8)
    _push_sparse(client, "embedding", [5, 9], 8)
    status = client.serving_status(0)
    assert status["tables"]["embedding"] == base + 2
    # slot tables (created by the sparse applies) never advertise
    assert set(status["tables"]) == {"embedding", "id_bias"}
    ids, covered, complete = client.pull_embedding_delta(
        0, "embedding", base
    )
    assert complete and covered == base + 2
    assert sorted(ids) == [3, 5, 7, 9]
    # a pruned-past sync point comes back incomplete
    shards[0]._delta = DeltaLog(base_version=100, keep_versions=2)
    _, _, complete = client.pull_embedding_delta(0, "embedding", 0)
    assert not complete
    client.close()


def test_delta_sync_staleness_bound_and_unrelated_table_retention():
    """The freshness contract under live churn: while table A's rows
    are rewritten every version, (a) no serveable entry ever exceeds
    the staleness window, and (b) table B's hot rows — untouched by
    training — keep HITTING across many A-advances instead of aging
    out (the miss storm the delta feed exists to prevent)."""
    shards = _ps_shards(1)
    scorer_client, cache = _client(shards, window=2)
    trainer_client = PSClient(shards)  # cache-less trainer side
    sync = EmbeddingDeltaSync(scorer_client, cache, refresh_rows=True)

    # warm both tables into the scorer cache
    a_ids = np.arange(1, 9, dtype=np.int64)
    b_ids = np.arange(1, 9, dtype=np.int64)
    scorer_client.pull_embedding_vectors("embedding", a_ids)
    scorer_client.pull_embedding_vectors("id_bias", b_ids)
    sync.sync_once()

    hits_before = cache.hits
    for round_ in range(12):
        # churn: rewrite half of A's rows (versions advance)
        _push_sparse(
            trainer_client, "embedding", a_ids[round_ % 2 :: 2], 8
        )
        sync.sync_once()
        assert cache.max_live_lag() <= 2
        # B still hits without any wire pull
        rows = cache.get_rows("id_bias", b_ids)
        assert all(r is not None for r in rows), (
            "unrelated table's hot rows were evicted by A's version "
            "advances at round %d" % round_
        )
    assert cache.hits > hits_before
    # the refreshed A rows serve the POST-update values: pull through
    # the trainer (no cache) and through the cache and compare
    fresh = trainer_client.pull_embedding_vectors("embedding", a_ids)
    cached = scorer_client.pull_embedding_vectors("embedding", a_ids)
    np.testing.assert_array_equal(fresh, cached)
    scorer_client.close()
    trainer_client.close()


def test_invalidate_table_fallback_on_incomplete_delta():
    shards = _ps_shards(1)
    client, cache = _client(shards)
    sync = EmbeddingDeltaSync(client, cache, refresh_rows=False)
    client.pull_embedding_vectors("embedding", np.arange(1, 9))
    client.pull_embedding_vectors("id_bias", np.arange(1, 9))
    sync.sync_once()
    # replace the shard's log with one that cannot answer our sync
    # point; advance the table so the sync tries
    _push_sparse(client, "embedding", [1, 2], 8)
    shards[0]._delta = DeltaLog(base_version=50, keep_versions=2)
    shards[0]._delta.note("embedding", [1, 2], 51)
    sync.sync_once()
    assert sync.tables_invalidated >= 1
    # the other table survived the fallback
    assert all(
        r is not None
        for r in cache.get_rows("id_bias", np.arange(1, 9))
    )
    client.close()


# ---------------------------------------------------------------------------
# the scorer
# ---------------------------------------------------------------------------


def test_scorer_end_to_end_and_cache_determinism(tmp_path):
    _, params = _deepfm_params()
    export_root = str(tmp_path / "exports")
    os.makedirs(export_root)
    _export(export_root, params, 0)
    shards = _ps_shards(2)
    client, cache = _client(shards)
    scorer = Scorer(ps_client=client, staleness_versions=2)
    try:
        watcher = ModelDirectoryWatcher(export_root, scorer)
        assert watcher.poll_once() == 0
        feats = _features()
        out1, v1 = scorer.score(feats)
        assert v1 == 0 and out1["probs"].shape == (4, 1)
        hits_before = cache.hits
        # second score of the same batch: rows served from cache, and
        # the output must be BITWISE identical (cache path == wire path)
        out2, _ = scorer.score(feats)
        assert cache.hits > hits_before
        np.testing.assert_array_equal(
            np.asarray(out1["logits"]), np.asarray(out2["logits"])
        )
        assert scorer.inflight_versions() == {}
        status = scorer.status()
        assert status["model_version"] == 0
        assert status["staleness_versions"] <= status["staleness_window"]
    finally:
        scorer.close()
        client.close()


def test_hot_swap_drains_inflight_requests(tmp_path):
    """A request in flight across an install finishes on the version
    it acquired; new requests score the new version immediately; the
    superseded version leaves the ledger once drained."""
    _, params = _deepfm_params(seed=0)
    _, params2 = _deepfm_params(seed=1)
    export_root = str(tmp_path / "exports")
    os.makedirs(export_root)
    _export(export_root, params, 1)
    shards = _ps_shards(1)
    client, _cache = _client(shards)
    scorer = Scorer(ps_client=client, staleness_versions=2)
    try:
        assert ModelDirectoryWatcher(export_root, scorer).poll_once() == 1
        feats = _features()
        scorer.score(feats)  # prepare v1 + record the template

        v1_model = scorer.model()
        entered = threading.Event()
        proceed = threading.Event()
        real_predict = v1_model.predict

        def slow_predict(*a, **kw):
            entered.set()
            assert proceed.wait(10.0)
            return real_predict(*a, **kw)

        v1_model.predict = slow_predict
        result = {}

        def request():
            result["out"], result["version"] = scorer.score(feats)

        t = threading.Thread(target=request)
        t.start()
        assert entered.wait(10.0)
        # swap to v2 while the request is parked inside v1
        _export(export_root, params2, 2)
        assert ModelDirectoryWatcher(export_root, scorer).poll_once() == 2
        assert scorer.model_version == 2
        assert scorer.inflight_versions().get(1) == 1
        out_new, v_new = scorer.score(feats)
        assert v_new == 2
        proceed.set()
        t.join(10.0)
        assert result["version"] == 1
        assert scorer.wait_drained(1, timeout=10.0)
        assert 1 not in scorer.inflight_versions()
        # different params must actually score differently (the swap
        # was real, not a re-label)
        assert not np.allclose(
            np.asarray(result["out"]["logits"]),
            np.asarray(out_new["logits"]),
        )
    finally:
        scorer.close()
        client.close()


def test_model_watcher_newest_complete_manifest(tmp_path):
    export_root = str(tmp_path / "exports")
    os.makedirs(export_root)
    _, params = _deepfm_params()
    _export(export_root, params, 3)
    _export(export_root, params, 12)
    # an incomplete artifact (no manifest) must be invisible
    os.makedirs(os.path.join(export_root, "v9999999999"))
    # a foreign manifest-shaped file is skipped, not fatal
    bad = os.path.join(export_root, "junk")
    os.makedirs(bad)
    with open(os.path.join(bad, "MANIFEST.json"), "w") as f:
        f.write("not json")
    scorer = Scorer()
    try:
        watcher = ModelDirectoryWatcher(export_root, scorer)
        path, version = watcher.newest_manifest()
        assert version == 12 and path.endswith("v%010d" % 12)
        assert watcher.poll_once() == 12
        assert watcher.poll_once() is None  # nothing newer
    finally:
        scorer.close()


# ---------------------------------------------------------------------------
# the streaming loop
# ---------------------------------------------------------------------------


def test_streaming_dispatcher_rolls_epochs_until_stopped():
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    task_d = TaskDispatcher(
        {"f": (0, 4)}, {}, {}, 2, num_epochs=1, streaming=True
    )
    # one epoch is 2 tasks; pull far past it
    seen = []
    for _ in range(9):
        task_id, task = task_d.get(1)
        assert task is not None, "streaming source drained"
        seen.append(task_id)
        task_d.report(task_id, True)
    task_d.set_streaming(False)
    drained = 0
    while True:
        task_id, task = task_d.get(1)
        if task is None:
            break
        task_d.report(task_id, True)
        drained += 1
    assert drained <= 2  # at most the already-open epoch's remainder
    assert task_d.finished()


def test_worker_streaming_export_cadence_and_retention(tmp_path):
    """A real PS-mode worker over an in-process master exports on the
    version cadence into complete, retention-bounded artifacts."""
    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.master.checkpoint_service import CheckpointService
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.worker.worker import Worker
    from tests.in_process_master import InProcessMaster
    from tests.test_utils import DatasetName, create_recordio_file

    export_root = str(tmp_path / "exports")
    f = create_recordio_file(
        64, DatasetName.FRAPPE, 10, temp_dir=str(tmp_path)
    )
    task_d = TaskDispatcher({f: (0, 64)}, {}, {}, 16, 1)
    master = MasterServicer(
        1,
        8,
        None,
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=True,
    )
    shards = _ps_shards(2)
    client = PSClient(shards)
    worker = Worker(
        worker_id=1,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=8,
        model_zoo=MODEL_ZOO_PATH,
        model_def=MODEL_DEF,
        model_params=MODEL_PARAMS,
        ps_client=client,
        export_dir=export_root,
        export_every_versions=2,
        export_keep=2,
    )
    worker._stub = InProcessMaster(master)
    try:
        worker.run()
    finally:
        client.close()
    assert task_d.finished()
    exports = sorted(os.listdir(export_root))
    assert exports, "no streaming export written"
    assert len(exports) <= 2, "retention bound violated: %r" % exports
    versions = []
    for d in exports:
        with open(
            os.path.join(export_root, d, "MANIFEST.json")
        ) as fh:
            manifest = json.load(fh)
        versions.append(manifest["model_version"])
        assert manifest["metadata"]["model_def"] == MODEL_DEF
    assert versions == sorted(versions)
    # the newest artifact round-trips through the scorer loader
    model = ScorerModel(
        os.path.join(export_root, exports[-1]), model_zoo=MODEL_ZOO_PATH
    )
    assert model.version == versions[-1]


# ---------------------------------------------------------------------------
# the fleet
# ---------------------------------------------------------------------------


def test_scorer_server_shm_vs_grpc_parity(tmp_path):
    """The same request through the plain bytes path and through a
    negotiated shm ring scores identically, and scorer_status serves
    over the wire."""
    from elasticdl_tpu.rpc.core import Client
    from elasticdl_tpu.rpc.shm_transport import ShmChannel

    _, params = _deepfm_params()
    export_root = str(tmp_path / "exports")
    os.makedirs(export_root)
    _export(export_root, params, 0)
    shards = _ps_shards(1)
    ps_client, _cache = _client(shards)
    scorer = Scorer(ps_client=ps_client, staleness_versions=2)
    server = None
    plain = shm_client = None
    try:
        ModelDirectoryWatcher(export_root, scorer).poll_once()
        server = ScorerServer(scorer, port=0, telemetry_port=-1)
        feats = _features()
        plain = Client("localhost:%d" % server.port)
        reply_a = plain.call("score", **feats)
        assert "error" not in reply_a, reply_a.get("error")
        assert reply_a["model_version"] == 0
        shm_client = Client("localhost:%d" % server.port)
        channel = ShmChannel(shm_client, n_slots=2, slot_mb=2)
        reply_b = channel.call("score", **feats)
        assert "error" not in reply_b, reply_b.get("error")
        np.testing.assert_array_equal(
            np.asarray(reply_a["out:logits"]),
            np.asarray(reply_b["out:logits"]),
        )
        status = plain.call("scorer_status")
        assert status["model_version"] == 0
        channel.close()
    finally:
        if server is not None:
            server.stop()
        scorer.close()
        ps_client.close()
        for c in (plain, shm_client):
            if c is not None:
                c.close()


def test_scorer_survives_ps_sigkill_relaunch(tmp_path):
    """A real PS shard SIGKILLed and relaunched (snapshot restore):
    the scorer's poll path detects the new shard_epoch, invalidates
    that shard's cache entries (PR-10 reconnect protocol), and keeps
    serving within the staleness bound."""
    import subprocess
    import sys as _sys

    from tests.fake_ps import free_port
    from elasticdl_tpu.worker.ps_client import BoundPS

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    port = free_port()
    snap_dir = str(tmp_path / "snap")
    cmd = [
        _sys.executable,
        "-m",
        "elasticdl_tpu.ps.main",
        "--ps_id", "0",
        "--port", str(port),
        "--model_zoo", MODEL_ZOO_PATH,
        "--model_def", MODEL_DEF,
        "--use_async", "true",
        "--grads_to_wait", "1",
        "--ps_snapshot_versions", "1",
        "--ps_snapshot_dir", snap_dir,
    ]
    env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")

    def spawn():
        return subprocess.Popen(
            cmd,
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )

    def wait_port(proc, timeout=90):
        import socket

        deadline = time.time() + timeout
        while True:
            assert proc.poll() is None, "PS died at boot"
            try:
                with socket.create_connection(
                    ("localhost", port), 1.0
                ):
                    return
            except OSError:
                assert time.time() < deadline, "PS never served"
                time.sleep(0.2)

    proc = spawn()
    try:
        wait_port(proc)
        cache = HotRowCache(4096, window=2)
        client = PSClient(
            [BoundPS("localhost:%d" % port, deadline_s=5.0, retries=2)],
            cache=cache,
        )
        client.push_model({}, INFOS, version=0)
        _, params = _deepfm_params()
        export_root = str(tmp_path / "exports")
        os.makedirs(export_root)
        _export(export_root, params, 0)
        scorer = Scorer(ps_client=client, staleness_versions=2)
        sync = EmbeddingDeltaSync(client, cache, refresh_rows=True)
        try:
            ModelDirectoryWatcher(export_root, scorer).poll_once()
            feats = _features()
            out1, _ = scorer.score(feats)
            # advance versions so the relaunch has a snapshot to restore
            _push_sparse(client, "embedding", [3, 5, 7], 8)
            sync.sync_once()
            epoch_before = client.shard_epochs.get(0)
            rows_before = len(cache)
            assert rows_before > 0

            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=30)
            proc = spawn()
            wait_port(proc)
            # the poll path detects the new incarnation and runs the
            # shard-selective invalidation
            deadline = time.time() + 30
            while client.shard_epochs.get(0) == epoch_before:
                assert time.time() < deadline, (
                    "relaunch never detected via serving_status"
                )
                try:
                    sync.sync_once()
                except Exception:
                    pass
                time.sleep(0.3)
            assert client.shard_epochs.get(0, 0) > (epoch_before or 0)
            # scoring resumes against the restored incarnation within
            # the bound
            out2, _ = scorer.score(feats)
            assert np.all(np.isfinite(np.asarray(out2["logits"])))
            assert cache.max_live_lag() <= 2
            restores = [
                e
                for e in profiling.events.tail(200)
                if e.get("kind") == "ps_shard_restore"
            ]
            assert restores, "no ps_shard_restore event emitted"
        finally:
            scorer.close()
            sync.stop()
            client.close()
    finally:
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except Exception:
                proc.kill()
