"""Host-shard vs device-shard BITWISE parity (docs/ps_device.md).

The device-resident PS store (``Parameters(device=True)``) must be an
invisible swap: the same RPC sequence through a host shard and a device
shard yields bitwise-equal pulled params, embedding rows, slot tables,
versions, and delta-log contents. The mechanism is shared compiled
step functions (ps/optimizer_wrapper.py module docstring: XLA contracts
FMAs inside a jit, so eager-vs-jit is NOT bitwise — both planes
therefore run ONE executable and differ only in storage), plus
id-seeded lazy init (ps/embedding_table._make_initializer) so fresh
rows are a pure function of their ids.

Every assert here is ``array_equal``/``==`` — no tolerances. If one of
these starts failing by ~1 ulp, a storage path stopped sharing the
compiled step (or a host round-trip crept into the device plane; edlint
R10's device scope polices that statically).

The SIGKILL drill at the bottom runs the crash/restore protocol from
test_ps_fleet_recovery against live subprocess fleets in BOTH modes and
pins that restored state and post-restore training stay bitwise equal.
"""

import glob
import os
import signal
import time

import numpy as np
import optax
import pytest

from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.ps.parameters import EmbeddingTableInfo, Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from tests.fake_ps import free_port
from tests.test_ps_fleet_recovery import (
    _client,
    _spawn_ps,
    _stop,
    _wait_port,
)


def _make_pair(use_async=True, grads_to_wait=1, opt=None):
    """(host servicer, device servicer) with independent adam states."""
    pair = []
    for device in (False, True):
        params = Parameters(device=device)
        pair.append(
            PserverServicer(
                params,
                grads_to_wait,
                (opt or optax.adam)(1e-3),
                use_async=use_async,
            )
        )
    return pair


def _push_model(servicer, dense, dim=8, initializer="normal"):
    servicer.push_model(
        {
            "version": 0,
            "params": [Tensor(n, v.copy()) for n, v in dense.items()],
            "embedding_infos": [
                {"name": "emb", "dim": dim, "initializer": initializer}
            ],
        }
    )


def _training_stream(steps=6, dim=8, seed=3):
    """Deterministic dense+sparse gradient stream; odd steps carry
    duplicate ids (the segment-sum combine branch), even steps are
    duplicate-free (the reorder branch)."""
    rng = np.random.default_rng(seed)
    stream = []
    for step in range(steps):
        ids = rng.choice(
            50, size=12, replace=(step % 2 == 1)
        ).astype(np.int64)
        stream.append(
            {
                "w": rng.standard_normal((16, 8)).astype(np.float32),
                "b": rng.standard_normal((8,)).astype(np.float32),
                "ids": ids,
                "rows": rng.standard_normal((12, dim)).astype(np.float32),
            }
        )
    return stream


def _drive(servicer, stream):
    for step, g in enumerate(stream):
        resp = servicer.push_gradient(
            {
                "model_version": step,
                "gradients": [
                    Tensor("w", g["w"].copy()),
                    Tensor("b", g["b"].copy()),
                    Tensor(
                        "emb", g["rows"].copy(), indices=g["ids"].copy()
                    ),
                ],
            }
        )
        assert resp["accepted"]


def _pulled_state(servicer, ids):
    pull = servicer.pull_variable({})
    dense = {t.name: np.asarray(t.values) for t in pull["params"]}
    rows = np.asarray(
        servicer.pull_embedding_vector({"name": "emb", "ids": ids})["rows"]
    )
    delta = servicer.pull_embedding_delta(
        {"name": "emb", "since_version": -1}
    )
    serving = servicer.serving_status({})
    return pull["version"], dense, rows, delta, serving


def _assert_bitwise_state(host, device):
    hv, hd, hr, hdelta, hserv = host
    dv, dd, dr, ddelta, dserv = device
    assert hv == dv
    assert hd.keys() == dd.keys()
    for name in hd:
        assert np.array_equal(hd[name], dd[name]), name
    assert np.array_equal(hr, dr)
    assert np.array_equal(
        np.asarray(hdelta["ids"]), np.asarray(ddelta["ids"])
    )
    assert hdelta["version"] == ddelta["version"]
    assert hdelta["complete"] == ddelta["complete"]
    assert hserv["tables"] == dserv["tables"]
    assert hserv["floors"] == dserv["floors"]
    assert hserv["version"] == dserv["version"]


def _assert_tables_bitwise(host_params, device_params):
    """Every table — embedding AND optimizer slots — row-for-row
    bitwise, including insertion order of the materialized ids."""
    assert (
        host_params.embedding_params.keys()
        == device_params.embedding_params.keys()
    )
    for name, host_table in host_params.embedding_params.items():
        h_ids, h_rows = host_table.snapshot()
        d_ids, d_rows = device_params.embedding_params[name].snapshot()
        assert np.array_equal(h_ids, d_ids), name
        assert np.array_equal(h_rows, d_rows), name


def test_async_rpc_parity_bitwise():
    host, device = _make_pair(use_async=True)
    dense0 = {
        "w": np.arange(128, dtype=np.float32).reshape(16, 8) / 7.0,
        "b": np.linspace(-1.0, 1.0, 8, dtype=np.float32),
    }
    stream = _training_stream()
    ids = np.arange(60, dtype=np.int64)  # includes never-pushed ids
    for servicer in (host, device):
        _push_model(servicer, dense0)
        _drive(servicer, stream)
    _assert_bitwise_state(
        _pulled_state(host, ids), _pulled_state(device, ids)
    )
    _assert_tables_bitwise(host._parameters, device._parameters)


def test_sync_mode_parity_bitwise():
    """grads_to_wait=2 averaging + the stale-drop branch behave the
    same on both planes."""
    host, device = _make_pair(use_async=False, grads_to_wait=2)
    dense0 = {"w": np.full((16, 8), 0.25, np.float32)}
    rng = np.random.default_rng(11)
    pushes = []
    for _ in range(4):
        pushes.append(
            (
                rng.standard_normal((16, 8)).astype(np.float32),
                rng.integers(0, 30, size=10).astype(np.int64),
                rng.standard_normal((10, 8)).astype(np.float32),
            )
        )
    for servicer in (host, device):
        servicer.push_model(
            {
                "version": 0,
                "params": [Tensor("w", dense0["w"].copy())],
                "embedding_infos": [{"name": "emb", "dim": 8}],
            }
        )
        for g_w, ids, rows in pushes:
            servicer.push_gradient(
                {
                    "model_version": servicer._parameters.version,
                    "gradients": [
                        Tensor("w", g_w.copy()),
                        Tensor("emb", rows.copy(), indices=ids.copy()),
                    ],
                }
            )
    ids = np.arange(30, dtype=np.int64)
    _assert_bitwise_state(
        _pulled_state(host, ids), _pulled_state(device, ids)
    )
    _assert_tables_bitwise(host._parameters, device._parameters)


def test_snapshot_drain_bitwise_and_cross_mode_restore():
    """The device->disk drain produces byte-identical snapshot state,
    and a snapshot is MODE-PORTABLE: host-captured state restored into
    a device store (and vice versa) serves bitwise-identically — a
    fleet can flip --ps_device across a relaunch without a reset."""
    host, device = _make_pair(use_async=True)
    dense0 = {
        "w": np.ones((16, 8), np.float32),
        "b": np.zeros((8,), np.float32),
    }
    stream = _training_stream(steps=4)
    for servicer in (host, device):
        _push_model(servicer, dense0)
        _drive(servicer, stream)

    h_state = host._parameters.snapshot_state()
    d_state = device._parameters.snapshot_state()
    assert h_state["version"] == d_state["version"]
    assert h_state["dense"].keys() == d_state["dense"].keys()
    for name in h_state["dense"]:
        assert np.array_equal(h_state["dense"][name], d_state["dense"][name])
    assert h_state["tables"].keys() == d_state["tables"].keys()
    for name in h_state["tables"]:
        for key in ("ids", "rows"):
            assert np.array_equal(
                h_state["tables"][name][key], d_state["tables"][name][key]
            ), (name, key)

    # cross-mode restore: host capture -> device store, device capture
    # -> host store; both must serve what the originals serve
    crossed = []
    for state, into_device in ((h_state, True), (d_state, False)):
        params = Parameters(device=into_device)
        params.restore_state(state)
        crossed.append(
            PserverServicer(params, 1, optax.adam(1e-3), use_async=True)
        )
    ids = np.arange(60, dtype=np.int64)
    baseline = _pulled_state(host, ids)
    for servicer in crossed:
        version, dense, rows, _, _ = _pulled_state(servicer, ids)
        assert version == baseline[0]
        for name in baseline[1]:
            assert np.array_equal(dense[name], baseline[1][name])
        assert np.array_equal(rows, baseline[2])


def test_lazy_init_rows_bitwise_across_modes_any_order():
    """Fresh-row materialization is a pure function of the id on BOTH
    planes: pulling disjoint id sets in opposite orders still mints
    bitwise-equal rows (the id-seeded initializer contract)."""
    for initializer in ("normal", "uniform"):
        host = Parameters(device=False)
        device = Parameters(device=True)
        infos = [EmbeddingTableInfo("emb", 6, initializer)]
        host.init_from_model(0, {}, infos)
        device.init_from_model(0, {}, infos)
        first = np.asarray([9, 3, 27], dtype=np.int64)
        second = np.asarray([0, 27, 14], dtype=np.int64)
        host.get_embedding_param("emb", first)
        device.get_embedding_param("emb", second)  # opposite order
        everything = np.asarray([0, 3, 9, 14, 27], dtype=np.int64)
        assert np.array_equal(
            host.get_embedding_param("emb", everything),
            device.get_embedding_param("emb", everything),
        ), initializer


def _wait_snapshot(snap_dir, ps_id, version, timeout=60):
    """Poll until the cadence snapshot for ``version`` is PUBLISHED —
    the drill must not race the async writer, or the two fleets could
    roll back to different versions and the comparison means nothing."""
    want = os.path.join(snap_dir, "ps-%d" % ps_id, "snap_v%d" % version)
    deadline = time.time() + timeout
    while not glob.glob(want):
        assert time.time() < deadline, "snapshot v%d never published" % version
        time.sleep(0.2)


def _run_fleet_drill(tmp_path, mode, extra_mode_args):
    """One single-shard live fleet: train, wait for the snapshot,
    SIGKILL, relaunch, pull restored state, train more, pull again."""
    snap_dir = str(tmp_path / ("snaps-" + mode))
    extra = [
        "--ps_snapshot_versions", "1",
        "--ps_snapshot_dir", snap_dir,
    ] + list(extra_mode_args)
    port = free_port()
    proc = _spawn_ps(0, port, extra=extra, log_dir=str(tmp_path))
    try:
        _wait_port(proc, port)
        client = _client([port])
        try:
            client.push_model(
                {"w": np.full((3, 3), 1.5, np.float32)},
                [EmbeddingTableInfo("emb", 4)],
            )
            ids = np.arange(8, dtype=np.int64)
            client.pull_embedding_vectors("emb", ids)
            for i in range(3):
                client.push_gradient(
                    {"w": np.full((3, 3), 0.125, np.float32)},
                    [
                        Tensor(
                            "emb",
                            np.ones((8, 4), np.float32) * (i + 1),
                            indices=ids,
                        )
                    ],
                    i,
                )
            client.drain()
            ok, version, _ = client.pull_dense()
            assert ok
            _wait_snapshot(snap_dir, 0, version)

            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=10)
            proc = _spawn_ps(0, port, extra=extra, log_dir=str(tmp_path))
            _wait_port(proc, port)

            status = client._ps[0].ps_status({})
            assert status["initialized"] is True
            assert status["restored_version"] == version
            ok, got_version, dense_restored = client.pull_dense()
            assert ok and got_version == version
            rows_restored = client.pull_embedding_vectors("emb", ids)

            # training continues against the restored shard
            client.push_gradient(
                {"w": np.full((3, 3), -0.25, np.float32)},
                [
                    Tensor(
                        "emb",
                        np.full((8, 4), 0.5, np.float32),
                        indices=ids,
                    )
                ],
                got_version,
            )
            client.drain()
            ok, final_version, dense_final = client.pull_dense()
            assert ok and final_version == version + 1
            rows_final = client.pull_embedding_vectors("emb", ids)
            return (
                version,
                dense_restored,
                rows_restored,
                dense_final,
                rows_final,
            )
        finally:
            client.close()
    finally:
        _stop([proc])


def test_sigkill_snapshot_relaunch_drill_bitwise(tmp_path):
    """The full crash protocol — SIGKILL, snapshot restore, reconnect,
    continued training — leaves a device shard bitwise-identical to a
    host shard run through the same drill."""
    host = _run_fleet_drill(tmp_path, "host", [])
    device = _run_fleet_drill(
        tmp_path, "device", ["--ps_device", "true"]
    )
    assert host[0] == device[0]
    for h, d in zip(host[1:], device[1:]):
        if isinstance(h, dict):
            assert h.keys() == d.keys()
            for name in h:
                assert np.array_equal(h[name], d[name]), name
        else:
            assert np.array_equal(np.asarray(h), np.asarray(d))
