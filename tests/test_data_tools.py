"""Data conversion tool tests (recordio_gen + ODPS conversion utils)."""

import numpy as np

from elasticdl_tpu.data.example import decode_example
from elasticdl_tpu.data.odps_recordio_conversion_utils import (
    write_recordio_shards_from_iterator,
)
from elasticdl_tpu.data.recordio import RecordIOReader
from elasticdl_tpu.data.recordio_gen.frappe_recordio_gen import (
    convert as frappe_convert,
    parse_line,
)
from elasticdl_tpu.data.recordio_gen.image_label import convert


def test_image_label_sharding(tmp_path):
    rng = np.random.default_rng(0)
    data = [
        (rng.random((4, 4), dtype=np.float32), i % 10) for i in range(10)
    ]
    files = convert(iter(data), str(tmp_path), records_per_shard=4)
    assert len(files) == 3  # 4 + 4 + 2
    total = 0
    for f in files:
        with RecordIOReader(f) as r:
            for payload in r:
                ex = decode_example(payload)
                assert ex["image"].shape == (4, 4)
                assert ex["label"].shape == (1,)
                total += 1
    assert total == 10


def test_frappe_parse_and_convert(tmp_path):
    feats, label = parse_line("1 10:1:1 22:2:1 5:3:1", num_features=5)
    np.testing.assert_array_equal(feats, [10, 22, 5, 0, 0])
    assert label[0] == 1

    src = tmp_path / "frappe.txt"
    src.write_text("1 10:1:1 22:2:1\n0 3:1:1 4:2:1\n")
    files = frappe_convert(str(src), str(tmp_path / "out"), num_features=3)
    with RecordIOReader(files[0]) as r:
        assert len(r) == 2
        ex = decode_example(r.read(0))
        np.testing.assert_array_equal(ex["feature"], [10, 22, 0])


def test_odps_rows_to_shards(tmp_path):
    rows = [(1.5, 3, "setosa"), (2.5, 4, "virginica")]
    files = write_recordio_shards_from_iterator(
        iter(rows), ["sepal", "count", "class"], str(tmp_path)
    )
    with RecordIOReader(files[0]) as r:
        ex = decode_example(r.read(1))
        assert ex["sepal"][0] == np.float32(2.5)
        assert ex["count"][0] == 4
        assert bytes(ex["class"].tobytes()).decode() == "virginica"
