"""Tier-1 wiring for scripts/greps_guard.py — the source-pattern guard
over the two wedge classes VERDICT r5 root-caused (unescapable
jax.devices() probes; unbounded blocking queue puts)."""

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
GUARD = os.path.join(ROOT, "scripts", "greps_guard.py")


def test_repo_is_clean():
    proc = subprocess.run(
        [sys.executable, GUARD],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, (
        "wedge-pattern guard tripped:\n" + proc.stdout + proc.stderr
    )


def test_guard_detects_both_wedge_classes(tmp_path):
    pkg = tmp_path / "elasticdl_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import jax\n"
        "import queue\n"
        "def probe():\n"
        "    return jax.devices()\n"  # rule 1
        "def feed(q, item):\n"
        "    q.put(item)\n"  # rule 2
    )
    proc = subprocess.run(
        [sys.executable, GUARD, "--root", str(tmp_path)],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 1
    assert "jax.devices() outside escapable_call" in proc.stdout
    assert "queue put without timeout+cancel" in proc.stdout


def test_guard_accepts_safe_patterns(tmp_path):
    pkg = tmp_path / "elasticdl_tpu"
    pkg.mkdir()
    (pkg / "good.py").write_text(
        "from elasticdl_tpu.common.escapable import escapable_call\n"
        "import jax\n"
        "def probe():\n"
        "    return escapable_call(jax.devices, timeout=30)\n"
        "def feed(q, item, cancel):\n"
        "    while not cancel.is_set():\n"
        "        try:\n"
        "            q.put(item, timeout=0.5)\n"
        "            return True\n"
        "        except Exception:\n"
        "            continue\n"
        "    return False\n"
        "def cache_fill(cache, k, v):\n"
        "    cache.put(k, v)\n"  # not a queue: exempt by receiver name
    )
    proc = subprocess.run(
        [sys.executable, GUARD, "--root", str(tmp_path)],
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
