"""Historical tier-1 pin for the retired greps_guard regex rules.

The guard lived at ``scripts/greps_guard.py`` (regexes over the two r5
wedge classes), became a shim over edlint R1–R3 in PR 4, and the shim
itself is now deleted: this file invokes the analyzer directly with
``--rules R1,R2,R3`` and pins the same exit/report contract the
original guard established (0 clean, 1 with a per-violation report that
names both wedge classes), so the historical guarantee survives the
tooling underneath it being replaced twice.
"""

import os
import subprocess
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


def _guard(*extra):
    # every scan is whole-program now and writes an AST cache pickle
    # under $XDG_CACHE_HOME/edlint keyed by --root — point the child at
    # a throwaway dir so tmp_path roots don't accumulate dead pickles
    # in the user's real ~/.cache
    with tempfile.TemporaryDirectory(prefix="edlint-xdg-") as xdg:
        env = dict(os.environ, XDG_CACHE_HOME=xdg)
        return subprocess.run(
            [
                sys.executable,
                "-m",
                "elasticdl_tpu.tools.edlint",
                "--rules",
                "R1,R2,R3",
            ]
            + list(extra),
            capture_output=True,
            text=True,
            timeout=120,
            cwd=ROOT,
            env=env,
        )


def test_shim_is_gone():
    """The PR-4 shim retired for good: the entry point is edlint."""
    assert not os.path.exists(
        os.path.join(ROOT, "scripts", "greps_guard.py")
    )


def test_repo_is_clean():
    proc = _guard()
    assert proc.returncode == 0, (
        "wedge-pattern rules tripped:\n" + proc.stdout + proc.stderr
    )


def test_guard_detects_both_wedge_classes(tmp_path):
    pkg = tmp_path / "elasticdl_tpu"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import jax\n"
        "import queue\n"
        "def probe():\n"
        "    return jax.devices()\n"  # rule 1
        "def feed(q, item):\n"
        "    q.put(item)\n"  # rule 2
    )
    proc = _guard("--root", str(tmp_path))
    assert proc.returncode == 1
    assert "jax.devices() outside escapable_call" in proc.stdout
    assert "queue put without timeout+cancel" in proc.stdout


def test_guard_accepts_safe_patterns(tmp_path):
    pkg = tmp_path / "elasticdl_tpu"
    pkg.mkdir()
    (pkg / "good.py").write_text(
        "from elasticdl_tpu.common.escapable import escapable_call\n"
        "import jax\n"
        "def probe():\n"
        "    return escapable_call(jax.devices, timeout=30)\n"
        "def feed(q, item, cancel):\n"
        "    while not cancel.is_set():\n"
        "        try:\n"
        "            q.put(item, timeout=0.5)\n"
        "            return True\n"
        "        except Exception:\n"
        "            continue\n"
        "    return False\n"
        "def cache_fill(cache, k, v):\n"
        "    cache.put(k, v)\n"  # not a queue: exempt by receiver name
    )
    proc = _guard("--root", str(tmp_path))
    assert proc.returncode == 0, proc.stdout + proc.stderr
