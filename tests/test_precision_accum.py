"""Gradient accumulation and the mixed-precision policy (training/step.py,
training/precision.py).

The reference has neither: it trains f32 with whatever batch fits
(reference worker.py:545-568). These pin the TPU-side contracts: an
accumulated step equals the full-batch step, microbatch activation
bounding via scan, and the f32-master / bf16-compute / f32-loss split.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax import linen as nn

from elasticdl_tpu.nn.model_api import init_variables, split_variables
from elasticdl_tpu.training.precision import Policy, get_policy
from elasticdl_tpu.training.step import TrainState, make_train_step


class _MLP(nn.Module):
    @nn.compact
    def __call__(self, features, training=False):
        x = features["x"]
        x = nn.Dense(16)(x)
        x = nn.relu(x)
        return nn.Dense(4)(x)


def _mse(output, labels):
    return jnp.mean((output - labels) ** 2)


def _setup(seed=0, batch=16):
    model = _MLP()
    rng = np.random.default_rng(seed)
    features = {"x": rng.standard_normal((batch, 8)).astype(np.float32)}
    labels = rng.standard_normal((batch, 4)).astype(np.float32)
    variables = init_variables(model, jax.random.PRNGKey(0), features)
    params, state = split_variables(variables)
    opt = optax.sgd(0.05)
    ts = TrainState.create(params, state, opt)
    return model, features, labels, opt, ts


class TestGradAccumulation:
    def test_accumulated_step_equals_full_batch_step(self):
        model, features, labels, opt, ts = _setup()
        plain = make_train_step(model, _mse, opt)
        accum = make_train_step(model, _mse, opt, accum_steps=4)
        key = jax.random.PRNGKey(1)
        ts_a, loss_a = plain(ts, features, labels, key)
        *_, ts2 = _setup()
        ts_b, loss_b = accum(ts2, features, labels, key)
        # mean-of-microbatch-means == full-batch mean for equal micros
        np.testing.assert_allclose(
            float(loss_a), float(loss_b), rtol=1e-5
        )
        for pa, pb in zip(
            jax.tree_util.tree_leaves(ts_a.params),
            jax.tree_util.tree_leaves(ts_b.params),
        ):
            np.testing.assert_allclose(
                np.asarray(pa), np.asarray(pb), rtol=1e-5, atol=1e-6
            )
        assert int(ts_b.version) == 1

    def test_indivisible_batch_rejected(self):
        model, features, labels, opt, ts = _setup(batch=10)
        accum = make_train_step(model, _mse, opt, accum_steps=4)
        with pytest.raises(ValueError, match="not divisible"):
            accum(ts, features, labels, jax.random.PRNGKey(1))

    def test_state_threads_through_microbatches(self):
        """A batch-stat collection must see every microbatch once."""

        class Counting(nn.Module):
            @nn.compact
            def __call__(self, features, training=False):
                count = self.variable(
                    "batch_stats", "count", lambda: jnp.float32(0.0)
                )
                if training:
                    count.value = count.value + 1.0
                return nn.Dense(2)(features["x"])

        model = Counting()
        features = {"x": np.ones((8, 3), np.float32)}
        labels = np.zeros((8, 2), np.float32)
        variables = init_variables(model, jax.random.PRNGKey(0), features)
        params, state = split_variables(variables)
        opt = optax.sgd(0.01)
        ts = TrainState.create(params, state, opt)
        step = make_train_step(model, _mse, opt, accum_steps=4)
        ts, _ = step(ts, features, labels, jax.random.PRNGKey(1))
        assert float(ts.state["batch_stats"]["count"]) == 4.0


class TestPrecisionPolicy:
    def test_presets_and_unknown_name(self):
        pol = get_policy("mixed_bfloat16")
        assert pol.param_dtype == jnp.float32
        assert pol.compute_dtype == jnp.bfloat16
        assert get_policy(None) is None
        assert get_policy(pol) is pol
        with pytest.raises(ValueError, match="unknown precision"):
            get_policy("float8_dream")

    def test_cast_rules_skip_integers(self):
        pol = Policy()
        tree = {"w": jnp.ones((2, 2), jnp.float32), "ids": jnp.arange(3)}
        out = pol.cast_to_compute(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["ids"].dtype == tree["ids"].dtype

    def test_mixed_step_keeps_f32_masters_and_f32_loss(self):
        model, features, labels, opt, ts = _setup()
        step = make_train_step(
            model, _mse, opt, precision="mixed_bfloat16"
        )
        ts, loss = step(ts, features, labels, jax.random.PRNGKey(1))
        assert loss.dtype == jnp.float32
        assert np.isfinite(float(loss))
        for leaf in jax.tree_util.tree_leaves(ts.params):
            assert leaf.dtype == jnp.float32

    def test_mixed_step_tracks_f32_step_closely(self):
        model, features, labels, opt, ts = _setup()
        f32_step = make_train_step(model, _mse, opt)
        mixed_step = make_train_step(
            model, _mse, opt, precision="mixed_bfloat16"
        )
        key = jax.random.PRNGKey(1)
        ts_a, loss_a = f32_step(ts, features, labels, key)
        *_, ts2 = _setup()
        ts_b, loss_b = mixed_step(ts2, features, labels, key)
        # bf16 mantissa is 8 bits: expect ~1e-2 relative agreement
        np.testing.assert_allclose(
            float(loss_a), float(loss_b), rtol=5e-2
        )

    def test_accum_plus_precision_compose(self):
        model, features, labels, opt, ts = _setup()
        step = make_train_step(
            model, _mse, opt, accum_steps=2, precision="mixed_bfloat16"
        )
        ts, loss = step(ts, features, labels, jax.random.PRNGKey(1))
        assert np.isfinite(float(loss))
        assert int(ts.version) == 1
        for leaf in jax.tree_util.tree_leaves(ts.params):
            assert leaf.dtype == jnp.float32


def test_remat_step_matches_plain():
    """Full and policy-based rematerialization must be numerically
    identical to the plain step (same forward math, just recomputed in
    the backward), on both the plain and elastic step builders."""
    import flax.linen as nn
    import jax
    import optax

    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import (
        TrainState,
        make_train_step,
        parse_remat,
    )

    assert parse_remat("") is False
    assert parse_remat("full") is True
    assert parse_remat("dots_saveable") == "dots_saveable"

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, inputs, training=False):
            x = inputs["x"]
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(4)(x)

    def loss_fn(output, labels):
        return optax.softmax_cross_entropy_with_integer_labels(
            output, labels.reshape(-1)
        ).mean()

    model = MLP()
    rng = np.random.default_rng(0)
    features = {"x": rng.random((8, 16), dtype=np.float32)}
    labels = rng.integers(0, 4, size=(8, 1)).astype(np.int32)
    variables = init_variables(
        model, jax.random.PRNGKey(0), {"x": features["x"][:1]}
    )
    params, state = split_variables(variables)
    opt = optax.sgd(0.1)
    key = jax.random.PRNGKey(1)

    def run(remat):
        ts = TrainState.create(
            jax.tree_util.tree_map(np.array, params), state, opt
        )
        step = make_train_step(model, loss_fn, opt, remat=remat)
        losses = []
        for _ in range(3):
            ts, loss = step(ts, features, labels, key)
            losses.append(float(loss))
        return losses, jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, ts.params)
        )

    base_losses, base_params = run(False)
    for remat in (True, "dots_saveable"):
        losses, leaves = run(remat)
        np.testing.assert_allclose(losses, base_losses, rtol=1e-6)
        for a, b in zip(leaves, base_params):
            np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)

    with pytest.raises(ValueError, match="unknown remat policy"):
        make_train_step(model, loss_fn, opt, remat="not_a_policy")(
            TrainState.create(params, state, opt), features, labels, key
        )

    # elastic plane: remat step equals its own non-remat step
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from elasticdl_tpu.parallel.elastic import (
        broadcast_from_device0,
        host_copy,
        make_elastic_train_step,
    )

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("data",))

    def put(tree, spec):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(mesh, spec)), tree
        )

    g_feat = put(features, P("data"))
    g_lab = put(labels, P("data"))
    ones = put(np.ones(8, np.float32), P("data"))
    ep = put(np.zeros(8, np.int32), P("data"))
    outs = []
    for remat in (False, True):
        ts = broadcast_from_device0(
            mesh, host_copy(TrainState.create(params, state, opt))
        )
        estep = make_elastic_train_step(
            model, loss_fn, opt, mesh, remat=remat
        )
        with mesh:
            ts, loss, n, _ = estep(ts, g_feat, g_lab, ones, ep, key)
        outs.append((float(host_copy(loss)), host_copy(ts.params)))
    np.testing.assert_allclose(outs[1][0], outs[0][0], rtol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(outs[1][1]),
        jax.tree_util.tree_leaves(outs[0][1]),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
