"""Distributed-tracing plane tests (docs/observability.md).

Covers the Span API (nesting, thread-local context, the EDL_METRICS
kill switch, ring/pending bounds), cross-process span-context
propagation over real gRPC (the ``_sctx`` wire field + server-side
``rpc/*`` spans), trace-id survival across a task requeue AND a master
crash/relaunch (journal replay — pre- and post-failover spans link
into one trace), the worker-snapshot shipping path into the master's
``/trace`` endpoint, the ``/events?since=`` cursor, the Chrome
trace-event export, the tracetool critical-path breakdown, and the
crash flight recorder (trigger kinds, rate limit, prune, chaos-kill
wiring). Runs under EDL_LOCKTRACE=1 in scripts/check.sh.
"""

import json
import os
import urllib.request

import pytest

from elasticdl_tpu.common.constants import TaskExecCounterKey, TaskType
from elasticdl_tpu.master.journal import MasterJournal
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.master.telemetry import (
    JobTelemetry,
    ProcessTelemetry,
    TelemetryHTTPServer,
)
from elasticdl_tpu.tools.tracetool import critical_path
from elasticdl_tpu.utils import profiling
from elasticdl_tpu.utils.profiling import (
    NULL_SPAN,
    SpanLog,
    chrome_trace,
)
from elasticdl_tpu.worker.telemetry import WorkerTelemetry


@pytest.fixture(autouse=True)
def _clean_tracing_state():
    profiling.spans.reset()
    profiling.events.reset()
    profiling.flight_recorder.disarm()
    yield
    profiling.spans.reset()
    profiling.events.reset()
    profiling.flight_recorder.disarm()


# ---------------------------------------------------------------------------
# the Span API
# ---------------------------------------------------------------------------


def test_span_nesting_inherits_trace_and_parent():
    with profiling.span("step", trace_id="t1", examples=16) as outer:
        with profiling.span("step/compute") as inner:
            assert inner.trace_id == "t1"
            assert inner.parent_id == outer.span_id
            assert profiling.current_span() is inner
        assert profiling.current_span() is outer
    assert profiling.current_span() is None
    recs = {r["name"]: r for r in profiling.spans.tail()}
    assert recs["step"]["trace"] == "t1"
    assert recs["step"]["examples"] == 16
    assert recs["step/compute"]["parent"] == recs["step"]["span"]
    assert recs["step/compute"]["dur"] >= 0
    # span ids are process-scoped unique and carry the proc tag
    assert recs["step"]["span"].startswith(recs["step"]["proc"] + "/")


def test_span_records_error_kind_on_exception():
    with pytest.raises(ValueError):
        with profiling.span("step", trace_id="t1"):
            raise ValueError("boom")
    (rec,) = profiling.spans.tail()
    assert rec["error"] == "ValueError"


def test_kill_switch_returns_null_span_and_records_nothing():
    profiling.set_metrics_enabled(False)
    try:
        sp = profiling.span("step", trace_id="t1")
        assert sp is NULL_SPAN
        with sp:
            assert profiling.wire_span_context() is None
        assert profiling.spans.tail() == []
        # flight recorder honors the switch too
        assert profiling.flight_recorder.trigger("chaos_kill") is None
    finally:
        profiling.set_metrics_enabled(True)


def test_span_ring_and_pending_are_bounded_and_requeue_preserves_order():
    log = SpanLog(capacity=4, pending_capacity=3)
    for i in range(6):
        with log.begin("s%d" % i, trace_id="t"):
            pass
    assert [r["name"] for r in log.tail()] == ["s2", "s3", "s4", "s5"]
    drained = log.drain_pending()
    assert [r["name"] for r in drained] == ["s3", "s4", "s5"]
    log.requeue(drained[:2])
    assert [r["name"] for r in log.drain_pending()] == ["s3", "s4"]


def test_untraced_context_is_not_propagated():
    with profiling.span("host/maintenance"):
        # no trace id -> nothing rides the wire (servers would record
        # orphan spans for every untraced RPC otherwise)
        assert profiling.wire_span_context() is None
    assert profiling.span_from_wire({}, "rpc/x") is NULL_SPAN
    assert (
        profiling.span_from_wire({"_sctx": "bogus"}, "rpc/x")
        is NULL_SPAN
    )
    assert profiling.span_from_wire(None, "rpc/x") is NULL_SPAN


# ---------------------------------------------------------------------------
# cross-process propagation
# ---------------------------------------------------------------------------


def test_span_context_propagates_over_real_grpc():
    from elasticdl_tpu.rpc.core import Client, serve

    def handler(req):
        # a nested server-side span (the ps/apply shape) must parent on
        # the rpc span the instrumentation wrapper entered
        with profiling.span("ps/apply"):
            return {"ok": True}

    methods = profiling.instrument_service_methods(
        {"push_gradient": handler}, role="ps"
    )
    server = serve(methods, 0)
    client = Client("localhost:%d" % server._edl_port)
    try:
        with profiling.span("step", trace_id="t42") as caller:
            client.call(
                "push_gradient", _retriable=False, model_version=1
            )
    finally:
        client.close()
        server.stop(grace=None)
    recs = {r["name"]: r for r in profiling.spans.tail()}
    rpc = recs["rpc/push_gradient"]
    assert rpc["trace"] == "t42"
    assert rpc["parent"] == caller.span_id
    assert rpc["role"] == "ps"
    apply_rec = recs["ps/apply"]
    assert apply_rec["trace"] == "t42"
    assert apply_rec["parent"] == rpc["span"]


def test_untraced_rpc_records_no_server_span():
    from elasticdl_tpu.rpc.core import Client, serve

    methods = profiling.instrument_service_methods(
        {"ps_status": lambda req: {"ok": True}}, role="ps"
    )
    server = serve(methods, 0)
    client = Client("localhost:%d" % server._edl_port)
    try:
        client.call("ps_status")  # no open span -> no _sctx
    finally:
        client.close()
        server.stop(grace=None)
    assert [
        r for r in profiling.spans.tail() if r["name"].startswith("rpc/")
    ] == []


def test_pipelined_embedding_pull_span_carries_trace():
    from elasticdl_tpu.nn.comm_plane import EmbeddingPullPipeline

    pipe = EmbeddingPullPipeline()
    try:
        key = object()
        pipe.submit(key, {"plan": 1}, lambda: {"rows": 7}, trace_id="t9")
        plan, pulled = pipe.consume(key)
        assert pulled == {"rows": 7} and plan == {"plan": 1}
    finally:
        pipe.close()
    (rec,) = [
        r
        for r in profiling.spans.tail()
        if r["name"] == "step/embedding_pull_bg"
    ]
    assert rec["trace"] == "t9" and rec["pipelined"] is True


# ---------------------------------------------------------------------------
# trace ids survive requeue and master relaunch
# ---------------------------------------------------------------------------

SHARDS = {"data.edlr": (0, 24)}


def _dispatcher(journal=None):
    return TaskDispatcher(dict(SHARDS), {}, {}, 12, 1, journal=journal)


def _worker_step_span(task):
    trace = task.extended_config["trace_id"]
    with profiling.span("step", trace_id=trace):
        with profiling.span("step/compute"):
            pass
    return trace


def test_spans_link_across_a_task_requeue():
    d = _dispatcher()
    tid, task = d.get(worker_id=0)
    trace = _worker_step_span(task)  # worker A trains, then fails
    d.report(tid, False)
    tid2, task2 = d.get(worker_id=1)  # worker B picks the requeue up
    assert task2.extended_config["trace_id"] == trace
    assert task2.extended_config["_attempt"] == 1
    _worker_step_span(task2)
    linked = [
        r for r in profiling.spans.tail() if r.get("trace") == trace
    ]
    # both attempts' step+compute spans, plus the master's dispatch and
    # report spans, all join the one trace
    names = [r["name"] for r in linked]
    assert names.count("step") == 2 and names.count("step/compute") == 2
    assert "master/dispatch" in names and "master/report" in names


def test_spans_link_across_a_master_crash_and_relaunch(tmp_path):
    # one task total, so the relaunch's first dispatch IS the recovered
    # task (two tasks would leave the pick to the epoch shuffle)
    def _dispatcher(journal):
        return TaskDispatcher(
            {"data.edlr": (0, 12)}, {}, {}, 12, 1, journal=journal
        )

    journal = MasterJournal(str(tmp_path))
    state = journal.replay()
    d = _dispatcher(journal=journal)
    d.apply_recovery(state)
    journal.start()
    tid, task = d.get(worker_id=0)
    trace = _worker_step_span(task)
    journal.close()  # the crash: one task in flight

    journal2 = MasterJournal(str(tmp_path))
    state2 = journal2.replay()
    # snapshot NOW: the journal keeps folding post-boot records into
    # this same state object, so the done ack below will clear it
    pending_at_boot = set(state2.pending)
    d2 = _dispatcher(journal=journal2)
    d2.apply_recovery(state2)
    journal2.start()
    tid2, task2 = d2.get(worker_id=1)
    # the relaunched master re-dispatches the in-flight task with its
    # PRE-CRASH trace (attempt bumped), so post-failover spans join the
    # pre-failover ones
    assert task2.extended_config["trace_id"] == trace
    assert task2.extended_config["_attempt"] == 1
    _worker_step_span(task2)
    d2.report(
        tid2,
        True,
        exec_counters={
            TaskExecCounterKey.TRACE_ID: trace,
            TaskExecCounterKey.ATTEMPT: 1,
        },
    )
    journal2.close()
    linked = [
        r for r in profiling.spans.tail() if r.get("trace") == trace
    ]
    assert [r["name"] for r in linked].count("step") == 2
    # the master-plane report span resolved the same trace
    assert any(r["name"] == "master/report" for r in linked)
    # the crash left exactly this trace in flight at boot
    assert pending_at_boot == {trace}


# ---------------------------------------------------------------------------
# shipping: worker snapshot -> master /trace
# ---------------------------------------------------------------------------


class _Stub:
    def __init__(self, fail=False):
        self.fail = fail
        self.snaps = []

    def report_telemetry(self, snap):
        if self.fail:
            raise RuntimeError("master unreachable")
        self.snaps.append(snap)


def test_worker_snapshot_ships_spans_and_failed_ship_requeues():
    wt = WorkerTelemetry(3, interval_s=60.0)  # force=True below
    with profiling.span("step", trace_id="t7"):
        pass
    stub = _Stub()
    assert wt.ship(stub, force=True)
    (snap,) = stub.snaps
    assert [s["name"] for s in snap["spans"]] == ["step"]
    assert profiling.spans.drain_pending() == []

    with profiling.span("step", trace_id="t8"):
        pass
    assert not wt.ship(_Stub(fail=True), force=True)
    # the drained spans went back on the pending buffer
    requeued = profiling.spans.drain_pending()
    assert [s["trace"] for s in requeued] == ["t8"]


def test_job_telemetry_serves_worker_spans_on_trace_endpoint():
    jt = JobTelemetry()
    # spans "shipped from" a worker process (foreign proc tag — the
    # in-process dedup keeps same-proc spans out by design)
    jt.ingest(
        {
            "worker_id": 5,
            "spans": [
                {
                    "name": "step",
                    "trace": "t1",
                    "span": "worker-5/1",
                    "parent": None,
                    "proc": "worker-5",
                    "thread": "MainThread",
                    "ts": 1000.0,
                    "dur": 0.25,
                },
                {
                    "name": "step/compute",
                    "trace": "t1",
                    "span": "worker-5/2",
                    "parent": "worker-5/1",
                    "proc": "worker-5",
                    "thread": "MainThread",
                    "ts": 1000.1,
                    "dur": 0.2,
                },
            ],
        }
    )
    server = TelemetryHTTPServer(jt, port=0)
    try:
        doc = json.loads(
            urllib.request.urlopen(
                "http://127.0.0.1:%d/trace" % server.port, timeout=10
            ).read()
        )
        events = doc["traceEvents"]
        steps = [e for e in events if e.get("name") == "step"]
        assert steps and steps[0]["ph"] == "X"
        assert steps[0]["dur"] == 0.25e6  # microseconds
        procs = [
            e["args"]["name"]
            for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"
        ]
        assert "worker-5" in procs
        # ?trace_id= filters
        doc2 = json.loads(
            urllib.request.urlopen(
                "http://127.0.0.1:%d/trace?trace_id=absent" % server.port,
                timeout=10,
            ).read()
        )
        assert [
            e for e in doc2["traceEvents"] if e.get("ph") == "X"
        ] == []
    finally:
        server.close()


def test_resent_snapshot_spans_ingest_exactly_once():
    # report_telemetry is retriable: a snapshot resent through a
    # connection-reset window carries the SAME spans — ingest must be
    # idempotent by span id or /trace doubles every step
    shipped = [
        {
            "name": "step",
            "trace": "t1",
            "span": "worker-9/1",
            "parent": None,
            "proc": "worker-9",
            "thread": "MainThread",
            "ts": 1.0,
            "dur": 0.1,
        }
    ]
    profiling.spans.ingest(shipped)
    profiling.spans.ingest(shipped)  # the retry
    assert (
        len([r for r in profiling.spans.tail() if r["name"] == "step"])
        == 1
    )


def test_same_process_spans_are_not_duplicated_by_ingest():
    # the in-process local mode: worker and master share one SpanLog
    with profiling.span("step", trace_id="t1"):
        pass
    drained = profiling.spans.drain_pending()
    profiling.spans.ingest(drained)  # JobTelemetry would do this
    assert len(
        [r for r in profiling.spans.tail() if r["name"] == "step"]
    ) == 1


def test_events_endpoint_since_cursor():
    jt = JobTelemetry()
    first = profiling.events.emit("resize_begin", epoch=1)
    second = profiling.events.emit("resize_end", epoch=1)
    server = TelemetryHTTPServer(jt, port=0)
    try:
        url = "http://127.0.0.1:%d/events" % server.port
        all_events = [
            json.loads(l)
            for l in urllib.request.urlopen(url, timeout=10)
            .read()
            .decode()
            .splitlines()
            if l.strip()
        ]
        assert {e["id"] for e in all_events} >= {
            first["id"],
            second["id"],
        }
        newer = [
            json.loads(l)
            for l in urllib.request.urlopen(
                url + "?since=%d" % first["id"], timeout=10
            )
            .read()
            .decode()
            .splitlines()
            if l.strip()
        ]
        assert [e["id"] for e in newer] == [second["id"]]
        assert profiling.events.last_id() == second["id"]
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url + "?since=banana", timeout=10)
        assert err.value.code == 400
    finally:
        server.close()


def test_process_telemetry_serves_ps_shard_surface():
    # the --ps_telemetry_port adapter: /metrics + /healthz + /trace
    # parity with the master endpoint (docs/ps_recovery.md)
    health = {"state": "restoring"}
    pt = ProcessTelemetry()
    profiling.metrics.counter(
        "edl_tracing_test_total", "t"
    ).inc()
    with profiling.span("ps/apply", trace_id="t1"):
        pass
    server = TelemetryHTTPServer(
        pt, port=0, health_fn=lambda: health["state"]
    )
    try:
        base = "http://127.0.0.1:%d" % server.port
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(base + "/healthz", timeout=10)
        assert err.value.code == 503  # restoring -> not ready
        health["state"] = "serving"
        assert (
            urllib.request.urlopen(base + "/healthz", timeout=10).status
            == 200
        )
        body = (
            urllib.request.urlopen(base + "/metrics", timeout=10)
            .read()
            .decode()
        )
        assert "edl_tracing_test_total" in body
        doc = json.loads(
            urllib.request.urlopen(base + "/trace", timeout=10).read()
        )
        assert any(
            e.get("name") == "ps/apply" for e in doc["traceEvents"]
        )
    finally:
        server.close()


# ---------------------------------------------------------------------------
# chrome trace + tracetool
# ---------------------------------------------------------------------------


def _synthetic_steps(n=8, slow_at=6):
    out = []
    t = 0.0
    for i in range(n):
        pull, compute, push = 0.01, 0.03, (0.08 if i == slow_at else 0.01)
        dur = pull + compute + push + 0.002  # 2ms unattributed glue
        sid = "w/%d" % (10 * i)
        out.append(
            {
                "name": "step",
                "trace": "t%03d" % i,
                "span": sid,
                "parent": None,
                "proc": "worker-0",
                "thread": "MainThread",
                "ts": t,
                "dur": dur,
            }
        )
        for j, (nm, d) in enumerate(
            (
                ("step/pull_model", pull),
                ("step/compute", compute),
                ("step/grad_push", push),
            )
        ):
            out.append(
                {
                    "name": nm,
                    "trace": "t%03d" % i,
                    "span": "w/%d" % (10 * i + j + 1),
                    "parent": sid,
                    "proc": "worker-0",
                    "thread": "MainThread",
                    "ts": t,
                    "dur": d,
                }
            )
        t += dur
    return out


def test_tracetool_breakdown_attribution_and_dominant_phase():
    doc = chrome_trace(_synthetic_steps())
    report = critical_path(doc)
    assert report["steps"] == 8
    assert report["attribution"] >= 0.9
    shares = report["phases"]
    assert set(shares) == {
        "step/pull_model",
        "step/compute",
        "step/grad_push",
    }
    assert abs(sum(p["share"] for p in shares.values())
               - report["attribution"]) < 0.01
    # the p99 slow step is the grad_push outlier, flagged as dominant
    slow = report["slowest"][0]
    assert slow["trace"] == "t006"
    assert slow["dominant"] == "step/grad_push"
    # raw SpanLog records work too (the tests' convenience path)
    assert critical_path(_synthetic_steps())["steps"] == 8


def test_tracetool_cli_round_trip(tmp_path):
    from elasticdl_tpu.tools import tracetool

    path = tmp_path / "trace.json"
    path.write_text(json.dumps(chrome_trace(_synthetic_steps())))
    assert tracetool.main([str(path)]) == 0
    assert tracetool.main([str(path), "--json"]) == 0
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"traceEvents": []}))
    assert tracetool.main([str(empty)]) == 1
    assert tracetool.main([str(tmp_path / "missing.json")]) == 2


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def _read_postmortem(path):
    lines = [
        json.loads(l)
        for l in open(path, encoding="utf-8")
        if l.strip()
    ]
    return lines[0], lines[1:]


def test_flight_recorder_dumps_on_trigger_event(tmp_path):
    profiling.flight_recorder.arm(str(tmp_path), min_interval_s=0.0)
    with profiling.span("step", trace_id="t1"):
        pass
    profiling.events.emit("worker_join", worker=0)  # not a trigger
    assert os.listdir(str(tmp_path)) == []
    profiling.events.emit("ps_shard_failure", addr="x:1", method="pull")
    (dump,) = os.listdir(str(tmp_path))
    assert dump.startswith("postmortem-") and dump.endswith(
        "ps_shard_failure.jsonl"
    )
    header, body = _read_postmortem(os.path.join(str(tmp_path), dump))
    assert header["postmortem"] == "ps_shard_failure"
    assert header["trigger"]["addr"] == "x:1"
    kinds = {e["kind"] for e in body if e["type"] == "event"}
    assert {"worker_join", "ps_shard_failure"} <= kinds
    span_names = [s["name"] for s in body if s["type"] == "span"]
    assert "step" in span_names


def test_flight_recorder_rate_limit_and_prune(tmp_path):
    profiling.flight_recorder.arm(
        str(tmp_path), keep=2, min_interval_s=3600.0
    )
    assert profiling.flight_recorder.trigger("chaos_kill") is not None
    # inside the interval: suppressed (a requeue storm must not spam)
    assert profiling.flight_recorder.trigger("chaos_kill") is None
    profiling.flight_recorder.arm(
        str(tmp_path), keep=2, min_interval_s=0.0
    )
    for _ in range(4):
        assert profiling.flight_recorder.trigger("task_requeued")
    dumps = sorted(os.listdir(str(tmp_path)))
    assert len(dumps) == 2  # pruned to keep=2, newest kept
    assert dumps[-1].endswith("task_requeued.jsonl")


def test_disarmed_recorder_ignores_triggers(tmp_path):
    assert not profiling.flight_recorder.armed
    assert profiling.flight_recorder.trigger("chaos_kill") is None
    profiling.events.emit("ps_shard_failure", addr="x")  # no crash
    assert os.listdir(str(tmp_path)) == []


def test_chaos_kill_emits_event_and_triggers_recorder(tmp_path):
    from elasticdl_tpu.tools.chaos import ChaosOp, FleetChaos

    profiling.flight_recorder.arm(str(tmp_path), min_interval_s=0.0)

    class _Manager:
        killed = []

        def kill_ps(self, shard):
            self.killed.append(shard)

    chaos = FleetChaos(
        _Manager(),
        status_fn=lambda shard: {"version": 99},
        schedule=[ChaosOp("kill", 0, at_version=5)],
        poll_s=0.01,
    )
    chaos.start()
    try:
        deadline = 5.0
        import time as _time

        t0 = _time.monotonic()
        while not chaos.done() and _time.monotonic() - t0 < deadline:
            _time.sleep(0.02)
        assert chaos.done()
    finally:
        chaos.stop()
    assert _Manager.killed == [0]
    kinds = [e["kind"] for e in profiling.events.tail()]
    assert "chaos_kill" in kinds
    dumps = [
        f for f in os.listdir(str(tmp_path)) if "chaos_kill" in f
    ]
    assert dumps, "the chaos kill must leave a postmortem"
    header, body = _read_postmortem(
        os.path.join(str(tmp_path), dumps[0])
    )
    assert header["postmortem"] == "chaos_kill"
    assert all(
        isinstance(line, dict) for line in body
    )  # every line parses
