"""Standard export artifact: manifest, orbax params, serialized serving
function, fresh-process round trip (docs/export.md).

Parity: the reference exports a tf SavedModel any serving stack loads
(reference worker/worker.py:695-715, model_handler.py:108-141); here the
artifact is orbax + jax.export and the round trip is proven from a
subprocess that never imports the model zoo."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.common.export import (
    EXPORT_FORMAT,
    export_model,
    is_export_dir,
    load_export,
    make_serving_fn,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _small_model():
    import flax.linen as nn

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, training=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(8)(x)
            x = nn.relu(x)
            return nn.Dense(3)(x)

    return M()


def _export_small(tmp_path):
    model = _small_model()
    x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    variables = model.init(jax.random.PRNGKey(0), x)
    params = variables["params"]
    manifest = export_model(
        str(tmp_path / "exp"),
        params,
        version=42,
        metadata={"model_def": "tiny"},
        serving_fn=make_serving_fn(model, {}),
        example_features=x,
    )
    return model, params, x, manifest


def test_export_round_trip_same_process(tmp_path):
    model, params, x, manifest = _export_small(tmp_path)
    d = str(tmp_path / "exp")
    assert is_export_dir(d)
    assert manifest["format"] == EXPORT_FORMAT
    assert manifest["artifacts"]["serving_fn"], "serving plane missing"
    assert manifest["model_version"] == 42

    loaded = load_export(d)
    assert loaded.version == 42
    assert loaded.metadata["model_def"] == "tiny"
    np.testing.assert_array_equal(
        loaded.params["Dense_0"]["kernel"],
        np.asarray(params["Dense_0"]["kernel"]),
    )
    # serve through the serialized StableHLO — and at a DIFFERENT batch
    # size than the example batch (the export is batch-polymorphic)
    x2 = np.random.RandomState(1).randn(9, 5).astype(np.float32)
    got = np.asarray(loaded.serve(x2))
    want = np.asarray(
        model.apply({"params": params}, x2, training=False)
    )
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_export_legacy_chkpt_member_loads(tmp_path):
    """The artifact dir doubles as a --checkpoint_filename_for_init
    value: load_from_checkpoint_file resolves the directory."""
    from elasticdl_tpu.common.model_utils import (
        load_from_checkpoint_file,
    )

    _, params, _, _ = _export_small(tmp_path)
    version, named = load_from_checkpoint_file(str(tmp_path / "exp"))
    assert version == 42
    np.testing.assert_array_equal(
        named["Dense_0/kernel"], np.asarray(params["Dense_0"]["kernel"])
    )


def test_export_params_only_when_serving_fn_absent(tmp_path):
    params = {"w": jnp.ones((2, 2))}
    manifest = export_model(str(tmp_path / "p"), params, version=1)
    assert manifest["artifacts"]["serving_fn"] is None
    loaded = load_export(str(tmp_path / "p"))
    assert not loaded.has_serving_fn()
    with pytest.raises(RuntimeError, match="no serving function"):
        loaded.serve(np.zeros((1, 2), np.float32))


def test_newer_format_version_rejected(tmp_path):
    export_model(str(tmp_path / "v"), {"w": jnp.ones(2)}, version=1)
    mpath = tmp_path / "v" / "MANIFEST.json"
    m = json.loads(mpath.read_text())
    m["format_version"] = 99
    mpath.write_text(json.dumps(m))
    with pytest.raises(ValueError, match="newer than this loader"):
        load_export(str(tmp_path / "v"))


def test_fresh_process_source_free_serving(tmp_path):
    """The acceptance round trip: a NEW python process loads the
    artifact with generic loaders only (orbax + jax.export — not the
    framework, not the model source) and serves a batch that matches
    this process's direct forward."""
    model, params, x, _ = _export_small(tmp_path)
    x2 = np.random.RandomState(7).randn(6, 5).astype(np.float32)
    want = np.asarray(
        model.apply({"params": params}, x2, training=False)
    )
    np.save(tmp_path / "x2.npy", x2)

    code = """
import os, sys, json
import numpy as np
import jax
# env vars alone do not stick when a sitecustomize pre-pins the
# accelerator platform (same reasoning as tests/conftest.py) — without
# this the "cpu" subprocess silently serves on the TPU in bf16
jax.config.update("jax_platforms", "cpu")
import orbax.checkpoint as ocp
from jax import export as jexport

d = sys.argv[1]
with open(os.path.join(d, "MANIFEST.json")) as f:
    manifest = json.load(f)
params = ocp.StandardCheckpointer().restore(
    os.path.join(d, manifest["artifacts"]["params"]))
with open(os.path.join(d, manifest["artifacts"]["serving_fn"]), "rb") as f:
    fn = jexport.deserialize(f.read())
x2 = np.load(sys.argv[2])
out = np.asarray(fn.call(params, x2))
np.save(sys.argv[3], out)
print("SERVED", out.shape)
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            code,
            str(tmp_path / "exp"),
            str(tmp_path / "x2.npy"),
            str(tmp_path / "out.npy"),
        ],
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "SERVED (6, 3)" in proc.stdout
    got = np.load(tmp_path / "out.npy")
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
