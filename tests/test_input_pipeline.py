"""Pipelined input plane tests (docs/input_pipeline.md).

Pins the tentpole invariants of the pipelined worker input plane:

- ordered parallel decode (`Dataset.map(fn, num_parallel_calls=N)`) is
  element-for-element equivalent to the serial map, including where an
  exception surfaces and what happens when the consumer is abandoned;
- vectorized batch assembly is array-for-array equivalent to the legacy
  `_tree_stack` on nested dict/tuple pytrees and the partial final batch;
- task prefetch yields the identical record stream and ack sequence as
  the serial fetch loop;
- a spare-park `requeue_inflight` under active task prefetch returns
  every unconsumed task to the master EXACTLY once — no doing-set leak,
  no double report — whether the race lands mid-`get_task` or
  mid-consumption;
- queued task acks defer to the boundary drain (overflow drains inline,
  failure acks flush immediately, requeue drains before fail-reports).
"""

import threading
import time

import numpy as np
import pytest

from elasticdl_tpu.common.constants import TaskType
from elasticdl_tpu.data.data_reader import AbstractDataReader, Metadata
from elasticdl_tpu.data.dataset import Dataset, _tree_stack
from elasticdl_tpu.data.input_stats import InputPlaneStats
from elasticdl_tpu.master.servicer import TaskResponse
from elasticdl_tpu.worker.task_data_service import TaskDataService


# ---------------------------------------------------------------------------
# fixtures: a ledgered fake master + a deterministic reader
# ---------------------------------------------------------------------------


class StubMaster:
    """Duck-types the worker surface TaskDataService uses, with the
    master-side doing-set ledger the leak assertions check."""

    def __init__(self, n_tasks, records_per_task, get_task_hook=None):
        self._lock = threading.Lock()
        self._todo = [
            TaskResponse(
                shard_name="shard_%d" % i,
                start=0,
                end=records_per_task,
                type=TaskType.TRAINING,
                model_version=0,
            )
            for i in range(n_tasks)
        ]
        self._next_id = 0
        self.doing = {}
        self.reports = []  # (task_id, err_msg) in arrival order
        self.dispensed = []  # task_ids in dispatch order
        self._get_task_hook = get_task_hook

    def get_task(self, task_type=None):
        if self._get_task_hook:
            self._get_task_hook(self)
        with self._lock:
            if not self._todo:
                return TaskResponse()  # empty shard: stream ends
            task = self._todo.pop(0)
            self._next_id += 1
            task.task_id = self._next_id
            self.doing[self._next_id] = task
            self.dispensed.append(self._next_id)
            return task

    def report_task_result(self, task_id, err_msg="", exec_counters=None):
        with self._lock:
            self.doing.pop(task_id, None)
            self.reports.append((task_id, err_msg))


class ListReader(AbstractDataReader):
    """shard_i record j -> b"shard_i:j"; optional per-record latency."""

    def __init__(self, latency_s=0.0):
        self._latency_s = latency_s

    def read_records(self, task):
        for i in range(task.start, task.end):
            if self._latency_s:
                time.sleep(self._latency_s)
            yield ("%s:%d" % (task.shard_name, i)).encode()

    def create_shards(self):
        return {}

    @property
    def metadata(self):
        return Metadata()


def make_service(stub, reader=None, **kwargs):
    return TaskDataService(
        stub, False, data_reader=reader or ListReader(), **kwargs
    )


def settle(predicate, timeout=5.0):
    """Wait for a cross-thread condition with a hard deadline."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


# ---------------------------------------------------------------------------
# ordered parallel decode
# ---------------------------------------------------------------------------


def test_parallel_map_matches_serial_in_order():
    src = list(range(200))

    def fn(x):
        # jitter so pool completion order differs from submission order
        time.sleep((x % 5) * 1e-4)
        return x * 3

    serial = list(Dataset.from_tensors(src).map(fn))
    for n in (2, 4, 7):
        parallel = list(
            Dataset.from_tensors(src).map(fn, num_parallel_calls=n)
        )
        assert parallel == serial


def test_parallel_map_exception_surfaces_at_its_ordinal():
    def fn(x):
        if x == 7:
            raise RuntimeError("boom@7")
        time.sleep((x % 3) * 1e-4)
        return x * 2

    got = []
    with pytest.raises(RuntimeError, match="boom@7"):
        for v in Dataset.from_tensors(range(30)).map(
            fn, num_parallel_calls=4
        ):
            got.append(v)
    # elements before the failing one all arrived, in order, and
    # nothing past it leaked out
    assert got == [x * 2 for x in range(7)]


def test_parallel_map_cooperative_cancel_on_abandoned_consumer():
    pulled = []
    lock = threading.Lock()

    def src():
        i = 0
        while True:  # infinite source: only cancel can stop the pulls
            with lock:
                pulled.append(i)
            yield i
            i += 1

    it = iter(
        Dataset.from_generator(src).map(
            lambda x: x, num_parallel_calls=4
        )
    )
    assert [next(it) for _ in range(5)] == list(range(5))
    it.close()  # abandon the consumer (the spare-park shape)
    with lock:
        n_after_close = len(pulled)
    # the submission window bounds how far the source ran ahead
    assert n_after_close <= 5 + 2 * 4 + 1
    time.sleep(0.25)
    with lock:
        assert len(pulled) == n_after_close  # no pulls after the close


# ---------------------------------------------------------------------------
# vectorized batch assembly
# ---------------------------------------------------------------------------


def _assert_tree_equal(a, b):
    assert type(a) is type(b)
    if isinstance(a, dict):
        assert a.keys() == b.keys()
        for k in a:
            _assert_tree_equal(a[k], b[k])
    elif isinstance(a, (tuple, list)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            _assert_tree_equal(x, y)
    else:
        assert a.dtype == b.dtype
        assert a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def test_vectorized_batch_matches_tree_stack_on_nested_pytrees():
    elems = [
        (
            {
                "a": np.full((2, 3), i, np.float32),
                "b": (np.arange(4, dtype=np.int32) + i, np.int64(i)),
            },
            np.float64(i) / 7.0,
        )
        for i in range(10)
    ]
    # batch 4 over 10 elements: two full batches + a partial final batch
    fast = list(Dataset.from_tensors(elems).batch(4))
    ref = list(Dataset.from_tensors(elems).batch(4, vectorized=False))
    assert len(fast) == len(ref) == 3
    for f, r in zip(fast, ref):
        _assert_tree_equal(f, r)
    assert fast[-1][1].shape == (2,)  # the partial batch kept its size


def test_vectorized_batch_drop_remainder_and_scalars():
    elems = [{"x": i, "y": float(i)} for i in range(7)]
    fast = list(Dataset.from_tensors(elems).batch(3, drop_remainder=True))
    ref = list(
        Dataset.from_tensors(elems).batch(
            3, drop_remainder=True, vectorized=False
        )
    )
    assert len(fast) == len(ref) == 2
    for f, r in zip(fast, ref):
        _assert_tree_equal(f, r)


def test_vectorized_batch_falls_back_on_mixed_leaf_dtypes():
    # legacy np.stack PROMOTES int+float to float; raw buffer assignment
    # would silently truncate — the fast path must detect and fall back
    elems = [{"y": np.int64(3)}, {"y": np.float64(2.7)}]
    (fast,) = list(Dataset.from_tensors(elems).batch(2))
    (ref,) = list(
        Dataset.from_tensors(elems).batch(2, vectorized=False)
    )
    _assert_tree_equal(fast, ref)
    assert fast["y"].dtype == np.float64
    np.testing.assert_allclose(fast["y"], [3.0, 2.7])

    # first element narrower than a later one (shape mismatch): both
    # paths must agree (np.stack raises; the fast path defers to it)
    bad = [{"y": np.zeros(2)}, {"y": np.zeros(3)}]
    with pytest.raises(ValueError):
        list(Dataset.from_tensors(bad).batch(2))


def test_vectorized_batch_falls_back_for_bytes_leaves():
    elems = [b"a" * (i + 1) for i in range(5)]  # varying lengths
    fast = list(Dataset.from_tensors(elems).batch(2))
    ref = [_tree_stack(elems[0:2]), _tree_stack(elems[2:4]), _tree_stack(elems[4:5])]
    for f, r in zip(fast, ref):
        np.testing.assert_array_equal(f, r)


# ---------------------------------------------------------------------------
# shuffle satellite: reshuffle each iteration
# ---------------------------------------------------------------------------


def test_shuffle_reshuffles_each_iteration_deterministically():
    ds = Dataset.from_tensors(range(64)).shuffle(16, seed=11)
    first, second = list(ds), list(ds)
    assert sorted(first) == sorted(second) == list(range(64))
    assert first != second  # epoch 2 must not replay epoch 1's order

    replay = Dataset.from_tensors(range(64)).shuffle(
        16, seed=11, reshuffle_each_iteration=False
    )
    assert list(replay) == list(replay)

    # seeded determinism within one iteration: same seed, same epoch
    # index -> same order across dataset instances
    again = Dataset.from_tensors(range(64)).shuffle(16, seed=11)
    assert list(again) == first


# ---------------------------------------------------------------------------
# task prefetch
# ---------------------------------------------------------------------------


def _drain_stream(service):
    records = []
    ds = service.get_dataset()
    assert ds is not None
    for rec in ds:
        records.append(rec)
        service.report_record_done(1)
    service.drain_acks()
    return records


def test_task_prefetch_stream_equivalent_to_serial():
    serial_stub = StubMaster(5, 8)
    serial = _drain_stream(make_service(serial_stub, task_prefetch=0))

    for depth in (1, 3):
        stub = StubMaster(5, 8)
        pre = _drain_stream(
            make_service(stub, task_prefetch=depth)
        )
        assert pre == serial
        assert settle(lambda: not stub.doing)
        # identical ack sequence: every task acked once, in task order
        assert stub.reports == serial_stub.reports


def test_task_prefetch_with_queued_acks_equivalent():
    stub = StubMaster(4, 6)
    service = make_service(stub, task_prefetch=2, ack_queue_size=8)
    records = _drain_stream(service)
    assert len(records) == 4 * 6
    assert not stub.doing
    assert sorted(t for t, _ in stub.reports) == [1, 2, 3, 4]
    assert all(msg == "" for _, msg in stub.reports)


def test_task_prefetch_propagates_reader_errors_and_hands_task_back():
    class BoomReader(ListReader):
        def read_records(self, task):
            if task.shard_name == "shard_2":
                raise IOError("bad shard")
            yield from ListReader.read_records(self, task)

    stub = StubMaster(4, 4)
    service = make_service(
        stub, reader=BoomReader(), task_prefetch=2
    )
    with pytest.raises(IOError, match="bad shard"):
        _drain_stream(service)
    # the failed-read task was popped from the fetch queue but never
    # reached the ledger: it must still go back to the master (no
    # doing-set leak), along with everything the fetcher held
    assert settle(lambda: not stub.doing, timeout=5.0)
    reported = [t for t, _ in stub.reports]
    assert len(reported) == len(set(reported))
    assert set(stub.dispensed) == set(reported)


def test_requeue_under_active_prefetch_returns_every_task_once():
    """The tentpole race: a spare park while the fetcher holds prefetched
    tasks and the consumer is mid-task. Every dispensed task must end up
    acked or requeued EXACTLY once, with the master's doing-set empty."""
    stub = StubMaster(8, 10)
    service = make_service(
        stub, reader=ListReader(latency_s=0.002), task_prefetch=3
    )
    ds = service.get_dataset()
    it = iter(ds)
    consumed = 0
    for _ in range(15):  # 1.5 tasks in: ledger has in-flight work
        next(it)
        consumed += 1
        service.report_record_done(1)
    # give the fetcher time to stack prefetched-but-unconsumed tasks
    assert settle(lambda: len(stub.dispensed) >= 4)

    service.requeue_inflight("spare park")
    it.close()  # the park drops the round's stream

    # the fetcher hands back everything it held (its own thread may be
    # mid-get_task; that task comes back too)
    assert settle(lambda: not stub.doing, timeout=5.0)
    reported = [t for t, _ in stub.reports]
    assert len(reported) == len(set(reported)), (
        "task reported twice: %r" % stub.reports
    )
    # task 1 completed (10 records consumed): acked clean. Every other
    # dispensed task went back with the requeue/abandon message.
    acked = {t for t, msg in stub.reports if msg == ""}
    failed = {t for t, msg in stub.reports if msg != ""}
    assert acked == {1}
    assert failed == set(stub.dispensed) - {1}

    # the next round opens cleanly after the park
    assert service.get_dataset() is not None


def test_requeue_landing_mid_get_task_with_prefetch():
    """requeue_inflight racing the fetcher's in-flight get_task: the
    fetcher must hand its fresh task straight back, not append it."""
    service_box = {}
    fired = threading.Event()

    def hook(stub):
        # fire exactly once, from the FETCHER thread, after tasks began
        if len(stub.dispensed) == 2 and not fired.is_set():
            fired.set()
            service_box["svc"].requeue_inflight("spare park")

    stub = StubMaster(6, 4, get_task_hook=hook)
    service = make_service(stub, task_prefetch=1)
    service_box["svc"] = service
    ds = service.get_dataset()
    it = iter(ds)
    got = []
    try:
        for rec in it:
            got.append(rec)
            service.report_record_done(1)
    finally:
        it.close()
    assert settle(lambda: not stub.doing, timeout=5.0)
    reported = [t for t, _ in stub.reports]
    assert len(reported) == len(set(reported))
    assert set(stub.dispensed) == set(reported)


# ---------------------------------------------------------------------------
# async task acknowledgment
# ---------------------------------------------------------------------------


def test_queued_acks_defer_to_boundary_drain():
    stub = StubMaster(3, 4)
    service = make_service(stub, ack_queue_size=8)
    ds = service.get_dataset()
    records = list(ds)
    assert len(records) == 12
    service.report_record_done(8)  # completes tasks 1 and 2
    assert stub.reports == []  # queued, not sent: off the hot loop
    assert len(stub.doing) == 3
    service.drain_acks()
    assert stub.reports == [(1, ""), (2, "")]
    service.report_record_done(4)
    service.drain_acks()
    assert settle(lambda: not stub.doing)


def test_ack_queue_overflow_drains_inline():
    stub = StubMaster(5, 2)
    service = make_service(stub, ack_queue_size=2)
    ds = service.get_dataset()
    list(ds)
    service.report_record_done(6)  # 3 completed tasks > queue bound 2
    assert len(stub.reports) >= 3  # backpressure drained inline
    service.report_record_done(4)
    service.drain_acks()
    assert [t for t, _ in stub.reports] == [1, 2, 3, 4, 5]


def test_failure_ack_flushes_queue_and_reports_immediately():
    stub = StubMaster(3, 4)
    service = make_service(stub, ack_queue_size=8)
    ds = service.get_dataset()
    list(ds)
    service.report_record_done(4)  # task 1 clean -> queued
    assert stub.reports == []
    service.report_record_done(4, err_msg="step diverged")
    # ordered flush: task 1's clean ack lands BEFORE task 2's failure
    assert stub.reports[0] == (1, "")
    assert stub.reports[1][0] == 2 and stub.reports[1][1]
    service.report_record_done(4)
    service.drain_acks()
    assert settle(lambda: not stub.doing)


def test_requeue_drains_queued_acks_before_fail_reports():
    stub = StubMaster(3, 4)
    service = make_service(stub, ack_queue_size=8)
    ds = service.get_dataset()
    it = iter(ds)
    for _ in range(6):
        next(it)
    service.report_record_done(4)  # task 1 completed -> queued ack
    service.requeue_inflight("spare park")
    it.close()
    assert settle(lambda: not stub.doing)
    assert stub.reports[0] == (1, "")  # the queued clean ack went first
    failed = {t for t, msg in stub.reports if msg}
    assert 2 in failed and 1 not in failed


# ---------------------------------------------------------------------------
# input-plane observability
# ---------------------------------------------------------------------------


def test_input_stats_populate_across_stages():
    stub = StubMaster(3, 8)
    stats = InputPlaneStats()
    service = make_service(
        stub,
        reader=ListReader(latency_s=0.001),
        task_prefetch=1,
        stats=stats,
    )
    ds = service.get_dataset()
    ds = ds.map(
        lambda r: {"x": np.float32(len(r))}, num_parallel_calls=2
    ).batch(4).prefetch(1)
    batches = list(ds)
    service.drain_acks()
    snap = stats.snapshot()
    assert snap["tasks"] == 3
    assert snap["records"] == 24
    assert snap["batches"] == len(batches) == 6
    assert snap["read_s"] > 0
    assert snap["parse_s"] > 0
    assert snap["batch_s"] >= 0
    line = stats.format_line()
    assert "tasks=3" in line and "records=24" in line
    stats.reset()
    assert stats.snapshot()["records"] == 0


def test_stats_charge_ack_time():
    stub = StubMaster(2, 2)
    stats = InputPlaneStats()
    service = make_service(stub, ack_queue_size=4, stats=stats)
    ds = service.get_dataset()
    list(ds)
    service.report_record_done(4)
    service.drain_acks()
    assert stats.snapshot()["ack_s"] >= 0
    assert settle(lambda: not stub.doing)


# ---------------------------------------------------------------------------
# ODPS reader cache satellite
# ---------------------------------------------------------------------------


def test_odps_reader_cached_per_table_and_closed(monkeypatch):
    import elasticdl_tpu.data.odps_io as odps_io
    from elasticdl_tpu.data.data_reader import ODPSDataReader

    made = []

    class FakeODPSReader:
        def __init__(self, **kwargs):
            self.table = kwargs["table"]
            self.closed = False
            made.append(self)

        def table_schema_names(self):
            return ["c0"]

        def read_batch(self, start, end, columns=None):
            for i in range(start, end):
                yield (i,)

        def close(self):
            self.closed = True

    monkeypatch.setattr(odps_io, "ODPSReader", FakeODPSReader)
    reader = ODPSDataReader(
        project="p", access_id="i", access_key="k", table="t"
    )
    t1 = TaskResponse(
        shard_name="t:shard_0", start=0, end=3, type=TaskType.TRAINING
    )
    t2 = TaskResponse(
        shard_name="t:shard_1", start=3, end=6, type=TaskType.TRAINING
    )
    assert len(list(reader.read_records(t1))) == 3
    assert len(list(reader.read_records(t2))) == 3
    assert len(made) == 1  # one reader per table, not per task
    other = TaskResponse(
        shard_name="u:shard_0", start=0, end=2, type=TaskType.TRAINING
    )
    list(reader.read_records(other))
    assert len(made) == 2
    reader.close()
    assert all(r.closed for r in made)
    assert reader._readers == {}
