"""Overlapped PS data-plane tests: concurrent shard fan-out semantics,
the double-buffered async push window, and drain-on-boundary behavior
(docs/dense_overlap.md). Fault injection comes from tests/fake_ps.py."""

import time

import numpy as np
import pytest

from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.worker.ps_client import PSClient
from tests.fake_ps import FaultyPS, ShardKilledError, TablePS


def make_fleet(n, **faulty_kwargs):
    inners = [TablePS() for _ in range(n)]
    return inners, [FaultyPS(t, **faulty_kwargs) for t in inners]


# ---------------------------------------------------------------------------
# fan-out
# ---------------------------------------------------------------------------


def test_fanout_pull_matches_serial_and_overlaps():
    """Concurrent fan-out returns byte-identical results to the serial
    loop, while the per-shard legs actually overlap in time."""
    _, slow = make_fleet(4, delay_s=0.15)
    _, serial_stubs = make_fleet(4)
    ids = np.arange(32, dtype=np.int64)

    serial = PSClient(serial_stubs, fanout=False)
    overlapped = PSClient(slow, fanout=True)

    expect = serial.pull_embedding_vectors("emb", ids)
    t0 = time.monotonic()
    got = overlapped.pull_embedding_vectors("emb", ids)
    wall = time.monotonic() - t0
    np.testing.assert_array_equal(got, expect)
    # 4 shards x 0.15s serially would be >= 0.6s; overlapped tracks the
    # slowest single shard (generous 3x margin for thread scheduling)
    assert wall < 0.45, "fan-out did not overlap: %.3fs" % wall
    assert max(s.max_concurrency() for s in slow) >= 1
    assert any(s.max_concurrency() >= 2 for s in slow) or all(
        len(s.calls) == 1 for s in slow
    )


def test_fanout_wall_tracks_slowest_shard_not_sum():
    """One injected slow shard: wall time ~= the slow shard, not the
    sum over shards (the acceptance-criteria microbench shape)."""
    inners = [TablePS() for _ in range(4)]
    stubs = [
        FaultyPS(t, delay_s=(0.4 if i == 2 else 0.05))
        for i, t in enumerate(inners)
    ]
    client = PSClient(stubs, fanout=True)
    ids = np.arange(16, dtype=np.int64)
    t0 = time.monotonic()
    client.pull_embedding_vectors("emb", ids)
    wall = time.monotonic() - t0
    # serial would be 3*0.05 + 0.4 = 0.55s; fan-out ~0.4s
    assert wall < 0.55
    assert wall >= 0.4


def test_fanout_error_is_deterministic_lowest_shard():
    """When several shards fail in one fan-out, the LOWEST-numbered
    shard's exception surfaces, and only after every leg finished."""
    inners = [TablePS() for _ in range(3)]

    class Boom(RuntimeError):
        pass

    class BoomPS(FaultyPS):
        def _forward(self, method, req):
            raise Boom("shard-2 error")

    stubs = [
        FaultyPS(inners[0]),
        FaultyPS(inners[1], kill_after=0),  # shard 1: ShardKilledError
        BoomPS(inners[2]),  # shard 2: Boom
    ]
    client = PSClient(stubs, fanout=True)
    try:
        with pytest.raises(ShardKilledError):
            client.pull_embedding_vectors(
                "emb", np.arange(9, dtype=np.int64)
            )
        # shard 0's leg completed even though the call failed overall
        assert len(stubs[0].calls) == 1
    finally:
        # the captured exception pins this frame (and the client) via
        # its traceback, so pool GC can't collect the fan-out threads —
        # the locktrace leak guard rightly flags that without close()
        client.close()


def test_push_gradient_combines_all_shards_not_last():
    """accepted = all(shards), version = min(shards) — a rejection on a
    NON-final shard must not be masked by the last shard's accept."""
    inners = [TablePS(), TablePS(), TablePS()]
    inners[2].version = 50  # last shard reports the highest version
    stubs = [
        FaultyPS(inners[0], reject_pushes=True),  # first shard rejects
        FaultyPS(inners[1]),
        FaultyPS(inners[2]),
    ]
    client = PSClient(stubs, fanout=True)
    accepted, version = client.push_gradient(
        {"w": np.ones((2,), np.float32)},
        [Tensor("emb", np.ones((3, 2), np.float32), indices=[0, 1, 2])],
        version=0,
    )
    assert not accepted  # the reference's choose-last would say True
    assert version == 1  # min over (1, 1, 51), not the last shard's 51


def test_fanout_off_single_shard_paths_still_work():
    inners, stubs = make_fleet(1)
    client = PSClient(stubs, fanout=True)  # 1 shard -> serial path
    rows = client.pull_embedding_vectors("emb", np.array([3, 1, 3]))
    assert rows.shape == (3, 4)
    accepted, version = client.push_gradient({}, [], 0)
    assert accepted and version == 1


# ---------------------------------------------------------------------------
# double-buffered async push
# ---------------------------------------------------------------------------


def test_async_push_window_bounds_inflight():
    """push_inflight=1: the first push returns ~immediately, the second
    blocks until the first completes (bounded double buffering)."""
    inners = [TablePS()]
    stubs = [
        FaultyPS(inners[0], delay_s=0.3, delay_methods={"push_gradient"})
    ]
    client = PSClient(stubs, push_inflight=1)
    grads = {"w": np.ones((2,), np.float32)}

    t0 = time.monotonic()
    accepted, _ = client.push_gradient(grads, [], 0)
    first = time.monotonic() - t0
    assert accepted  # optimistic accept
    assert first < 0.15, "async push blocked: %.3fs" % first
    assert client.pending_push_count == 1

    t0 = time.monotonic()
    client.push_gradient(grads, [], 0)
    second = time.monotonic() - t0
    assert second >= 0.15, "window did not bound in-flight pushes"

    accepted, version = client.drain()
    assert accepted and version == 2
    assert client.pending_push_count == 0
    assert inners[0].pushes == 2


def test_pull_dense_drains_async_window():
    """The model a worker pulls reflects its own completed pushes: the
    pull waits for the in-flight push and sees the advanced version."""
    inners = [TablePS()]
    stubs = [
        FaultyPS(inners[0], delay_s=0.2, delay_methods={"push_gradient"})
    ]
    client = PSClient(stubs, push_inflight=2)
    client.push_gradient({"w": np.ones((1,), np.float32)}, [], 0)
    ok, version, _ = client.pull_dense()
    assert ok and version == 1
    assert client.pending_push_count == 0


def test_async_push_surfaces_shard_death_at_reap():
    """A shard that dies mid-push raises at the next window reap/drain
    rather than hanging or passing silently."""
    inners = [TablePS()]
    stubs = [FaultyPS(inners[0], kill_after=1)]
    client = PSClient(stubs, push_inflight=1)
    try:
        client.push_gradient({"w": np.ones((1,), np.float32)}, [], 0)
        client.drain()
        client.push_gradient({"w": np.ones((1,), np.float32)}, [], 1)
        with pytest.raises(ShardKilledError):
            client.drain()
        # a later drain is clean: the failed push left the window
        assert client.drain() == (True, 1)
    finally:
        client.close()  # see test_fanout_error: traceback pins the pool


def test_async_push_reports_late_rejection_on_drain():
    inners = [TablePS()]
    stubs = [FaultyPS(inners[0], reject_pushes=True)]
    client = PSClient(stubs, push_inflight=1)
    accepted, _ = client.push_gradient(
        {"w": np.ones((1,), np.float32)}, [], 0
    )
    assert accepted  # optimistic
    accepted, _ = client.drain()
    assert not accepted  # reconciled truth
    assert client.drain()[0]  # rejection consumed by the first drain


def test_async_push_equivalence_with_sync_fixed_seed():
    """Exact equivalence: the same gradient sequence pushed through the
    async window (drained at the end) and through synchronous pushes
    yields bit-identical dense params and embedding rows."""
    import optax

    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer

    def fleet():
        return [
            PserverServicer(
                Parameters(), 1, optax.sgd(0.1), use_async=True
            )
            for _ in range(2)
        ]

    rng = np.random.default_rng(1234)
    dense_names = ["a/w", "a/b", "b/w"]
    steps = [
        (
            {
                n: rng.normal(size=(3,)).astype(np.float32)
                for n in dense_names
            },
            [
                Tensor(
                    "emb",
                    rng.normal(size=(4, 2)).astype(np.float32),
                    indices=rng.integers(0, 8, size=4),
                )
            ],
        )
        for _ in range(6)
    ]

    def run(push_inflight):
        servicers = fleet()
        client = PSClient(
            servicers, fanout=True, push_inflight=push_inflight
        )
        client.push_model(
            {n: np.zeros((3,), np.float32) for n in dense_names},
            embedding_infos=[
                type(
                    "I",
                    (),
                    {"name": "emb", "dim": 2, "initializer": "zeros"},
                )
            ],
        )
        client.pull_embedding_vectors("emb", np.arange(8))
        for v, (dense, sparse) in enumerate(steps):
            accepted, _ = client.push_gradient(dense, sparse, v)
            assert accepted
        accepted, _ = client.drain()
        assert accepted
        ok, version, named = client.pull_dense()
        assert ok
        rows = client.pull_embedding_vectors("emb", np.arange(8))
        client.close()
        return version, named, rows

    v_sync, named_sync, rows_sync = run(push_inflight=0)
    v_async, named_async, rows_async = run(push_inflight=1)
    assert v_sync == v_async
    assert set(named_sync) == set(named_async)
    for name in named_sync:
        np.testing.assert_array_equal(named_sync[name], named_async[name])
    np.testing.assert_array_equal(rows_sync, rows_async)


# ---------------------------------------------------------------------------
# worker integration: drain on task boundary
# ---------------------------------------------------------------------------


def test_worker_e2e_async_push_drains_and_matches_sync(monkeypatch):
    """Full worker job with the async push window: completes, leaves no
    push in flight at the end, and — because every pull drains — the
    final sharded model state exactly matches the synchronous run."""
    import optax

    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.master.checkpoint_service import CheckpointService
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer
    from elasticdl_tpu.worker.worker import Worker
    from tests.in_process_master import InProcessMaster
    from tests.test_utils import (
        MODEL_ZOO_PATH,
        DatasetName,
        create_recordio_file,
    )

    model_def = "mnist_functional_api.mnist_functional_api.custom_model"
    f = create_recordio_file(64, DatasetName.IMAGE_DEFAULT, (28, 28))

    # the zoo dataset_fn buffer-shuffles with OS entropy and the
    # dispatcher shuffles tasks via the global random state; pin both
    # so the two arms train on byte-identical batch sequences and the
    # comparison isolates the push mode
    from elasticdl_tpu.data.dataset import Dataset

    monkeypatch.setattr(
        Dataset, "shuffle", lambda self, buffer_size, seed=None: self
    )

    def run(push_inflight):
        import random

        random.seed(42)
        servicers = [
            PserverServicer(
                Parameters(), 1, optax.sgd(0.01), use_async=True
            )
            for _ in range(2)
        ]
        client = PSClient(
            servicers, fanout=True, push_inflight=push_inflight
        )
        task_d = TaskDispatcher({f: (0, 64)}, {}, {}, 32, 1)
        master = MasterServicer(
            1,
            32,
            None,
            task_d,
            checkpoint_service=CheckpointService("", 0, 0, False),
            use_async=True,
        )
        worker = Worker(
            worker_id=1,
            job_type=JobType.TRAINING_ONLY,
            minibatch_size=32,
            model_zoo=MODEL_ZOO_PATH,
            model_def=model_def,
            ps_client=client,
            seed=7,
        )
        worker._stub = InProcessMaster(master)
        worker.run()
        assert task_d.finished()
        assert client.pending_push_count == 0
        state = {}
        for i, s in enumerate(servicers):
            for k, v in s._parameters.to_named_arrays().items():
                state["%d/%s" % (i, k)] = np.array(v)
        client.close()
        return state

    sync_state = run(push_inflight=0)
    async_state = run(push_inflight=1)
    assert set(sync_state) == set(async_state)
    for k in sync_state:
        np.testing.assert_array_equal(sync_state[k], async_state[k])


def test_boundary_drain_failure_does_not_kill_worker():
    """A PS failure surfacing at a task-boundary drain is logged and
    dropped (bounded staleness), never propagated — the worker process
    must survive and let the next minibatch's pull hit the failed-task
    path. (The minibatch-path drain, inside pull_dense, still raises.)
    """
    from elasticdl_tpu.worker.worker import Worker

    class FailingDrainClient:
        def drain(self):
            raise RuntimeError("injected: shard died mid-push")

    worker = Worker.__new__(Worker)  # no heavy init needed
    worker._ps_client = FailingDrainClient()
    worker._drain_ps_pushes()  # must not raise


def test_close_is_best_effort_after_failed_drain():
    inners = [TablePS()]
    stubs = [FaultyPS(inners[0], kill_after=0)]
    client = PSClient(stubs, push_inflight=1)
    client.push_gradient({"w": np.ones((1,), np.float32)}, [], 0)
    client.close()  # drain fails inside; close still releases pools
    assert client.pending_push_count == 0


def test_close_refuses_to_resurrect_pools():
    """A pull/push racing close() must not lazily recreate an executor
    nothing will ever shut down (close() detaches the handles under
    the pool lock and shuts the threads down OUTSIDE it, so a late
    caller would otherwise see None and mint a leaking pool)."""
    _, stubs = make_fleet(2)
    client = PSClient(stubs, fanout=True, push_inflight=1)
    client._get_fanout_pool()  # warm one pool pre-close
    client.close()
    with pytest.raises(RuntimeError, match="closed"):
        client._get_fanout_pool()
    with pytest.raises(RuntimeError, match="closed"):
        client.push_gradient({"w": np.ones((1,), np.float32)}, [], 0)
    assert client._fanout_pool is None and client._push_pool is None


def test_multi_table_pull_one_round_matches_per_table():
    """pull_embedding_vectors_multi returns per-table results identical
    to sequential per-table pulls, in ONE concurrent round (wall tracks
    one leg, not tables x shards legs)."""
    inners = [TablePS(), TablePS()]
    slow = [FaultyPS(t, delay_s=0.15) for t in inners]
    client = PSClient(slow, fanout=True)
    ref_client = PSClient([TablePS(), TablePS()], fanout=False)
    tables = {
        "emb_a": np.arange(12, dtype=np.int64),
        "emb_b": np.array([5, 3, 5, 8], dtype=np.int64),
        "emb_empty": np.array([], dtype=np.int64),
    }
    t0 = time.monotonic()
    got = client.pull_embedding_vectors_multi(tables)
    wall = time.monotonic() - t0
    for name, ids in tables.items():
        np.testing.assert_array_equal(
            got[name], ref_client.pull_embedding_vectors(name, ids)
        )
    # 2 tables x 2 shards x 0.15s serially = 0.6s; one round ~0.15s
    assert wall < 0.45, "multi-pull did not overlap: %.3fs" % wall
    client.close()
    ref_client.close()


def test_cache_probe_once_per_distinct_id():
    """Vectorized cache probe: a batch with duplicates costs one probe
    per DISTINCT id, every position is served, and the RPC-skip
    semantics stay pinned."""
    from tests.fake_ps import TablePS

    stubs = [TablePS(), TablePS()]
    client = PSClient(
        stubs, hot_row_cache_rows=64, staleness_window=1, fanout=True
    )
    ids = np.array([4, 1, 4, 1, 4, 2], dtype=np.int64)
    first = client.pull_embedding_vectors("emb", ids)
    assert stubs[0].pulls == 1 and stubs[1].pulls == 1
    cache = client.hot_row_cache
    hits0, misses0 = cache.hits, cache.misses
    again = client.pull_embedding_vectors("emb", ids)
    np.testing.assert_array_equal(again, first)
    # no new RPC, and exactly one probe per distinct id (3), all hits
    assert stubs[0].pulls == 1 and stubs[1].pulls == 1
    assert cache.hits - hits0 == 3
    assert cache.misses == misses0
