"""bf16 wire compression for the PS-mode hot path (rpc/wire_compression).

The reference ships the dense model pull and every gradient push as f32
protobufs with no compression (reference worker.py:748-825); the rebuild
halves those wire bytes opt-in via --wire_dtype=bfloat16. These pin the
protocol: receivers see f32 again, non-f32 payloads pass through, sparse
indices survive, and a flag mismatch degrades to no-compression.
"""

import numpy as np
import optax
import pytest

from elasticdl_tpu.common.tensor import (
    Tensor,
    deserialize_tensor,
    serialize_tensor,
)
from elasticdl_tpu.rpc.wire_compression import (
    compress_tensors,
    decompress_tensors,
)


def test_roundtrip_within_bf16_tolerance_and_names_listed():
    rng = np.random.default_rng(0)
    dense = Tensor("w", rng.standard_normal((8, 4)).astype(np.float32))
    sparse = Tensor(
        "emb",
        rng.standard_normal((3, 4)).astype(np.float32),
        indices=np.array([5, 2, 9]),
    )
    out, names = compress_tensors([dense, sparse], "bfloat16")
    assert names == ["w", "emb"]
    # compression MARKS (allocation-free): values still alias the
    # caller's f32 arrays, the downcast fuses into the frame copy-out
    assert out[0].values is dense.values
    assert str(out[0].wire_dtype) == "bfloat16"
    frame = deserialize_tensor(serialize_tensor(out[0]))
    assert str(frame.values.dtype) == "bfloat16"
    back = decompress_tensors([frame, out[1]], names)
    assert back[0].values.dtype == np.float32
    # bf16 has 8 mantissa bits
    np.testing.assert_allclose(
        back[0].values, dense.values, rtol=1e-2, atol=1e-2
    )
    np.testing.assert_array_equal(back[1].indices, sparse.indices)
    # the in-process transport never serialized: the marked tensor
    # passes decompress at full f32 precision, mark shed
    assert back[1].values is sparse.values or np.array_equal(
        back[1].values, sparse.values
    )
    assert back[1].wire_dtype is None


def test_non_f32_payloads_pass_through():
    ids = Tensor("ids", np.arange(6, dtype=np.int64))
    out, names = compress_tensors([ids], "bfloat16")
    assert names == []
    assert out[0].values.dtype == np.int64
    # decompress with no names is identity
    assert decompress_tensors(out, [])[0] is out[0]


def test_disabled_and_unknown_dtype():
    t = Tensor("w", np.ones((2,), np.float32))
    out, names = compress_tensors([t], "")
    assert names == [] and out[0] is t
    with pytest.raises(ValueError, match="unsupported wire_dtype"):
        compress_tensors([t], "float16")


def test_ps_pull_push_roundtrip_with_compression():
    """In-process PS with wire_dtype on both sides: the worker-facing
    surface still speaks f32, and training math proceeds."""
    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer
    from elasticdl_tpu.worker.ps_client import PSClient

    params = Parameters()
    servicer = PserverServicer(
        params,
        grads_to_wait=1,
        optimizer=optax.sgd(0.1),
        wire_dtype="bfloat16",
    )
    client = PSClient([servicer], wire_dtype="bfloat16")
    w0 = np.linspace(-1, 1, 12, dtype=np.float32).reshape(3, 4)
    client.push_model({"w": w0}, version=0)

    ok, version, named = client.pull_dense()
    assert ok and version == 0
    assert named["w"].dtype == np.float32
    np.testing.assert_allclose(named["w"], w0, rtol=1e-2, atol=1e-2)

    grad = np.full((3, 4), 0.5, np.float32)
    accepted, version = client.push_gradient({"w": grad}, [], 0)
    assert accepted and version == 1
    _, _, after = client.pull_dense()
    # sgd(0.1): w - 0.1*0.5, within bf16 wire tolerance both directions
    np.testing.assert_allclose(
        after["w"], w0 - 0.05, rtol=2e-2, atol=2e-2
    )


def test_flag_mismatch_degrades_to_uncompressed():
    """Server compressing + client not configured still yields f32 at
    the API surface (decompression is driven by the response field)."""
    from elasticdl_tpu.ps.parameters import Parameters
    from elasticdl_tpu.ps.servicer import PserverServicer
    from elasticdl_tpu.worker.ps_client import PSClient

    params = Parameters()
    servicer = PserverServicer(
        params,
        grads_to_wait=1,
        optimizer=optax.sgd(0.1),
        wire_dtype="bfloat16",
    )
    client = PSClient([servicer])  # no wire_dtype
    w0 = np.ones((2, 2), np.float32)
    client.push_model({"w": w0}, version=0)
    ok, _, named = client.pull_dense()
    assert ok and named["w"].dtype == np.float32

    # client compressing + server always decompresses by request field
    client2 = PSClient([servicer], wire_dtype="bfloat16")
    accepted, version = client2.push_gradient(
        {"w": np.ones((2, 2), np.float32)}, [], 0
    )
    assert accepted and version == 1


def test_master_plane_compression_over_real_rpc():
    """MasterRpcService + MasterClient over a real rpc.core server:
    get_model decompresses to f32; compressed report_gradient applies."""
    from elasticdl_tpu.common.constants import GetModelMethod
    from elasticdl_tpu.master.checkpoint_service import CheckpointService
    from elasticdl_tpu.master.rpc_service import (
        MasterClient,
        MasterRpcService,
    )
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.rpc.core import serve

    task_d = TaskDispatcher({}, {}, {}, 4, 1)
    servicer = MasterServicer(
        1,
        4,
        optax.sgd(0.1),
        task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
    )
    service = MasterRpcService(servicer, wire_dtype="bfloat16")
    server = serve(service.rpc_methods(), 0)
    try:
        client = MasterClient(
            "localhost:%d" % server._edl_port, wire_dtype="bfloat16"
        )
        w0 = np.linspace(0, 1, 6, dtype=np.float32).reshape(2, 3)
        client.report_variable({"w": w0})
        version, named = client.get_model(0, GetModelMethod.MINIMUM)
        assert named["w"].dtype == np.float32
        np.testing.assert_allclose(named["w"], w0, rtol=1e-2, atol=1e-2)

        grad = Tensor("w", np.full((2, 3), 0.2, np.float32))
        accepted, version = client.report_gradient([grad], 0)
        assert accepted and version == 1
        _, after = client.get_model(1, GetModelMethod.MINIMUM)
        np.testing.assert_allclose(
            after["w"], w0 - 0.02, rtol=2e-2, atol=2e-2
        )
    finally:
        server.stop(0)
