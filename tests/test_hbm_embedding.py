"""HBM-sharded embedding: lookup + gradient correctness on the 8-dev mesh."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from elasticdl_tpu.nn.hbm_embedding import (
    HbmEmbedding,
    sharded_lookup,
    table_sharding,
)
from elasticdl_tpu.parallel.mesh import create_mesh


def test_sharded_lookup_matches_take():
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    rng = np.random.default_rng(0)
    table = rng.standard_normal((64, 5)).astype(np.float32)
    ids = rng.integers(0, 64, size=(3, 7))
    got = np.asarray(
        jax.jit(lambda t, i: sharded_lookup(t, i, mesh, "data"))(table, ids)
    )
    np.testing.assert_allclose(got, table[ids], rtol=1e-6)


def test_sharded_lookup_gradient_is_row_sparse_scatter():
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    table = np.ones((16, 3), np.float32)
    ids = np.array([[1, 5, 1]])

    def loss(t):
        return sharded_lookup(t, ids, mesh, "data").sum()

    g = np.asarray(jax.jit(jax.grad(loss))(table))
    expected = np.zeros_like(table)
    expected[1] = 2.0  # duplicate id accumulates
    expected[5] = 1.0
    np.testing.assert_array_equal(g, expected)


class TinyCTR(nn.Module):
    mesh: object = None

    @nn.compact
    def __call__(self, features, training=False):
        ids = features["ids"]
        emb = HbmEmbedding(
            vocab_size=128, features=8, mesh=self.mesh, axis="data"
        )(ids)
        x = emb.sum(axis=1)
        return nn.Dense(1)(x).reshape(-1)


def test_hbm_embedding_trains_sharded():
    """Full jitted train step with the table sharded over the mesh; the
    optimizer state co-shards with the table parameter."""
    mesh = create_mesh({"data": 8}, axis_names=("data",))
    model = TinyCTR(mesh=mesh)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 128, size=(16, 4))
    y = (ids == 7).any(axis=1).astype(np.float32)
    features = {"ids": ids}

    variables = model.init(jax.random.PRNGKey(0), features)
    params = variables["params"]
    # place the table sharded, everything else replicated
    params = jax.tree_util.tree_map(jax.device_put, params)
    params["HbmEmbedding_0"]["table"] = jax.device_put(
        params["HbmEmbedding_0"]["table"], table_sharding(mesh)
    )
    opt = optax.adam(3e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, opt_state):
        def loss_fn(p):
            out = model.apply({"params": p}, features)
            return optax.sigmoid_binary_cross_entropy(out, y).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state2 = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state2, loss

    with mesh:
        losses = []
        for _ in range(60):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # the table stayed sharded through the updates
    spec = params["HbmEmbedding_0"]["table"].sharding.spec
    assert "data" in str(spec)
    # adam's moment buffers co-sharded with the table
    mu_table = opt_state[0].mu["HbmEmbedding_0"]["table"]
    assert "data" in str(mu_table.sharding.spec)


def test_a2a_lookup_matches_take():
    from elasticdl_tpu.nn.hbm_embedding import all_to_all_lookup

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    rng = np.random.default_rng(0)
    table = rng.standard_normal((64, 5)).astype(np.float32)
    ids = rng.integers(0, 64, size=(3, 7))
    got = np.asarray(
        jax.jit(lambda t, i: all_to_all_lookup(t, i, mesh, "data"))(
            table, ids
        )
    )
    np.testing.assert_allclose(got, table[ids], rtol=1e-6)


def test_a2a_lookup_gradient_routes_to_owner_shards():
    from elasticdl_tpu.nn.hbm_embedding import all_to_all_lookup

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    table = np.ones((16, 3), np.float32)
    ids = np.array([[1, 5, 1]])

    def loss(t):
        return all_to_all_lookup(t, ids, mesh, "data").sum()

    g = np.asarray(jax.jit(jax.grad(loss))(table))
    expected = np.zeros_like(table)
    expected[1] = 2.0  # duplicate id accumulates
    expected[5] = 1.0
    np.testing.assert_array_equal(g, expected)


def test_a2a_lookup_capacity_overflow_drops_to_zero():
    from elasticdl_tpu.nn.hbm_embedding import all_to_all_lookup

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    table = np.arange(32, dtype=np.float32).reshape(16, 2)
    # 4 ids all owned by shard 0 with capacity 2: two resolve, two drop
    ids = np.array([0, 1, 0, 1])
    got = np.asarray(
        jax.jit(
            lambda t, i: all_to_all_lookup(t, i, mesh, "data", capacity=2)
        )(table, ids)
    )
    assert (got[:2] == table[ids[:2]]).all()
    assert (got[2:] == 0).all()


def test_a2a_overflow_is_counted_not_silent():
    """An undersized capacity must produce a nonzero overflow signal:
    both from the raw lookup (return_overflow) and accumulated into the
    HbmEmbedding metrics counter across steps."""
    from elasticdl_tpu.nn.hbm_embedding import (
        a2a_overflow_total,
        all_to_all_lookup,
    )

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    table = np.arange(32, dtype=np.float32).reshape(16, 2)
    ids = np.array([0, 1, 0, 1])  # all owned by shard 0; capacity 2
    _, n_over = jax.jit(
        lambda t, i: all_to_all_lookup(
            t, i, mesh, "data", capacity=2, return_overflow=True
        )
    )(table, ids)
    assert int(n_over) == 2

    # layer-level: the metrics collection accumulates across train steps
    # (dedup=False: this test meters PER-OCCURRENCE capacity overflow;
    # the dedup fast path would resolve these duplicate ids in 2 slots)
    model = HbmEmbedding(
        vocab_size=16, features=2, mesh=mesh, axis="data",
        method="a2a", capacity=2, dedup=False,
    )
    variables = model.init(jax.random.PRNGKey(0), ids)
    state = {k: v for k, v in variables.items() if k != "params"}
    assert a2a_overflow_total(state) == 0

    @jax.jit
    def step(params, state):
        _, new_state = model.apply(
            {"params": params, **state}, ids, mutable=["metrics"]
        )
        return dict(new_state)

    with mesh:
        state = step(variables["params"], state)
        state = step(variables["params"], state)
    assert a2a_overflow_total(state) == 4  # 2 dropped ids x 2 steps

    # a generous capacity keeps the counter at zero
    ok_model = HbmEmbedding(
        vocab_size=16, features=2, mesh=mesh, axis="data", method="a2a"
    )
    v2 = ok_model.init(jax.random.PRNGKey(0), ids)
    with mesh:
        _, s2 = jax.jit(
            lambda p, s: ok_model.apply(
                {"params": p, **s}, ids, mutable=["metrics"]
            )
        )(v2["params"], {k: v for k, v in v2.items() if k != "params"})
    assert a2a_overflow_total(dict(s2)) == 0


def test_lookup_rejects_non_divisible_vocab():
    from elasticdl_tpu.nn.hbm_embedding import all_to_all_lookup

    import pytest

    mesh = create_mesh({"data": 8}, axis_names=("data",))
    table = np.ones((15, 2), np.float32)  # 15 % 8 != 0
    ids = np.array([0, 1])
    with pytest.raises(ValueError, match="not divisible"):
        all_to_all_lookup(table, ids, mesh, "data")
    with pytest.raises(ValueError, match="not divisible"):
        sharded_lookup(table, ids, mesh, "data")


def test_a2a_lookup_with_dp_sharded_batch():
    """table on 'model', ids sharded over 'data': each dp replica routes
    only its own slice."""
    from elasticdl_tpu.nn.hbm_embedding import all_to_all_lookup

    mesh = create_mesh(
        {"data": 2, "model": 4}, axis_names=("data", "model")
    )
    rng = np.random.default_rng(1)
    table = rng.standard_normal((32, 4)).astype(np.float32)
    ids = rng.integers(0, 32, size=(8,))
    got = np.asarray(
        jax.jit(lambda t, i: all_to_all_lookup(t, i, mesh, "model"))(
            table, ids
        )
    )
    np.testing.assert_allclose(got, table[ids], rtol=1e-6)
