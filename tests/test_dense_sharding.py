"""The pjit dense plane (ROADMAP item 5): 2D ``data x model`` sharded
dense models inside the elastic world, plus the dlpack wire bridge.

Three contracts pinned here:

- PARITY: the GSPMD weighted step (make_pjit_train_step) computes the
  SAME training trajectory as the replicated shard_map arm from one
  common init — bitwise losses, 1e-6 parameters (XLA may reassociate
  the partitioned matmul reductions).
- LAYOUT RE-SOLVE: a resize moves state DIRECTLY between old and new
  NamedSharding layouts (2x2 -> 4x1 -> 2x2 at the function level, a
  4x2 -> 8x1 -> 2x4 establish journey at the trainer level), carrying
  every leaf bitwise — no host round trip, no disk, no re-init.
- WIRE PIN: a ``jax.Array`` frames BYTE-IDENTICALLY to its host-staged
  twin (fused bf16 downcast included) — the dlpack bridge changes how
  bytes are produced, never which bytes.
"""

import numpy as np
import optax
import pytest

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

import elasticdl_tpu.parallel.distributed as dist_mod
from elasticdl_tpu.nn.model_api import init_variables, split_variables
from elasticdl_tpu.parallel.distributed import WorldSpec
from elasticdl_tpu.parallel.elastic import (
    ElasticDPTrainer,
    build_state_specs,
    collect_sharded_paths,
    make_pjit_train_step,
    place_from_host_specs,
    specs_use_axis,
)
from elasticdl_tpu.parallel.sharding import tp_param_specs
from elasticdl_tpu.training.step import TrainState
from model_zoo.transformer_lm import transformer_lm as tzoo

KW = dict(
    vocab_size=32,
    num_layers=2,
    num_heads=4,
    head_dim=8,
    embed_dim=16,
    mlp_dim=32,
    use_flash=False,
)


def _batches(n, batch=16, length=8, seed=3):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        toks = rng.integers(0, KW["vocab_size"], (batch, length))
        toks = toks.astype(np.int32)
        out.append(({"tokens": toks}, toks.copy()))
    return out


def _gather(tree):
    """Full host values of a (possibly sharded) device pytree."""
    return jax.tree_util.tree_map(
        lambda x: np.asarray(jax.device_get(x)), tree
    )


def _assert_trees_close(a, b, rtol=0.0, atol=0.0):
    for (pa, la), (_pb, lb) in zip(
        jax.tree_util.tree_leaves_with_path(a),
        jax.tree_util.tree_leaves_with_path(b),
    ):
        np.testing.assert_allclose(
            np.asarray(la),
            np.asarray(lb),
            rtol=rtol,
            atol=atol,
            err_msg=str(pa),
        )


@pytest.fixture
def singleton_world(monkeypatch):
    """ElasticDPTrainer establish without jax.distributed (the same
    bypass test_elastic_sharded uses for single-process worlds)."""
    monkeypatch.setattr(dist_mod, "ensure_world", lambda s, **k: None)
    yield


def _tp_builder(tensor_parallel):
    def builder(mesh):
        return (
            tzoo.custom_model(**KW),
            tzoo.param_shardings(mesh, tensor_parallel=tensor_parallel),
        )

    return builder


# ---------------------------------------------------------------------------
# parity: pjit 2D-sharded step vs the replicated arm, one common init
# ---------------------------------------------------------------------------


def test_pjit_sharded_matches_replicated_trainer(singleton_world):
    batches = _batches(4)
    spec = WorldSpec(
        coordinator="", num_processes=1, process_id=0, epoch=0
    )

    trep = ElasticDPTrainer(
        tzoo.custom_model(**KW), tzoo.loss, optax.sgd(0.05)
    )
    trep.establish(spec, example_batch=batches[0])
    tsh = ElasticDPTrainer(
        tzoo.custom_model(**KW),
        tzoo.loss,
        optax.sgd(0.05),
        distributed_builder=_tp_builder(2),
        mesh_axes_fn=lambda n: tzoo.mesh_axes(n, tensor_parallel=2),
    )
    tsh.establish(spec, example_batch=batches[0])
    try:
        assert tsh._pjit_dense
        assert dict(tsh.mesh.shape) == {"data": 4, "model": 2}
        # the dense model is REALLY sharded: a TP kernel holds 1/2 of
        # its rows per device, not a replica
        kern = tsh._ts.params["block_0"]["query"]["kernel"]
        assert kern.sharding.spec == P(None, "model", None)
        shard = kern.addressable_shards[0].data
        assert shard.shape[1] * 2 == kern.shape[1]
        # both inits are the same deterministic host init
        _assert_trees_close(
            _gather(trep._ts.params), _gather(tsh._ts.params)
        )
        for features, labels in batches:
            l_rep, n_rep, _ = trep.train_step(
                features, labels, 16, sync=True
            )
            l_pjit, n_pjit, _ = tsh.train_step(
                features, labels, 16, sync=True
            )
            # losses come out bitwise on this toolchain; the gate is
            # 1e-6 (the acceptance bound — reassociation headroom)
            assert abs(l_rep - l_pjit) <= 1e-6 * max(1.0, abs(l_rep))
            assert n_rep == n_pjit
        _assert_trees_close(
            _gather(trep._ts.params),
            _gather(tsh._ts.params),
            rtol=2e-6,
            atol=2e-6,
        )
    finally:
        trep.close()
        tsh.close()


def test_pjit_weighted_drain_step_is_identity(singleton_world):
    """Weight-0 (drain) steps pass state through unchanged and do not
    advance the version — the elastic no-op contract on the pjit arm."""
    batches = _batches(2)
    spec = WorldSpec(
        coordinator="", num_processes=1, process_id=0, epoch=0
    )
    tsh = ElasticDPTrainer(
        tzoo.custom_model(**KW),
        tzoo.loss,
        optax.sgd(0.05),
        distributed_builder=_tp_builder(2),
        mesh_axes_fn=lambda n: tzoo.mesh_axes(n, tensor_parallel=2),
    )
    tsh.establish(spec, example_batch=batches[0])
    try:
        tsh.train_step(*batches[0], 16, sync=True)
        before = _gather(tsh._ts)
        v_before = tsh.version
        loss, n, count = tsh.train_step(None, None, 16, sync=True)
        assert count == 0 and n == 0
        assert tsh.version == v_before
        _assert_trees_close(before, _gather(tsh._ts))
    finally:
        tsh.close()


def test_pjit_mode_rejects_accum_steps(singleton_world):
    batches = _batches(1)
    t = ElasticDPTrainer(
        tzoo.custom_model(**KW),
        tzoo.loss,
        optax.sgd(0.05),
        accum_steps=2,
        distributed_builder=_tp_builder(2),
        mesh_axes_fn=lambda n: tzoo.mesh_axes(n, tensor_parallel=2),
    )
    with pytest.raises(ValueError, match="accum_steps"):
        t.establish(
            WorldSpec(
                coordinator="", num_processes=1, process_id=0, epoch=0
            ),
            example_batch=batches[0],
        )
    t.close()


# ---------------------------------------------------------------------------
# layout re-solve: 2x2 -> 4x1 -> 2x2 at the function level
# ---------------------------------------------------------------------------


def _mesh4(data, model):
    devs = np.asarray(jax.devices()[:4]).reshape(data, model)
    return Mesh(devs, ("data", "model"))


def _place(mesh, ts, specs):
    return place_from_host_specs(mesh, ts, specs)


def _relayout(ts, mesh, specs):
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs
    )
    return jax.tree_util.tree_map(jax.device_put, ts, shardings)


def test_layout_resolve_2x2_4x1_2x2_carries_state():
    """The ISSUE journey on an explicit 4-device submesh: state placed
    2x2, re-solved to 4x1 (model axis collapses to a divisor of 1),
    stepped, re-solved back to 2x2 — every move bitwise, and the final
    state equals an uninterrupted 2x2 run's."""
    batches = _batches(3, batch=8)
    model = tzoo.custom_model(**KW)
    opt = optax.sgd(0.05)
    variables = init_variables(
        model,
        jax.random.PRNGKey(0),
        jax.tree_util.tree_map(lambda x: x[:1], batches[0][0]),
    )
    params, state = split_variables(variables)
    ts_host = TrainState.create(params, state, opt)
    sharded = collect_sharded_paths(tp_param_specs())
    assert specs_use_axis(sharded, "model")
    specs = build_state_specs(ts_host, sharded)

    row = ("data", "model")

    def run(mesh_seq):
        """Step once per mesh, re-solving the layout between steps."""
        mesh = mesh_seq[0]
        ts = _place(mesh, ts_host, specs)
        losses = []
        steps = {}
        for i, (features, labels) in enumerate(batches):
            if mesh_seq[i] is not mesh:
                mesh = mesh_seq[i]
                ts = _relayout(ts, mesh, specs)
            if id(mesh) not in steps:
                steps[id(mesh)] = make_pjit_train_step(
                    model, tzoo.loss, opt, mesh, specs
                )
            step = steps[id(mesh)]
            n_dev = mesh.devices.size

            def put(x):
                x = np.asarray(x)
                spec = P(*((row,) + (None,) * (x.ndim - 1)))
                return jax.device_put(x, NamedSharding(mesh, spec))

            g_f = jax.tree_util.tree_map(put, features)
            g_l = jax.tree_util.tree_map(put, labels)
            w = put(np.ones((n_dev,), np.float32))
            e = put(np.zeros((n_dev,), np.int32))
            with mesh:
                ts, loss, _n, _ = step(
                    ts, g_f, g_l, w, e, jax.random.PRNGKey(7)
                )
            losses.append(float(loss))
        return losses, _gather(ts)

    m22, m41 = _mesh4(2, 2), _mesh4(4, 1)
    # the relayout MOVE is bitwise: place on 2x2, re-solve to 4x1 and
    # back, no step in between — every leaf identical
    placed = _place(m22, ts_host, specs)
    round_tripped = _relayout(
        _relayout(placed, m41, specs), _mesh4(2, 2), specs
    )
    _assert_trees_close(_gather(placed), _gather(round_tripped))
    # training THROUGH the journey tracks the uninterrupted 2x2 run at
    # the 1e-6 parity gate (a step executed on a different layout
    # reassociates its partitioned reductions at float-ulp level)
    journey_losses, journey_ts = run([m22, m41, _mesh4(2, 2)])
    straight_losses, straight_ts = run([m22, m22, m22])
    np.testing.assert_allclose(
        journey_losses, straight_losses, rtol=1e-6, atol=1e-6
    )
    _assert_trees_close(journey_ts, straight_ts, rtol=1e-6, atol=1e-6)


def test_trainer_resize_journey_relayout(singleton_world):
    """Trainer-level establish journey over the 8-device world:
    4x2 -> 8x1 -> 2x4. Each resize takes the DIRECT relayout path
    (state moved between NamedShardings, bitwise), and training
    continues on every new layout."""
    batches = _batches(4)
    layout = {"axes": {"data": 4, "model": 2}}
    tsh = ElasticDPTrainer(
        tzoo.custom_model(**KW),
        tzoo.loss,
        optax.sgd(0.05),
        distributed_builder=_tp_builder(2),
        mesh_axes_fn=lambda n: dict(layout["axes"]),
    )
    spec_of = lambda epoch: WorldSpec(
        coordinator="", num_processes=1, process_id=0, epoch=epoch
    )
    tsh.establish(spec_of(0), example_batch=batches[0])
    try:
        tsh.train_step(*batches[0], 16, sync=True)
        tsh.train_step(*batches[1], 16, sync=True)
        before = _gather(tsh._ts)
        layout["axes"] = {"data": 8, "model": 1}
        tsh.establish(spec_of(1), example_batch=batches[0])
        assert dict(tsh.mesh.shape) == {"data": 8, "model": 1}
        _assert_trees_close(before, _gather(tsh._ts))
        loss_81, _, _ = tsh.train_step(*batches[2], 16, sync=True)
        before = _gather(tsh._ts)
        layout["axes"] = {"data": 2, "model": 4}
        tsh.establish(spec_of(2), example_batch=batches[0])
        assert dict(tsh.mesh.shape) == {"data": 2, "model": 4}
        _assert_trees_close(before, _gather(tsh._ts))
        kern = tsh._ts.params["block_0"]["query"]["kernel"]
        shard = kern.addressable_shards[0].data
        assert shard.shape[1] * 4 == kern.shape[1]
        loss_24, _, _ = tsh.train_step(*batches[3], 16, sync=True)
        assert np.isfinite(loss_81) and np.isfinite(loss_24)
    finally:
        tsh.close()


def test_direct_relayout_matches_checkpoint_interchange(
    singleton_world, tmp_path
):
    """ISSUE 20 acceptance: across the dp4x2 -> dp2x4 -> dp8x1
    journey, the DIRECT redistribution path (state device_put old ->
    new NamedShardings) produces the bitwise-identical TrainState the
    sharded-checkpoint interchange (the backend-died fallback) would
    have restored. Both paths run from the same pre-resize state: the
    direct trainer relays in place; a fresh trainer with a
    restore_provider pointed at a pre-resize snapshot establishes cold
    onto the new layout. Bitwise (atol=0) across params, optimizer
    slots, and counters."""
    batches = _batches(4)
    layout = {"axes": {"data": 4, "model": 2}}
    direct = ElasticDPTrainer(
        tzoo.custom_model(**KW),
        tzoo.loss,
        optax.sgd(0.05),
        distributed_builder=_tp_builder(2),
        mesh_axes_fn=lambda n: dict(layout["axes"]),
    )
    spec_of = lambda epoch: WorldSpec(
        coordinator="", num_processes=1, process_id=0, epoch=epoch
    )
    direct.establish(spec_of(0), example_batch=batches[0])
    try:
        direct.train_step(*batches[0], 16, sync=True)
        direct.train_step(*batches[1], 16, sync=True)
        journey = ({"data": 2, "model": 4}, {"data": 8, "model": 1})
        for leg, axes in enumerate(journey):
            before = _gather(direct._ts)
            ckdir = tmp_path / ("leg%d" % leg)
            direct.save_sharded(str(ckdir))
            layout["axes"] = dict(axes)
            direct.establish(spec_of(leg + 1), example_batch=batches[0])
            assert dict(direct.mesh.shape) == axes
            after_direct = _gather(direct._ts)
            # direct trainer has no restore_provider and no mirrors:
            # preserving the trained state proves the relayout branch
            # ran (the only other outcome is deterministic re-init)
            _assert_trees_close(before, after_direct)
            cold = ElasticDPTrainer(
                tzoo.custom_model(**KW),
                tzoo.loss,
                optax.sgd(0.05),
                distributed_builder=_tp_builder(2),
                mesh_axes_fn=lambda n: dict(layout["axes"]),
                restore_provider=lambda: str(ckdir),
            )
            cold.establish(spec_of(0), example_batch=batches[0])
            try:
                assert dict(cold.mesh.shape) == axes
                _assert_trees_close(after_direct, _gather(cold._ts))
            finally:
                cold.close()
            # advance the state so the next leg moves fresh bytes
            direct.train_step(*batches[2 + leg], 16, sync=True)
    finally:
        direct.close()


# ---------------------------------------------------------------------------
# zoo/worker routing
# ---------------------------------------------------------------------------


def test_tp_specs_cover_the_name_rule_families():
    """tp_param_specs is the promotion of parallel/sharding's TP name
    rules: every rule family appears as a suffix-spec, and the specs
    claim the transformer's real parameter paths."""
    from elasticdl_tpu.common.pytree import key_path_names
    from elasticdl_tpu.parallel.elastic import spec_path_matches

    sharded = collect_sharded_paths(tp_param_specs())
    for family in (
        ("query", "kernel"),
        ("key", "kernel"),
        ("value", "kernel"),
        ("out", "kernel"),
        ("mlp_up", "kernel"),
        ("mlp_down", "kernel"),
        ("embed", "embedding"),
    ):
        assert family in sharded, family
    model = tzoo.custom_model(**KW)
    variables = init_variables(
        model,
        jax.random.PRNGKey(0),
        {"tokens": np.zeros((1, 8), np.int32)},
    )
    params, _ = split_variables(variables)
    claimed = []

    def visit(key_path, _leaf):
        names = key_path_names(key_path)
        for spec_path in sharded:
            if spec_path_matches(spec_path, names):
                claimed.append("/".join(names))

    jax.tree_util.tree_map_with_path(visit, params)
    assert "block_0/query/kernel" in claimed
    assert "block_1/mlp_down/kernel" in claimed
    assert "embed/embedding" in claimed


def test_zoo_emits_model_axis_specs_and_worker_routes_pjit():
    specs = tzoo.param_shardings(None, tensor_parallel=2)
    assert specs_use_axis(collect_sharded_paths(specs), "model")
    assert tzoo.mesh_axes(8, tensor_parallel=2) == {
        "data": 4,
        "model": 2,
    }
    with pytest.raises(ValueError):
        tzoo.mesh_axes(6, tensor_parallel=4)
    with pytest.raises(ValueError):
        tzoo.param_shardings(
            None, tensor_parallel=2, pipeline_stages=2
        )
    # the worker's probe routes pjit-dense configs to the plain module
    from elasticdl_tpu.worker.elastic_allreduce_worker import (
        ElasticAllReduceWorker,
    )

    zoo_module = {"param_shardings": tzoo.param_shardings}
    assert ElasticAllReduceWorker._zoo_wants_pjit_dense(
        zoo_module, "tensor_parallel=2"
    )
    assert not ElasticAllReduceWorker._zoo_wants_pjit_dense(
        zoo_module, "pipeline_stages=2"
    )
    # deepfm's hbm-table specs stay on the collective path
    from model_zoo.deepfm_edl_embedding import (
        deepfm_edl_embedding as dzoo,
    )

    assert not ElasticAllReduceWorker._zoo_wants_pjit_dense(
        {"param_shardings": dzoo.param_shardings}, ""
    )


# ---------------------------------------------------------------------------
# wire pin: jax.Array frames byte-identically to its host twin
# ---------------------------------------------------------------------------


def test_device_array_frames_byte_identical():
    import ml_dtypes

    from elasticdl_tpu.common.tensor import (
        Tensor,
        device_host_view,
        is_device_array,
        serialize_tensor,
    )
    from elasticdl_tpu.rpc.core import pack_message, unpack_message

    host = np.random.default_rng(5).standard_normal((256, 16))
    host = host.astype(np.float32)
    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    arms = {
        "single": jax.device_put(host, jax.devices()[0]),
        "replicated": jax.device_put(host, NamedSharding(mesh, P())),
        "sharded": jax.device_put(
            host, NamedSharding(mesh, P("data"))
        ),
    }
    for name, dev in arms.items():
        assert is_device_array(dev)
        # Tensor keeps the device array unmaterialized (the bridge)
        t = Tensor("t", dev)
        assert t.values is dev
        assert bytes(serialize_tensor(t)) == bytes(
            serialize_tensor(Tensor("t", host))
        ), name
        # fused bf16 downcast: device and host twins still byte-equal
        td, th = Tensor("t", dev), Tensor("t", host)
        td.wire_dtype = np.dtype(ml_dtypes.bfloat16)
        th.wire_dtype = np.dtype(ml_dtypes.bfloat16)
        assert bytes(serialize_tensor(td)) == bytes(
            serialize_tensor(th)
        ), name + "/bf16"
    # message packer accepts bare jax.Array fields
    m_dev = bytes(pack_message({"params": arms["replicated"], "v": 1}))
    m_host = bytes(pack_message({"params": host, "v": 1}))
    assert m_dev == m_host
    np.testing.assert_array_equal(unpack_message(m_dev)["params"], host)
    # the zero-copy claim itself: a replicated array's host view
    # shares memory with its shard-0 device buffer (CPU backend)
    view = device_host_view(arms["replicated"])
    assert np.shares_memory(
        view, np.from_dlpack(arms["replicated"].addressable_shards[0].data)
    )


def test_wire_bound_pytree_keeps_device_leaves():
    from elasticdl_tpu.common.tensor import (
        is_device_array,
        pytree_to_named_arrays,
    )

    tree = {
        "dense": {"kernel": jax.numpy.ones((4, 4))},
        "host": np.ones((2,), np.float32),
    }
    wire = pytree_to_named_arrays(tree, keep_device=True)
    assert is_device_array(wire["dense/kernel"])
    assert isinstance(wire["host"], np.ndarray)
    ckpt = pytree_to_named_arrays(tree)
    assert isinstance(ckpt["dense/kernel"], np.ndarray)
