"""Pipeline parallelism on the ELASTIC weighted step (pp x dp).

The multi-process elastic plane expresses every parallelism inside one
shard_map (a nested shard_map is impossible), so the pipeline ring runs
in its raw-collective form (parallel/pipeline.collective_pipeline_apply)
over a ("data", "pipe") mesh — the same recipe as the HBM embedding's
collective lookups. These tests pin the semantics single-process on the
virtual 8-device CPU mesh: the collective pp x dp step must match the
sequential (mesh=None) pipelined model trained on the plain elastic DP
step, exactly — same losses, same trained parameters — including the
weighted-elasticity cases (weight-0 devices, fractional tail weights).

The reference has no pipeline parallelism at all (SURVEY.md §2.2); its
elasticity premise "any worker can die anytime"
(reference master/task_dispatcher.py:247-255) is what the multi-process
rungs in tests/test_elastic_allreduce.py extend to this topology.
"""

import numpy as np
import pytest

import jax
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from elasticdl_tpu.nn.model_api import init_variables, split_variables
from elasticdl_tpu.parallel.elastic import (
    build_state_specs,
    build_world_mesh,
    collect_sharded_paths,
    host_copy,
    make_elastic_train_step,
    place_from_host_specs,
)
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.training.step import TrainState
from model_zoo.transformer_lm import transformer_lm as zoo

VOCAB = 64
LENGTH = 8
MODEL_KW = dict(
    vocab_size=VOCAB,
    num_layers=2,
    num_heads=2,
    head_dim=8,
    embed_dim=16,
    mlp_dim=32,
    use_flash=False,
)


def _batches(n_steps, batch=16, seed=11):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_steps):
        ids = rng.integers(0, VOCAB, size=(batch, LENGTH)).astype(
            np.int32
        )
        out.append(({"tokens": ids}, ids))
    return out


def _init_state(model, example, opt):
    variables = init_variables(model, jax.random.PRNGKey(0), example)
    params, state = split_variables(variables)
    return TrainState.create(params, state, opt)


def _put_rows(mesh, tree, row_axes):
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(
            x,
            NamedSharding(
                mesh, P(*((row_axes,) + (None,) * (np.asarray(x).ndim - 1)))
            ),
        ),
        tree,
    )


def _run(mesh, model, specs, batches, weights, opt):
    """Drive the elastic step over ``batches``; returns (losses, ts)."""
    row_axes = (
        tuple(mesh.axis_names)
        if len(mesh.axis_names) > 1
        else mesh.axis_names[0]
    )
    ts_host = _init_state(model, batches[0][0], opt)
    if specs is not None:
        ts = place_from_host_specs(mesh, ts_host, specs)
    else:
        ts = jax.device_put(ts_host, NamedSharding(mesh, P()))
    step = make_elastic_train_step(
        model, zoo.loss, opt, mesh, state_specs=specs
    )
    w = jax.device_put(
        np.asarray(weights, np.float32),
        NamedSharding(mesh, P(row_axes)),
    )
    ep = jax.device_put(
        np.zeros(8, np.int32), NamedSharding(mesh, P(row_axes))
    )
    key = jax.random.PRNGKey(5)
    losses = []
    with mesh:
        for features, labels in batches:
            ts, loss, n, _ = step(
                ts,
                _put_rows(mesh, features, row_axes),
                _put_rows(mesh, labels, row_axes),
                w,
                ep,
                key,
            )
            losses.append(float(loss))
    return losses, ts


def _stacked_leaves(params):
    return {
        "/".join(str(k) for k in path): np.asarray(leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]
    }


def _pp_setup(opt, example):
    mesh = create_mesh(
        {"data": 4, "pipe": 2}, axis_names=("data", "pipe")
    )
    model = zoo.build_collective_model(pipeline_stages=2, **MODEL_KW)
    sharded = collect_sharded_paths(
        zoo.param_shardings(mesh, pipeline_stages=2)
    )
    ts_probe = _init_state(model, example, opt)
    specs = build_state_specs(ts_probe, sharded)
    return mesh, model, specs


def test_collective_pp_dp_step_matches_sequential():
    """pp x dp on the elastic weighted step == the sequential pipelined
    model on the plain elastic DP step: same losses, same trained
    parameters (stage subtree included)."""
    opt = optax.sgd(0.05)
    batches = _batches(4)
    mesh, model, specs = _pp_setup(opt, batches[0][0])
    losses, ts = _run(mesh, model, specs, batches, np.ones(8), opt)

    seq_model = zoo.build_distributed_model(
        mesh=None, pipeline_stages=2, **MODEL_KW
    )
    seq_mesh = create_mesh({"data": 8}, axis_names=("data",))
    seq_losses, seq_ts = _run(
        seq_mesh, seq_model, None, batches, np.ones(8), opt
    )

    np.testing.assert_allclose(losses, seq_losses, rtol=2e-4, atol=1e-5)
    got = _stacked_leaves(jax.device_get(ts.params))
    want = _stacked_leaves(jax.device_get(seq_ts.params))
    assert got.keys() == want.keys()
    for k in got:
        np.testing.assert_allclose(
            got[k], want[k], rtol=5e-4, atol=2e-5, err_msg=k
        )


def test_collective_pp_dp_weighted_devices_match_sequential():
    """Per-device participation weights must mean the same thing on the
    pp x dp mesh as on the flat DP mesh: two weight-0 devices and one
    fractional tail weight, identical loss trajectory and parameters."""
    opt = optax.sgd(0.05)
    batches = _batches(3, seed=23)
    weights = np.array([1, 1, 0, 1, 0.25, 1, 0, 1], np.float32)
    mesh, model, specs = _pp_setup(opt, batches[0][0])
    losses, ts = _run(mesh, model, specs, batches, weights, opt)

    seq_model = zoo.build_distributed_model(
        mesh=None, pipeline_stages=2, **MODEL_KW
    )
    seq_mesh = create_mesh({"data": 8}, axis_names=("data",))
    seq_losses, seq_ts = _run(
        seq_mesh, seq_model, None, batches, weights, opt
    )
    np.testing.assert_allclose(losses, seq_losses, rtol=2e-4, atol=1e-5)
    got = _stacked_leaves(jax.device_get(ts.params))
    want = _stacked_leaves(jax.device_get(seq_ts.params))
    for k in got:
        np.testing.assert_allclose(
            got[k], want[k], rtol=5e-4, atol=2e-5, err_msg=k
        )


def test_collective_pp_drain_is_exact_noop():
    """All-zero weights: state passes through bit-identical and the
    version does not advance (drain-mode dummy steps)."""
    opt = optax.sgd(0.05)
    batches = _batches(1, seed=3)
    mesh, model, specs = _pp_setup(opt, batches[0][0])
    row_axes = tuple(mesh.axis_names)
    ts_host = _init_state(model, batches[0][0], opt)
    ts = place_from_host_specs(mesh, ts_host, specs)
    step = make_elastic_train_step(
        model, zoo.loss, opt, mesh, state_specs=specs
    )
    zeros = jax.device_put(
        np.zeros(8, np.float32), NamedSharding(mesh, P(row_axes))
    )
    ep = jax.device_put(
        np.zeros(8, np.int32), NamedSharding(mesh, P(row_axes))
    )
    with mesh:
        ts2, _, n, _ = step(
            ts,
            _put_rows(mesh, batches[0][0], row_axes),
            _put_rows(mesh, batches[0][1], row_axes),
            zeros,
            ep,
            jax.random.PRNGKey(1),
        )
    assert int(n) == 0
    assert int(host_copy(ts2.version)) == int(host_copy(ts.version))
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(ts2.params)),
        jax.tree_util.tree_leaves(jax.device_get(ts.params)),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_build_world_mesh_layouts():
    mesh = build_world_mesh(None)
    assert mesh.axis_names == ("data",)
    mesh = build_world_mesh(lambda n: {"data": n // 2, "pipe": 2})
    assert mesh.axis_names == ("data", "pipe")
    assert mesh.shape["pipe"] == 2
    with pytest.raises(ValueError):
        build_world_mesh(lambda n: {"data": 3, "pipe": 3})
