"""Service unit tests.

Parity: reference tests/servicer_test.py, checkpoint_test.py,
evaluation_service_test.py, staleness_aware_test.py, tensor/dtype and
model_utils units.
"""

import numpy as np
import optax
import pytest

from elasticdl_tpu.common.constants import GetModelMethod, TaskType
from elasticdl_tpu.common.model_utils import (
    get_dict_from_params_str,
    get_module_file_path,
    load_from_checkpoint_file,
    save_checkpoint_to_file,
)
from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.master.checkpoint_service import CheckpointService
from elasticdl_tpu.master.evaluation_service import (
    EvaluationService,
    _EvaluationJob,
)
from elasticdl_tpu.master.learning_rate_modulator import (
    add_lr_modulation_to_optimizer,
)
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher


def _dispatcher(records=64, rpt=16, epochs=1):
    return TaskDispatcher({"f": (0, records)}, {}, {}, rpt, epochs)


# -- master servicer (reference servicer_test.py) ---------------------------


def test_get_task_and_wait_semantics():
    m = MasterServicer(1, 8, optax.sgd(0.1), _dispatcher(records=16))
    t1 = m.get_task(1)
    assert t1.shard_name == "f" and t1.minibatch_size == 8
    # drain: one task total; next get returns no-task but doing nonempty
    t2 = m.get_task(1)
    assert not t2.shard_name and t2.type == TaskType.WAIT
    m.report_task_result(t1.task_id)
    t3 = m.get_task(1)
    assert not t3.shard_name and t3.type is None


def test_report_gradient_validation():
    m = MasterServicer(1, 8, optax.sgd(0.1), _dispatcher(), use_async=True)
    m.report_variable({"w": np.ones((2, 3), np.float32)})
    with pytest.raises(ValueError):
        m.report_gradient([Tensor("bogus", np.ones((2, 3)))], 0)
    with pytest.raises(ValueError):
        m.report_gradient([Tensor("w", np.ones((3, 3)))], 0)
    with pytest.raises(ValueError):
        # indexed grad with out-of-range row
        m.report_gradient(
            [Tensor("w", np.ones((1, 3), np.float32), indices=[5])], 0
        )
    accepted, version = m.report_gradient(
        [Tensor("w", np.full((2, 3), 0.1, np.float32))], 0
    )
    assert accepted and version == 1


def test_sync_rejects_stale_version():
    m = MasterServicer(1, 8, optax.sgd(0.1), _dispatcher())
    m.report_variable({"w": np.ones((2,), np.float32)})
    m.report_gradient([Tensor("w", np.ones((2,), np.float32))], 0)
    accepted, version = m.report_gradient(
        [Tensor("w", np.ones((2,), np.float32))], 0
    )
    assert not accepted and version == 1
    with pytest.raises(ValueError):
        m.get_model(99, GetModelMethod.MINIMUM)


def test_indexed_grad_scatter_adds_duplicates():
    m = MasterServicer(1, 8, optax.sgd(1.0), _dispatcher(), use_async=True)
    m.report_variable({"emb": np.zeros((4, 2), np.float32)})
    m.report_gradient(
        [
            Tensor(
                "emb",
                np.ones((3, 2), np.float32),
                indices=[1, 1, 3],
            )
        ],
        0,
    )
    _, named = m.get_model(1)
    np.testing.assert_array_equal(named["emb"][1], [-2.0, -2.0])
    np.testing.assert_array_equal(named["emb"][3], [-1.0, -1.0])
    np.testing.assert_array_equal(named["emb"][0], [0.0, 0.0])


# -- checkpoint service (reference checkpoint_test.py) ----------------------


def test_checkpoint_ring_retention(tmp_path):
    svc = CheckpointService(str(tmp_path), 1, 3, False)
    for v in range(5):
        svc.save(v, {"w": np.full((2,), v, np.float32)}, False)
    assert svc.get_latest_checkpoint_version() == 4
    assert svc.get_checkpoint_path(0) == ""  # evicted
    assert svc.get_checkpoint_path(2) != ""
    version, named = svc.get_checkpoint_model(3)
    assert version == 3
    np.testing.assert_array_equal(named["w"], 3.0)


def test_checkpoint_file_roundtrip(tmp_path):
    path = str(tmp_path / "m.chkpt")
    arrays = {
        "a": np.arange(6, dtype=np.float32).reshape(2, 3),
        "b": np.arange(4, dtype=np.int64),
    }
    save_checkpoint_to_file(arrays, 17, path)
    version, named = load_from_checkpoint_file(path)
    assert version == 17
    for k in arrays:
        np.testing.assert_array_equal(named[k], arrays[k])


def test_init_from_checkpoint(tmp_path):
    path = str(tmp_path / "m.chkpt")
    save_checkpoint_to_file({"w": np.full((2,), 7, np.float32)}, 11, path)
    m = MasterServicer(
        1,
        8,
        optax.sgd(0.1),
        _dispatcher(),
        checkpoint_filename_for_init=path,
    )
    assert m.get_model_version() == 11
    _, named = m.get_model(11)
    np.testing.assert_array_equal(named["w"], 7.0)


# -- evaluation service (reference evaluation_service_test.py) --------------


def test_evaluation_job_single_and_multi_output():
    job = _EvaluationJob(
        {"accuracy": lambda labels, p: labels.reshape(-1) == p.argmax(1)},
        model_version=3,
        total_tasks=2,
    )
    outputs = {"output": np.eye(4, dtype=np.float32)}
    labels = np.arange(4)
    assert job.report_evaluation_metrics(3, outputs, labels)
    assert not job.report_evaluation_metrics(2, outputs, labels)
    job.complete_task()
    assert not job.finished()
    job.complete_task()
    assert job.finished()
    assert job.get_evaluation_summary()["accuracy"] == 1.0


def test_eval_service_pins_checkpoint_version(tmp_path):
    task_d = TaskDispatcher({"f": (0, 8)}, {"f": (0, 8)}, {}, 8, 1)
    ckpt = CheckpointService(str(tmp_path), 0, 0, True)
    svc = EvaluationService(
        ckpt,
        None,
        task_d,
        0,
        0,
        1,
        False,
        lambda: {"acc": lambda labels, p: labels == labels},
    )
    task_d.set_evaluation_service(svc)
    m = MasterServicer(
        1,
        8,
        optax.sgd(0.1),
        task_d,
        checkpoint_service=ckpt,
        evaluation_service=svc,
        use_async=True,
    )
    m.report_variable({"w": np.zeros((2,), np.float32)})
    m.report_gradient([Tensor("w", np.ones((2,), np.float32))], 0)
    # version 1 was checkpointed for the eval round
    tid, task = task_d.get_eval_task(1)
    assert task.model_version == 1
    version, named = m.get_model(1, GetModelMethod.FIXED)
    assert version == 1 and "w" in named


# -- staleness-aware LR (reference staleness_aware_test.py) -----------------


def test_lr_modulation_scales_updates():
    opt, modulator = add_lr_modulation_to_optimizer(optax.sgd(1.0))
    params = {"w": np.ones((2,), np.float32)}
    state = opt.init(params)
    grads = {"w": np.ones((2,), np.float32)}

    modulator.set_multiplier(0.25)
    updates, state = opt.update(grads, state, params)
    np.testing.assert_allclose(np.asarray(updates["w"]), -0.25)

    modulator.set_multiplier(1.0)
    updates, _ = opt.update(grads, state, params)
    np.testing.assert_allclose(np.asarray(updates["w"]), -1.0)


def test_staleness_modulation_in_async_servicer():
    m = MasterServicer(
        1,
        8,
        optax.sgd(1.0),
        _dispatcher(),
        use_async=True,
        lr_staleness_modulation=True,
    )
    m.report_variable({"w": np.zeros((2,), np.float32)})
    m.report_gradient([Tensor("w", np.ones((2,), np.float32))], 0)  # v0->1
    m.report_gradient([Tensor("w", np.ones((2,), np.float32))], 1)  # fresh
    _, named = m.get_model(2)
    np.testing.assert_allclose(named["w"], -2.0)
    # stale by 2: multiplier 1/2
    m.report_gradient([Tensor("w", np.ones((2,), np.float32))], 0)
    _, named = m.get_model(3)
    np.testing.assert_allclose(named["w"], -2.5)


# -- misc utils -------------------------------------------------------------


def test_params_str_and_module_path():
    assert get_dict_from_params_str("a=1,b='x',c=2.5") == {
        "a": 1,
        "b": "x",
        "c": 2.5,
    }
    assert get_dict_from_params_str("") is None
    assert get_module_file_path("/zoo", "pkg.mod.custom_model") == (
        "/zoo/pkg/mod.py"
    )
