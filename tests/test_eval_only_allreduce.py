"""Serving-only jobs (pure eval / pure predict) on the allreduce plane.

The reference serves train/eval/predict from one worker loop
(reference worker/worker.py:866-876). The elastic allreduce worker now
serves both pure modes too: no collective, no world membership — tasks
drain against params loaded from a sharded checkpoint directory or an
exported model file, scored with host-twin forwards over local devices.
"""

import threading

import jax
import numpy as np
import pytest

from elasticdl_tpu.common.args import parse_master_args
from elasticdl_tpu.common.constants import JobType
from elasticdl_tpu.master.master import Master
from elasticdl_tpu.worker.elastic_allreduce_worker import (
    ElasticAllReduceWorker,
)
from tests.test_utils import MODEL_ZOO_PATH, DatasetName, create_recordio_file

MODEL_DEF = "mnist_subclass.mnist_subclass.CustomModel"


def _trained_params():
    from elasticdl_tpu.common.model_utils import (
        get_model_spec,
    )
    from elasticdl_tpu.nn.model_api import init_variables, split_variables

    spec = get_model_spec(
        model_zoo=MODEL_ZOO_PATH,
        model_def=MODEL_DEF,
        model_params="",
        dataset_fn="dataset_fn",
        loss="loss",
        optimizer="optimizer",
        eval_metrics_fn="eval_metrics_fn",
    )
    variables = init_variables(
        spec.model,
        jax.random.PRNGKey(3),
        {"image": np.zeros((1, 28, 28), np.float32)},
    )
    return split_variables(variables)


def _eval_only_master(val_dir, extra=()):
    args = parse_master_args(
        [
            "--job_name",
            "eval-only-test",
            "--model_zoo",
            MODEL_ZOO_PATH,
            "--model_def",
            MODEL_DEF,
            "--minibatch_size",
            "16",
            "--num_minibatches_per_task",
            "2",
            "--num_epochs",
            "1",
            "--training_data",
            "",
            "--validation_data",
            str(val_dir),
            "--num_workers",
            "1",
            "--num_ps_pods",
            "0",
            "--port",
            "0",
            "--distribution_strategy",
            "AllreduceStrategy",
        ]
        + list(extra)
    )
    master = Master(args)
    assert master.job_type == JobType.EVALUATION_ONLY
    return master


def _run_eval_only(master, worker_kwargs):
    published = []
    orig = master.evaluation_service._publish_summary

    def capture(round_):
        published.append(round_.get_evaluation_summary())
        return orig(round_)

    master.evaluation_service._publish_summary = capture
    master.evaluation_service.start()
    worker = ElasticAllReduceWorker(
        worker_id=0,
        job_type=JobType.EVALUATION_ONLY,
        minibatch_size=16,
        model_zoo=MODEL_ZOO_PATH,
        model_def=MODEL_DEF,
        stub=master.master_servicer,
        **worker_kwargs,
    )
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.2}, daemon=True
    )
    runner.start()
    worker.run()
    runner.join(timeout=60)
    assert not runner.is_alive(), "master did not finish"
    assert master.task_d.finished()
    return published


def test_eval_only_rejected_without_a_model_source(tmp_path):
    create_recordio_file(
        32, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(tmp_path)
    )
    with pytest.raises(ValueError, match="scores a saved"):
        _eval_only_master(tmp_path)


def test_eval_only_from_sharded_checkpoint(tmp_path):
    from elasticdl_tpu.common.sharded_checkpoint import save_sharded

    val_dir = tmp_path / "val"
    val_dir.mkdir()
    create_recordio_file(
        64, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(val_dir)
    )
    ckpt_dir = tmp_path / "ckpt"
    params, state = _trained_params()
    save_sharded(
        str(ckpt_dir / "ckpt_v7"),
        {"params": params, "state": state},
        version=7,
    )

    master = _eval_only_master(
        val_dir, extra=("--checkpoint_dir", str(ckpt_dir))
    )
    published = _run_eval_only(
        master, {"checkpoint_dir": str(ckpt_dir)}
    )
    assert published, "no evaluation round completed"
    assert any("accuracy" in m for m in published), published


def test_predict_only_on_allreduce_plane(tmp_path):
    """Prediction-only under AllreduceStrategy: tasks stream through the
    dataset machinery, forward runs on checkpoint-loaded params, outputs
    reach the zoo's processor — no collective anywhere."""
    from elasticdl_tpu.common.model_utils import save_checkpoint_to_file
    from elasticdl_tpu.common.tensor import pytree_to_named_arrays
    from elasticdl_tpu.worker.prediction_outputs_processor import (
        BasePredictionOutputsProcessor,
    )

    records = 64
    pred_dir = tmp_path / "pred"
    pred_dir.mkdir()
    create_recordio_file(
        records, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(pred_dir)
    )
    params, _ = _trained_params()
    model_file = str(tmp_path / "model.chkpt")
    save_checkpoint_to_file(
        pytree_to_named_arrays(params), 5, model_file
    )

    args = parse_master_args(
        [
            "--job_name", "predict-only-test",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", MODEL_DEF,
            "--minibatch_size", "16",
            "--num_minibatches_per_task", "2",
            "--num_epochs", "1",
            "--training_data", "",
            "--prediction_data", str(pred_dir),
            "--num_workers", "1",
            "--num_ps_pods", "0",
            "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
            "--checkpoint_filename_for_init", model_file,
        ]
    )
    master = Master(args)
    assert master.job_type == JobType.PREDICTION_ONLY

    class CapturingProcessor(BasePredictionOutputsProcessor):
        def __init__(self):
            self.chunks = []

        def process(self, predictions, worker_id):
            self.chunks.append((worker_id, np.asarray(predictions)))

    worker = ElasticAllReduceWorker(
        worker_id=3,
        job_type=JobType.PREDICTION_ONLY,
        minibatch_size=16,
        model_zoo=MODEL_ZOO_PATH,
        model_def=MODEL_DEF,
        stub=master.master_servicer,
        checkpoint_filename_for_init=model_file,
    )
    processor = CapturingProcessor()
    worker._prediction_outputs_processor = processor
    runner = threading.Thread(
        target=master.run, kwargs={"poll_secs": 0.2}, daemon=True
    )
    runner.start()
    worker.run()
    runner.join(timeout=60)
    assert not runner.is_alive(), "master did not finish"
    assert master.task_d.finished()
    total = sum(chunk.shape[0] for _, chunk in processor.chunks)
    assert total == records
    for worker_id, chunk in processor.chunks:
        assert worker_id == 3
        assert chunk.shape[1:] == (10,)
        assert np.isfinite(chunk).all()


def test_predict_only_rejected_without_a_model_source(tmp_path):
    create_recordio_file(
        32, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(tmp_path)
    )
    args = parse_master_args(
        [
            "--job_name", "p", "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", MODEL_DEF, "--minibatch_size", "16",
            "--num_epochs", "1", "--training_data", "",
            "--prediction_data", str(tmp_path), "--num_workers", "1",
            "--num_ps_pods", "0", "--port", "0",
            "--distribution_strategy", "AllreduceStrategy",
        ]
    )
    with pytest.raises(ValueError, match="scores a saved"):
        Master(args)


def test_eval_only_from_exported_model_file(tmp_path):
    from elasticdl_tpu.common.model_utils import save_checkpoint_to_file
    from elasticdl_tpu.common.tensor import pytree_to_named_arrays

    val_dir = tmp_path / "val"
    val_dir.mkdir()
    create_recordio_file(
        64, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(val_dir)
    )
    params, _ = _trained_params()
    model_file = str(tmp_path / "model.chkpt")
    save_checkpoint_to_file(
        pytree_to_named_arrays(params), 11, model_file
    )

    master = _eval_only_master(
        val_dir, extra=("--checkpoint_filename_for_init", model_file)
    )
    published = _run_eval_only(
        master, {"checkpoint_filename_for_init": model_file}
    )
    assert published, "no evaluation round completed"
    assert any("accuracy" in m for m in published), published
