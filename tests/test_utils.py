"""Shared test fixtures.

Parity: reference tests/test_utils.py — the EDLR fixture generator for 4
dataset schemas (:54-124) and ``distributed_train_and_evaluate`` (:127-269),
which runs a *full* distributed train/eval job in one process against the
in-process master stub and returns the final model version.
"""

import os
import tempfile

import numpy as np

from elasticdl_tpu.common.constants import JobType, TaskType
from elasticdl_tpu.common.model_utils import (
    get_module_file_path,
    load_module,
)
from elasticdl_tpu.data.example import encode_example
from elasticdl_tpu.data.recordio import RecordIOWriter
from elasticdl_tpu.master.checkpoint_service import CheckpointService
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
from elasticdl_tpu.worker.worker import Worker
from tests.in_process_master import InProcessMaster

MODEL_ZOO_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "model_zoo"
)


class PserverArgs:
    """Stub args object for parameter-server tests (reference :25-44)."""

    def __init__(
        self,
        grads_to_wait=8,
        lr_staleness_modulation=0,
        use_async=False,
        model_zoo=None,
        model_def=None,
        optimizer="optimizer",
        port=9999,
        log_level="INFO",
    ):
        self.grads_to_wait = grads_to_wait
        self.lr_staleness_modulation = lr_staleness_modulation
        self.use_async = use_async
        self.model_zoo = model_zoo
        self.model_def = model_def
        self.optimizer = optimizer
        self.port = port
        self.log_level = log_level


class DatasetName:
    IMAGENET = "imagenet1"
    FRAPPE = "frappe1"
    TEST_MODULE = "test_module1"
    IMAGE_DEFAULT = "image_default1"


def create_recordio_file(size, dataset_name, shape, temp_dir=None, seed=None):
    """Write ``size`` synthetic examples of a schema to an EDLR file."""
    rng = np.random.default_rng(seed)
    temp_file = tempfile.NamedTemporaryFile(delete=False, dir=temp_dir)
    with RecordIOWriter(temp_file.name) as f:
        for _ in range(size):
            if dataset_name == DatasetName.IMAGENET:
                # raw uint8 image instead of a JPEG payload: the TPU input
                # pipeline feeds decoded arrays
                example = {
                    "image": rng.integers(
                        255, size=shape, dtype=np.int64
                    ).astype(np.uint8),
                    "label": np.array(
                        [rng.integers(1, 11)], dtype=np.int64
                    ),
                }
            elif dataset_name == DatasetName.FRAPPE:
                example = {
                    "feature": rng.integers(
                        5383, size=(shape,), dtype=np.int64
                    ),
                    "label": np.array(
                        [rng.integers(2)], dtype=np.int64
                    ),
                }
            elif dataset_name == DatasetName.TEST_MODULE:
                x = rng.random(shape, dtype=np.float32)
                example = {"x": x, "y": 2 * x + 1}
            elif dataset_name == DatasetName.IMAGE_DEFAULT:
                example = {
                    "image": rng.random(
                        int(np.prod(shape)), dtype=np.float32
                    )
                    * 255.0,
                    "label": np.array(
                        [rng.integers(0, 10)], dtype=np.int64
                    ),
                }
            else:
                raise ValueError("Unknown dataset name %s." % dataset_name)
            f.write(encode_example(example))
    return temp_file.name


def distributed_train_and_evaluate(
    feature_shape,
    model_zoo_path,
    model_def,
    model_params="",
    eval_metrics_fn="eval_metrics_fn",
    training=True,
    dataset_name=DatasetName.IMAGE_DEFAULT,
    callback_classes=(),
    use_async=False,
    get_model_steps=1,
):
    """Run a full train/eval job in-process; returns the final version."""
    job_type = (
        JobType.TRAINING_WITH_EVALUATION
        if training
        else JobType.EVALUATION_ONLY
    )
    batch_size = 8 if dataset_name == DatasetName.IMAGENET else 16
    worker = Worker(
        worker_id=1,
        job_type=job_type,
        minibatch_size=batch_size,
        model_zoo=model_zoo_path,
        model_def=model_def,
        model_params=model_params,
        eval_metrics_fn=eval_metrics_fn,
        get_model_steps=get_model_steps,
    )

    if dataset_name in [DatasetName.IMAGENET, DatasetName.FRAPPE]:
        record_num = batch_size
    else:
        record_num = 128
    shards = {
        create_recordio_file(record_num, dataset_name, feature_shape): (
            0,
            record_num,
        )
    }
    if training:
        training_shards = shards
        evaluation_shards = shards
    else:
        training_shards = {}
        evaluation_shards = shards
    task_d = TaskDispatcher(
        training_shards,
        evaluation_shards,
        {},
        records_per_task=64,
        num_epochs=1,
    )

    model_module = load_module(
        get_module_file_path(model_zoo_path, model_def)
    ).__dict__
    checkpoint_service = CheckpointService("", 0, 0, True)
    if training:
        evaluation_service = EvaluationService(
            checkpoint_service,
            None,
            task_d,
            0,
            0,
            1,
            False,
            model_module[eval_metrics_fn],
        )
    else:
        evaluation_service = EvaluationService(
            checkpoint_service,
            None,
            task_d,
            0,
            0,
            0,
            True,
            model_module[eval_metrics_fn],
        )
    task_d.set_evaluation_service(evaluation_service)
    grads_to_wait = 1 if use_async else 2
    master = MasterServicer(
        grads_to_wait,
        batch_size,
        worker._opt_fn(),
        task_d,
        init_var=None,
        checkpoint_filename_for_init=None,
        checkpoint_service=checkpoint_service,
        evaluation_service=evaluation_service,
        use_async=use_async,
    )
    callbacks = [
        callback_class(master, worker) for callback_class in callback_classes
    ]
    worker._stub = InProcessMaster(master, callbacks)

    worker.run()

    task = master.get_task(1)
    if task.shard_name:
        raise RuntimeError(
            "There are some tasks unfinished after worker exits."
        )
    return master._version
