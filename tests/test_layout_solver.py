"""Layout solver (parallel/layout_solver.py): the pure dp x tp x
micro-batch planner behind elastic layout re-solve.

Everything here is host-only math — no jax import, no devices, no
mesh. That is the point: the solver runs on the establish path of
every process in a forming world, so these tests pin the properties
that keep worlds formable:

- infeasible layouts (over the per-device memory budget) never win
  while a feasible one exists, and a world with NO admissible tp
  divisor yields None rather than a bogus plan;
- determinism: the same inputs solve to the same ranking in-process,
  across fresh module state, and in a separate interpreter (the
  multi-process consensus requirement, checked the cheap way);
- tie-breaks are stable and documented (lower tp, then higher dp,
  then larger micro-batch);
- the telemetry-fed scoring regime agrees with the static regime on
  ORDERING when telemetry carries no per-component breakdown (a
  uniform rescale must not flip a comparison).
"""

import subprocess
import sys

import pytest

from elasticdl_tpu.parallel import layout_solver as ls
from elasticdl_tpu.parallel.layout_solver import (
    Layout,
    LayoutPlanner,
    ModelProfile,
    StepTelemetry,
    mesh_axes_for,
)

# A transformer-ish profile: some replicated state, a model-sharded
# majority, admissible tp degrees 1/2/4 (e.g. 4 attention heads).
PROFILE = ModelProfile(
    replicated_bytes=1.0e6,
    tp_bytes=8.0e6,
    activation_bytes_per_row=4.0e3,
    flops_per_row=2.0e8,
    tp_degrees=(1, 2, 4),
)


def _ranking(n=8, **kw):
    return [
        (s.layout.dp, s.layout.tp, s.layout.microbatch, s.feasible)
        for s in ls.solve(n, PROFILE, **kw)
    ]


# ---------------------------------------------------------------- shape


def test_enumerate_covers_divisor_degrees_only():
    layouts = ls.enumerate_layouts(8, PROFILE, microbatches=(4,))
    assert {(l.dp, l.tp) for l in layouts} == {(8, 1), (4, 2), (2, 4)}
    assert all(l.n_devices == 8 for l in layouts)
    # tp=4 does not divide a 6-world; tp=2 does
    layouts6 = ls.enumerate_layouts(6, PROFILE, microbatches=(4,))
    assert {(l.dp, l.tp) for l in layouts6} == {(6, 1), (3, 2)}


def test_mesh_axes_keep_model_axis_at_tp1():
    # tp=1 still emits the model axis: the pjit plane (and direct
    # relayout) must stay active across a dp8xtp1 layout
    assert mesh_axes_for(Layout(8, 1, 4)) == {"data": 8, "model": 1}
    assert list(mesh_axes_for(Layout(4, 2, 4))) == ["data", "model"]


def test_no_admissible_layout_returns_none():
    narrow = ModelProfile(1.0, 1.0, 1.0, 1.0, tp_degrees=(1,))
    # a 0-device world has no layouts at all
    assert ls.best(0, narrow) is None
    assert ls.solve(0, narrow) == []


# ------------------------------------------------------- infeasibility


def test_infeasible_layouts_never_beat_feasible_ones():
    # budget admits tp=4 (replicated + tp/4 + small activations) but
    # not tp=1 (full tp_bytes resident per device)
    budget = (
        PROFILE.replicated_bytes
        + PROFILE.tp_bytes / 4
        + PROFILE.activation_bytes_per_row * 8
    )
    ranked = ls.solve(8, PROFILE, memory_budget=budget)
    feas = [s.feasible for s in ranked]
    # feasible block strictly precedes the infeasible tail, tail kept
    assert True in feas and False in feas
    assert feas.index(False) == feas.count(True)
    win = ls.best(8, PROFILE, memory_budget=budget)
    assert win.feasible
    assert ls.device_bytes(win.layout, PROFILE) <= budget


def test_over_budget_everything_still_reports_ranked_tail():
    win = ls.best(8, PROFILE, memory_budget=1.0)
    # nothing fits; best() still reports the least-bad candidate so
    # the caller can say WHY, flagged infeasible
    assert win is not None and not win.feasible


def test_budget_env_parse(monkeypatch):
    assert ls.memory_budget_from_env({}) is None
    assert ls.memory_budget_from_env(
        {"EDL_LAYOUT_MEM_BUDGET_MB": "64"}
    ) == 64 * (1 << 20)
    assert (
        ls.memory_budget_from_env({"EDL_LAYOUT_MEM_BUDGET_MB": "junk"})
        is None
    )
    assert (
        ls.memory_budget_from_env({"EDL_LAYOUT_MEM_BUDGET_MB": "-3"})
        is None
    )


# -------------------------------------------------------- determinism


def test_solve_is_deterministic_in_process():
    assert _ranking() == _ranking()
    budget = 2.5e6
    assert _ranking(memory_budget=budget) == _ranking(
        memory_budget=budget
    )


def test_solve_is_deterministic_across_interpreters():
    # the consensus requirement: a fresh interpreter (stand-in for a
    # different worker process / a just-restarted joiner) must produce
    # the identical ranking from the identical inputs
    code = (
        "from elasticdl_tpu.parallel import layout_solver as ls\n"
        "p = ls.ModelProfile(1.0e6, 8.0e6, 4.0e3, 2.0e8,"
        " tp_degrees=(1, 2, 4))\n"
        "print([(s.layout.dp, s.layout.tp, s.layout.microbatch,"
        " s.feasible) for s in ls.solve(8, p, memory_budget=2.5e6)])\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        check=True,
    )
    assert eval(out.stdout.strip()) == _ranking(memory_budget=2.5e6)


def test_tie_break_stability():
    # a profile where compute dominates and comm is free: every
    # layout of a given micro-batch scores identically, so the rank
    # must fall through to the documented tie-break — lower tp first,
    # then higher dp, then larger micro-batch
    flat = ModelProfile(
        replicated_bytes=0.0,
        tp_bytes=0.0,
        activation_bytes_per_row=0.0,
        flops_per_row=1.0e9,
        tp_degrees=(1, 2, 4),
    )
    ranked = ls.solve(8, flat, microbatches=(4,))
    assert [(s.layout.tp, s.layout.dp) for s in ranked] == [
        (1, 8),
        (2, 4),
        (4, 2),
    ]
    # larger micro-batch wins a same-score tie within one (dp, tp):
    # with zero comm and overhead dominated away, per-example time is
    # flat, global throughput rises with mb, so no tie — instead pin
    # that the quantizer kills float-noise-only differences
    a = ls._quantized_score(1.0000000001)
    b = ls._quantized_score(1.0000000002)
    assert a == b


# ------------------------------------- telemetry vs static agreement


def test_uniform_telemetry_preserves_static_ordering():
    # telemetry with NO breakdown rescales every layout's step time by
    # one positive factor — ordering must match the static regime
    static = [r[:3] for r in _ranking()]
    tel = StepTelemetry(layout=Layout(4, 2, 8), step_time_s=0.125)
    fed = [
        (s.layout.dp, s.layout.tp, s.layout.microbatch)
        for s in ls.solve(8, PROFILE, telemetry=tel)
    ]
    assert fed == static


def test_breakdown_telemetry_recalibrates_components():
    # a breakdown reporting tp comm 100x costlier than the static
    # constants should demote tp-heavy layouts below the static rank
    mb = (8,)
    tel_layout = Layout(4, 2, 8)
    comp, dpc, tpc = ls._step_components(tel_layout, PROFILE)
    slow_tp = StepTelemetry(
        layout=tel_layout,
        step_time_s=comp + dpc + 100.0 * tpc + ls._STEP_OVERHEAD_S,
        compute_s=comp,
        dp_comm_s=dpc,
        tp_comm_s=100.0 * tpc,
    )
    static_eps = ls.predict_examples_per_sec(Layout(2, 4, 8), PROFILE)
    fed_eps = ls.predict_examples_per_sec(
        Layout(2, 4, 8), PROFILE, telemetry=slow_tp
    )
    assert fed_eps < static_eps
    # and the measured layout reproduces (approximately) its own
    # measurement under calibration
    own = ls.predict_examples_per_sec(
        tel_layout, PROFILE, telemetry=slow_tp
    )
    assert own == pytest.approx(
        tel_layout.dp * tel_layout.microbatch / slow_tp.step_time_s
    )
    del mb


# ------------------------------------------------------------ planner


def test_planner_falls_back_before_profile():
    calls = []

    def fallback(n):
        calls.append(n)
        return {"data": n}

    p = LayoutPlanner(fallback_axes_fn=fallback, memory_budget=None)
    assert p.axes_for(8) == {"data": 8}
    assert calls == [8]
    assert p.candidates(8) == []  # no profile -> no speculation hints
    p.set_profile(PROFILE)
    axes = p.axes_for(8)
    assert set(axes) == {"data", "model"}
    assert axes["data"] * axes["model"] == 8
    assert p.last_plan is not None


def test_planner_axes_are_telemetry_blind():
    p = LayoutPlanner(memory_budget=None)
    p.set_profile(PROFILE)
    before = p.axes_for(8)
    # telemetry claiming tp comm is free must NOT change the
    # establish-path answer (processes have divergent telemetry)
    comp, dpc, tpc = ls._step_components(Layout(2, 4, 8), PROFILE)
    p.set_telemetry(
        StepTelemetry(
            layout=Layout(2, 4, 8),
            step_time_s=comp + dpc + tpc / 1e6,
            compute_s=comp,
            dp_comm_s=dpc,
            tp_comm_s=tpc / 1e6,
        )
    )
    assert p.axes_for(8) == before


def test_planner_candidates_lead_with_deterministic_winner():
    p = LayoutPlanner(memory_budget=None)
    p.set_profile(PROFILE)
    winner = p.plan(8)
    cands = p.candidates(8, top=2)
    assert cands
    assert (cands[0].dp, cands[0].tp) == (
        winner.layout.dp,
        winner.layout.tp,
    )
    # distinct (dp, tp) pairs only
    pairs = [(c.dp, c.tp) for c in cands]
    assert len(pairs) == len(set(pairs))
