"""End-to-end in-process distributed training tests.

Parity: reference tests/worker_test.py + example_test.py — full train/eval
jobs against the in-process master, gradient-rejection retry, SSP local
updates, and the sync/async version invariant (async final version is 2x
the sync version for grads_to_wait=2 over identical data,
example_test.py:63-65).
"""

from tests.test_callbacks import CheckRetryCallback, CheckWorkerModelCallback
from tests.test_utils import (
    MODEL_ZOO_PATH,
    DatasetName,
    distributed_train_and_evaluate,
)

MNIST_MODEL_DEF = "mnist_functional_api.mnist_functional_api.custom_model"


def test_distributed_train_tf_example():
    version = distributed_train_and_evaluate(
        (28, 28),
        MODEL_ZOO_PATH,
        MNIST_MODEL_DEF,
        training=True,
    )
    # 128 records / batch 16 = 8 reports; sync applies every 2 -> 4 versions
    assert version == 4


def test_distributed_evaluate_tf_example():
    version = distributed_train_and_evaluate(
        (28, 28),
        MODEL_ZOO_PATH,
        MNIST_MODEL_DEF,
        training=False,
    )
    assert version == 0


def test_async_versions_double_sync():
    sync_version = distributed_train_and_evaluate(
        (28, 28),
        MODEL_ZOO_PATH,
        MNIST_MODEL_DEF,
        training=True,
        use_async=False,
    )
    async_version = distributed_train_and_evaluate(
        (28, 28),
        MODEL_ZOO_PATH,
        MNIST_MODEL_DEF,
        training=True,
        use_async=True,
    )
    assert async_version == 2 * sync_version


def test_worker_gradient_retry():
    version = distributed_train_and_evaluate(
        (28, 28),
        MODEL_ZOO_PATH,
        MNIST_MODEL_DEF,
        training=True,
        callback_classes=[CheckRetryCallback],
    )
    # the injected version bump adds one phantom version
    assert version >= 4


def test_worker_model_sync_with_master():
    distributed_train_and_evaluate(
        (28, 28),
        MODEL_ZOO_PATH,
        MNIST_MODEL_DEF,
        training=True,
        callback_classes=[CheckWorkerModelCallback],
    )


def test_ssp_local_updates():
    version = distributed_train_and_evaluate(
        (28, 28),
        MODEL_ZOO_PATH,
        MNIST_MODEL_DEF,
        training=True,
        use_async=True,
        get_model_steps=2,
    )
    assert version == 8
