"""Tiered embedding store (ps/tiered_store.py, docs/tiered_store.md).

Three layers of contract:

- **value transparency**: a tiered table is bitwise-indistinguishable
  from the untiered table it wraps — lazy init, overwrite, snapshot
  cuts — no matter how rows shuffle between warm and disk, on both the
  host dict store and the ``--ps_device`` arena;
- **crash consistency**: a spill segment IS a PR-10 snapshot shard, so
  a torn/manifest-less segment is invisible (the previous generation
  serves), and a demotion killed between manifest-seal and index-flip
  never loses a row (it lives in warm until the flip);
- **signals**: the delta-log ``note_applied`` pin ring and read pins
  block eviction of hot rows; the HotRowCache per-table counters feed
  the admission telemetry; the servicer aggregates tier counters into
  ``ps_status``.

Most tests stop the background demoter (``close()``) and drive
``_demote_once()`` directly so every spill is deterministic; the
thread-driven path is exercised through the Parameters/servicer
integration test.
"""

import collections
import os
import time

import numpy as np
import optax
import pytest

from elasticdl_tpu.common.tensor import Tensor
from elasticdl_tpu.nn.comm_plane import HotRowCache
from elasticdl_tpu.ps.embedding_table import EmbeddingTable
from elasticdl_tpu.ps.parameters import EmbeddingTableInfo, Parameters
from elasticdl_tpu.ps.servicer import PserverServicer
from elasticdl_tpu.ps.snapshot import (
    snapshot_versions,
    write_shard_snapshot,
)
from elasticdl_tpu.ps.tiered_store import TieredEmbeddingTable

DIM = 4


def _tiered(tmp_path, warm_rows=8, name="emb", init="zeros", inner=None,
            background=False):
    if inner is None:
        inner = EmbeddingTable(name, DIM, init)
    t = TieredEmbeddingTable(
        inner, os.path.join(str(tmp_path), "spill-" + name), warm_rows
    )
    if not background:
        t.close()  # tests drive _demote_once() deterministically
    return t


def _rows_for(ids, base=0.0):
    ids = np.asarray(ids, dtype=np.int64).reshape(-1)
    return (
        ids.astype(np.float32)[:, None] + np.float32(base)
    ) * np.ones((1, DIM), np.float32)


def _fill(t, n, base=0.0):
    ids = np.arange(n, dtype=np.int64)
    rows = _rows_for(ids, base)
    t.set(ids, rows)
    return ids, rows


def _drain(t):
    while t._demote_once():
        pass


# ---------------------------------------------------------------------------
# value transparency
# ---------------------------------------------------------------------------


def test_spill_then_cold_pull_roundtrip(tmp_path):
    t = _tiered(tmp_path, warm_rows=8)
    ids, rows = _fill(t, 32)
    _drain(t)
    s = t.stats()
    assert s["spilled_rows"] > 0 and s["spill_segments"] > 0
    assert t.warm_len() <= 8
    assert len(t) == 32  # logical size counts both tiers
    # warm and disk are disjoint
    warm = set(t._inner.embedding_vectors)
    assert not warm & set(t._disk)
    assert set(t._ticks) == warm
    # a full pull promotes the cold rows back, values intact
    np.testing.assert_array_equal(t.get(ids), rows)
    s = t.stats()
    assert s["cold_pull_rows"] > 0 and s["cold_pull_segments"] > 0
    assert s["promoted_rows"] > 0
    assert t.stats()["disk_rows"] == 0


def test_cold_pulls_are_batched_per_segment(tmp_path):
    t = _tiered(tmp_path, warm_rows=4)
    _fill(t, 16)
    _drain(t)
    segments = t.stats()["spill_segments"]
    assert segments >= 1
    cold = sorted(t._disk)
    t.get(np.asarray(cold, dtype=np.int64))
    s = t.stats()
    # one segment OPEN per cold cluster, never one per row
    assert s["cold_pull_segments"] <= segments
    assert s["cold_pull_rows"] == len(cold)


def test_values_match_untiered_table(tmp_path):
    t = _tiered(tmp_path, warm_rows=4, init="uniform")
    plain = EmbeddingTable("emb", DIM, "uniform")
    batches = [
        [1, 2, 3],
        [10, 11, 12, 13, 14],
        [1, 50, 60, 2],
        [70, 80, 90, 11, 3],
        [5, 6, 7, 8, 9, 10],
    ]
    for batch in batches:
        np.testing.assert_array_equal(t.get(batch), plain.get(batch))
        _drain(t)
    update = _rows_for([2, 60, 90], base=0.5)
    t.set([2, 60, 90], update)
    plain.set([2, 60, 90], update)
    _drain(t)
    sids, srows = t.snapshot()
    pids, prows = plain.snapshot()
    so, po = np.argsort(sids), np.argsort(pids)
    np.testing.assert_array_equal(sids[so], pids[po])
    np.testing.assert_array_equal(srows[so], prows[po])


def test_warm_write_supersedes_disk_copy(tmp_path):
    t = _tiered(tmp_path, warm_rows=2)
    _fill(t, 6)
    _drain(t)
    cold = sorted(t._disk)
    assert cold
    i = cold[0]
    new = np.full((1, DIM), 55.0, np.float32)
    t.set([i], new)
    assert i not in t._disk  # unindexed in the same hold as the write
    np.testing.assert_array_equal(t.get([i]), new)


# ---------------------------------------------------------------------------
# snapshot round-trips across tier configurations
# ---------------------------------------------------------------------------


def test_snapshot_roundtrip_tiered_to_plain(tmp_path):
    t = _tiered(tmp_path, warm_rows=4)
    ids, rows = _fill(t, 16)
    _drain(t)
    assert t.stats()["disk_rows"] > 0
    sids, srows = t.snapshot()
    assert len(sids) == 16
    plain = EmbeddingTable("emb", DIM, "zeros")
    plain.load_snapshot(sids, srows)
    np.testing.assert_array_equal(plain.get(list(ids)), rows)


def test_snapshot_roundtrip_plain_to_tiered(tmp_path):
    plain = EmbeddingTable("emb", DIM, "zeros")
    ids = np.arange(16, dtype=np.int64)
    rows = _rows_for(ids, base=7.0)
    plain.set(ids, rows)

    t = _tiered(tmp_path, warm_rows=4)
    _fill(t, 6, base=100.0)  # pre-restore junk, some of it spilled
    _drain(t)
    spill_dir = t._dir
    assert snapshot_versions(spill_dir)

    t.load_snapshot(*plain.snapshot())
    # the snapshot supersedes the disk tier entirely
    assert t.stats()["disk_rows"] == 0
    assert not snapshot_versions(spill_dir)
    np.testing.assert_array_equal(t.get(ids), rows)
    # the demoter re-spills overflow afterwards, values unchanged
    _drain(t)
    assert t.stats()["disk_rows"] > 0
    np.testing.assert_array_equal(t.get(ids), rows)


# ---------------------------------------------------------------------------
# crash consistency (the PR-10 segment format doing double duty)
# ---------------------------------------------------------------------------


def test_reattach_serves_spilled_rows_newest_generation_wins(tmp_path):
    t = _tiered(tmp_path, warm_rows=2, name="emb")
    ids, _ = _fill(t, 4)
    _drain(t)
    # promote everything, overwrite, spill again -> a NEWER generation
    # holds the current values; stale generations linger on disk
    t.get(ids)
    rows_v2 = _rows_for(ids, base=100.0)
    t.set(ids, rows_v2)
    _drain(t)
    assert len(snapshot_versions(t._dir)) >= 2
    # the warm tier is volatile: only rows cold at "crash" time have
    # their CURRENT value on disk (a row still warm here may resolve
    # to its older generation after re-attach, and that is correct)
    cold_now = dict(t._disk)
    assert cold_now

    t2 = _tiered(tmp_path, warm_rows=2, name="emb")
    # index agrees before any promoting get: same id -> same (newest)
    # generation the live table had it in
    for i, gen in cold_now.items():
        assert t2._disk[i] == gen
    cold = sorted(cold_now)
    np.testing.assert_array_equal(
        t2.get(cold), _rows_for(cold, base=100.0)
    )


def test_torn_and_manifestless_segments_previous_generation_serves(
    tmp_path,
):
    t = _tiered(tmp_path, warm_rows=2, name="emb")
    ids, _ = _fill(t, 4)
    _drain(t)
    gen1 = snapshot_versions(t._dir)
    assert gen1
    t.get(ids)
    t.set(ids, _rows_for(ids, base=100.0))
    _drain(t)
    gens = snapshot_versions(t._dir)
    newest = max(gens)
    assert newest > max(gen1)

    # a torn mid-write temp dir (crash before the atomic rename)
    torn = os.path.join(t._dir, "tmp-snap_v%d.123" % (newest + 1))
    os.makedirs(torn)
    with open(os.path.join(torn, "tables.npz"), "wb") as f:
        f.write(b"torn bytes")
    # ... and strip the NEWEST sealed generation's manifest: an
    # unpublished segment must be invisible to re-attach
    os.remove(
        os.path.join(t._dir, "snap_v%d" % newest, "manifest.json")
    )

    t2 = _tiered(tmp_path, warm_rows=2, name="emb")
    cold = sorted(t2._disk)
    assert cold
    assert all(gen < newest for gen in t2._disk.values())
    # the previous generation's (pre-overwrite) values serve
    np.testing.assert_array_equal(t2.get(cold), _rows_for(cold))


def test_crash_between_seal_and_index_keeps_row_warm(tmp_path):
    """A demoter killed after phase 2 (segment sealed) but before
    phase 3 (index flip): the victim is still warm, the sealed segment
    is unindexed — reads and snapshots never see the stale copy."""
    t = _tiered(tmp_path, warm_rows=8, name="emb")
    ids, rows = _fill(t, 4)
    stale = {
        "version": 50,
        "initialized": True,
        "dense": {},
        "tables": {
            "emb": {
                "ids": np.array([0], dtype=np.int64),
                "rows": np.full((1, DIM), 123.0, np.float32),
                "dim": DIM,
                "initializer": "zeros",
                "is_slot": False,
            }
        },
    }
    write_shard_snapshot(t._dir, stale)
    np.testing.assert_array_equal(t.get([0]), rows[:1])
    sids, srows = t.snapshot()
    assert int((sids == 0).sum()) == 1
    np.testing.assert_array_equal(srows[sids == 0], rows[:1])


def test_failed_segment_write_keeps_rows_warm(tmp_path, monkeypatch):
    import elasticdl_tpu.ps.tiered_store as ts

    t = _tiered(tmp_path, warm_rows=2)
    ids, rows = _fill(t, 6)

    def boom(*a, **kw):
        raise OSError("disk full")

    monkeypatch.setattr(ts, "write_shard_snapshot", boom)
    assert t._demote_once() == 0
    assert t.warm_len() == 6
    assert t.stats()["disk_rows"] == 0
    np.testing.assert_array_equal(t.get(ids), rows)


def test_row_touched_mid_spill_stays_warm(tmp_path, monkeypatch):
    """Phase 3 verifies ticks: a victim written to between capture and
    seal keeps its warm row; the segment's stale copy is never
    indexed."""
    import elasticdl_tpu.ps.tiered_store as ts

    t = _tiered(tmp_path, warm_rows=2)
    _fill(t, 6)
    real = ts.write_shard_snapshot
    hit = {}

    def touching_write(directory, state, **kw):
        seg = next(iter(state["tables"].values()))
        victim = int(np.asarray(seg["ids"]).reshape(-1)[0])
        hit["victim"] = victim
        # phase 2 holds no lock, so this concurrent write is legal
        t.set([victim], np.full((1, DIM), 777.0, np.float32))
        return real(directory, state, **kw)

    monkeypatch.setattr(ts, "write_shard_snapshot", touching_write)
    t._demote_once()
    victim = hit["victim"]
    assert victim not in t._disk
    assert victim in t._inner.embedding_vectors
    np.testing.assert_array_equal(
        t.get([victim]), np.full((1, DIM), 777.0, np.float32)
    )


# ---------------------------------------------------------------------------
# eviction signals
# ---------------------------------------------------------------------------


def test_note_applied_pins_recent_rows_against_demotion(tmp_path):
    t = _tiered(tmp_path, warm_rows=2)
    _fill(t, 8)
    t.note_applied([0, 1], version=5)
    _drain(t)
    # recently-applied rows survived the spill; everything else went
    assert 0 in t._inner.embedding_vectors
    assert 1 in t._inner.embedding_vectors
    assert 0 not in t._disk and 1 not in t._disk
    assert t.stats()["disk_rows"] == 6
    # the pin ring prunes pin_versions (=2) behind the clock: after
    # version 30 only the fresh note still pins
    t.note_applied([0], version=30)
    _drain(t)
    assert 1 in t._disk
    assert 0 not in t._disk


def test_read_pins_block_eviction(tmp_path):
    t = _tiered(tmp_path, warm_rows=2)
    _fill(t, 6)
    with t._mu:
        t._pins.update([3])
    _drain(t)
    assert 3 in t._inner.embedding_vectors and 3 not in t._disk
    with t._mu:
        t._pins.subtract([3])
        t._pins += collections.Counter()
    # fresh pressure with the pin released: 3 is now the oldest victim
    t.set([100, 101], _rows_for([100, 101]))
    _drain(t)
    assert 3 in t._disk


def test_cold_note_applied_never_fabricates_a_warm_victim(tmp_path):
    """A signal-only touch of a DISK-resident id (note_applied from the
    delta log) must not plant it in the warm recency index — the
    demoter would lazy-init a fresh row and seal THAT over the real
    value in a newer generation."""
    t = _tiered(tmp_path, warm_rows=2)
    ids, rows = _fill(t, 6)
    _drain(t)
    cold = sorted(t._disk)
    assert cold
    t.note_applied(cold, version=9)
    assert not set(cold) & set(t._ticks)
    # fresh pressure on NEW ids, then spill again: the cold rows must
    # come back with their spilled values, not lazy re-inits
    t.set([100, 101], _rows_for([100, 101], base=50.0))
    _drain(t)
    np.testing.assert_array_equal(t.get(cold), _rows_for(cold))


def test_hit_rate_signal_sets_eviction_depth(tmp_path):
    t = _tiered(tmp_path, warm_rows=10)
    _fill(t, 12)
    # no pulls yet -> hit rate 1.0 -> demote below budget for headroom
    with t._mu:
        assert t._demote_target_locked() == 9
    _drain(t)
    assert t.warm_len() == 9
    # force cold pulls until the hit rate drops below the slack gate:
    # a thrashing table keeps its full budget
    t.get(sorted(t._disk))
    _drain(t)
    while True:
        s = t.stats()
        pulls = s["warm_hit_rows"] + s["cold_pull_rows"]
        if pulls and s["warm_hit_rows"] / pulls < 0.98:
            break
        cold = sorted(t._disk)
        assert cold, "expected cold rows to pull"
        t.get(cold)
        _drain(t)
    with t._mu:
        assert t._demote_target_locked() == 10


# ---------------------------------------------------------------------------
# the device arm (arena inner, virtual CPU devices from conftest)
# ---------------------------------------------------------------------------


def test_device_tiered_matches_host_table(tmp_path):
    from elasticdl_tpu.ps.device_store import DeviceEmbeddingTable

    inner = DeviceEmbeddingTable("demb", DIM, "uniform")
    t = _tiered(tmp_path, warm_rows=4, name="demb", inner=inner)
    host = EmbeddingTable("demb", DIM, "uniform")
    ids = np.arange(12, dtype=np.int64)
    np.testing.assert_array_equal(t.get(ids), host.get(list(ids)))
    _drain(t)
    assert t.stats()["disk_rows"] > 0
    assert t.warm_len() <= 4
    # cold pulls promote through the arena, bitwise-identical
    np.testing.assert_array_equal(t.get(ids), host.get(list(ids)))
    # snapshot round-trip device-tiered -> plain host table
    _drain(t)
    sids, srows = t.snapshot()
    plain = EmbeddingTable("demb", DIM, "uniform")
    plain.load_snapshot(sids, srows)
    np.testing.assert_array_equal(plain.get(list(ids)), host.get(list(ids)))


def test_device_tiered_ensure_rows_promotes_before_lazy_init(tmp_path):
    from elasticdl_tpu.ps.device_store import DeviceEmbeddingTable

    inner = DeviceEmbeddingTable("demb", DIM, "zeros")
    t = _tiered(tmp_path, warm_rows=2, name="demb", inner=inner)
    ids = np.arange(6, dtype=np.int64)
    rows = _rows_for(ids, base=3.0)
    t.set(ids, rows)
    _drain(t)
    cold = sorted(t._disk)
    assert cold
    # the jitted-apply path: ensure_rows must surface the DISK values
    # in the arena, not zero-init fresh slots
    slots = t.ensure_rows(np.asarray(cold, dtype=np.int64))
    assert len(slots) == len(cold)
    np.testing.assert_array_equal(t.get(cold), _rows_for(cold, base=3.0))


def test_device_free_list_keeps_arena_at_warm_size(tmp_path):
    from elasticdl_tpu.ps.device_store import DeviceEmbeddingTable

    inner = DeviceEmbeddingTable("demb", DIM, "zeros")
    t = _tiered(tmp_path, warm_rows=8, name="demb", inner=inner)
    for batch in range(16):
        ids = np.arange(batch * 8, batch * 8 + 8, dtype=np.int64)
        t.get(ids)
        _drain(t)
    # 128 distinct ids cycled through; without slot reuse the arena
    # would have doubled past _MIN_CAPACITY
    assert int(inner._arena.shape[0]) == 64
    assert len(t) == 128


def test_device_missing_and_evict_rows():
    from elasticdl_tpu.ps.device_store import DeviceEmbeddingTable

    d = DeviceEmbeddingTable("x", DIM, "ones")
    d.get(np.arange(10, dtype=np.int64))
    assert d.missing_ids([5, 99]) == [99]
    assert len(d) == 10  # the probe must not lazy-init
    freed = {d._slots[3], d._slots[4]}
    assert d.evict_rows([3, 4, 777]) == 2
    assert len(d) == 8
    assert set(d._free) == freed
    # a reused slot is written before any read
    got = d.get(np.asarray([100, 101], dtype=np.int64))
    np.testing.assert_array_equal(got, np.ones((2, DIM), np.float32))
    assert not d._free


def test_host_missing_and_evict_rows():
    e = EmbeddingTable("x", 3, "zeros")
    e.get([1, 2, 3])
    assert e.missing_ids([2, 9]) == [9]
    assert len(e) == 3  # the probe must not lazy-init
    assert e.evict_rows([1, 9]) == 1
    assert 1 not in e.embedding_vectors


# ---------------------------------------------------------------------------
# HotRowCache per-table counters (the top tier's admission signal)
# ---------------------------------------------------------------------------


def test_hot_row_cache_per_table_counters():
    c = HotRowCache(max_rows=2, window=1)
    c.note_version("ps0", 1)
    row = np.ones(DIM, np.float32)
    c.put("emb_a", 1, "ps0", 1, row)
    assert c.get("emb_a", 1) is not None  # hit
    assert c.get("emb_a", 2) is None  # miss
    assert c.get("emb_b", 7) is None  # miss, other table
    # capacity eviction charges the VICTIM's table
    c.put("emb_b", 8, "ps0", 1, row)
    c.put("emb_b", 9, "ps0", 1, row)  # evicts emb_a:1 (LRU)
    stats = c.table_stats()
    assert stats["emb_a"] == {"hits": 1, "misses": 1, "evictions": 1}
    assert stats["emb_b"]["misses"] == 1
    assert stats["emb_b"]["evictions"] == 0
    # the aggregate series existing readers consume stays coherent
    assert c.hits == 1 and c.misses == 2


def test_worker_telemetry_exports_labeled_cache_series():
    from elasticdl_tpu.utils import profiling
    from elasticdl_tpu.worker.telemetry import WorkerTelemetry

    cache = HotRowCache(max_rows=4, window=1)
    cache.note_version("ps0", 1)
    cache.put("emb", 1, "ps0", 1, np.ones(DIM, np.float32))
    cache.get("emb", 1)
    cache.get("emb", 2)

    class _Client:
        hot_row_cache = cache

    tel = WorkerTelemetry(worker_id=3, ps_client=_Client())
    snap = tel.maybe_snapshot(force=True)
    assert snap["cache_tables"]["emb"]["hits"] == 1
    assert snap["cache_tables"]["emb"]["misses"] == 1
    text = profiling.metrics.prometheus_text()
    assert 'edl_cache_hits_total{table="emb",worker="3"} 1' in text
    assert 'edl_cache_misses_total{table="emb",worker="3"} 1' in text


# ---------------------------------------------------------------------------
# Parameters / servicer integration
# ---------------------------------------------------------------------------


def test_parameters_tier_config_wraps_row_and_slot_tables(tmp_path):
    p = Parameters(
        tier_config={
            "warm_rows": 4,
            "spill_dir": os.path.join(str(tmp_path), "spill"),
        }
    )
    try:
        p.init_from_model(
            0,
            {"w": np.zeros((2, 2), np.float32)},
            [EmbeddingTableInfo("emb", DIM, "zeros")],
        )
        assert isinstance(p.embedding_params["emb"], TieredEmbeddingTable)
        p.create_slot_params(["m"], {"m": 0.0})
        assert isinstance(
            p.embedding_params["emb-m"], TieredEmbeddingTable
        )
        # restore swaps in replacement tiered tables over the same
        # spill dirs; the outgoing demoters must be gone first
        state = p.snapshot_state()
        p.restore_state(state)
        assert isinstance(p.embedding_params["emb"], TieredEmbeddingTable)
    finally:
        p.close()


def test_servicer_forwards_apply_notes_and_reports_tier_stats(tmp_path):
    p = Parameters(
        tier_config={
            "warm_rows": 4,
            "spill_dir": os.path.join(str(tmp_path), "spill"),
        }
    )
    s = PserverServicer(p, 1, optax.sgd(0.1), use_async=False)
    try:
        s.push_model(
            {
                "version": 0,
                "params": [Tensor("w", np.ones((2, 2), np.float32))],
                "embedding_infos": [{"name": "emb", "dim": DIM}],
            }
        )
        for step in range(4):
            ids = np.arange(step * 8, step * 8 + 8, dtype=np.int64)
            s.push_gradient(
                {
                    "model_version": step,
                    "gradients": [
                        Tensor(
                            "emb",
                            np.ones((8, DIM), np.float32),
                            indices=ids,
                        ),
                    ],
                }
            )
        table = p.embedding_params["emb"]
        # the delta note reached the tiered table's pin ring
        assert table._applied
        # overflow exists; the BACKGROUND demoter spills it (the one
        # thread-driven path in this suite). Mid-apply spills of a
        # step's own rows are legal and get superseded by the apply's
        # warm write (set pops the disk entry), so wait until rows are
        # actually RESIDENT on disk, not merely until a spill happened.
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if table.stats()["disk_rows"] > 0:
                break
            table.signal_pressure()
            time.sleep(0.02)
        assert table.stats()["spilled_rows"] > 0
        resp = s.ps_status({})
        assert resp["tiered"]["spilled_rows"] > 0
        assert resp["tiered"]["disk_rows"] > 0
        # pull the currently-cold ids back through the servicer: the
        # cold pull promotes them and the reply is well-formed
        with table._mu:
            cold = sorted(table._disk)
        assert cold
        out = s.pull_embedding_vector(
            {"name": "emb", "ids": np.asarray(cold, dtype=np.int64)}
        )
        assert out is not None
        assert s.ps_status({})["tiered"]["cold_pull_rows"] > 0
    finally:
        p.close()


def test_tiered_metrics_collector_exports_labeled_series(tmp_path):
    from elasticdl_tpu.utils import profiling

    t = TieredEmbeddingTable(
        EmbeddingTable("memb", DIM, "zeros"),
        os.path.join(str(tmp_path), "spill-memb"),
        warm_rows=2,
    )
    try:
        _fill(t, 6)
        _drain(t)
        t.get(np.arange(6, dtype=np.int64))
        text = profiling.metrics.prometheus_text()
        assert 'edl_tiered_disk_rows{table="memb"}' in text
        spilled = [
            ln
            for ln in text.splitlines()
            if ln.startswith("edl_tiered_spilled_rows_total")
            and 'table="memb"' in ln
        ]
        assert spilled and float(spilled[0].rsplit(" ", 1)[1]) > 0
    finally:
        t.close()
    # close unregisters the collector: the series disappears
    text = profiling.metrics.prometheus_text()
    assert 'edl_tiered_disk_rows{table="memb"}' not in text
