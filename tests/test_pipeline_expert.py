"""Pipeline (pp) and expert (ep) parallelism on the virtual 8-dev mesh.

Neither axis exists in the reference (SURVEY §2.2); both are built on
the same seam as dp/tp/sp — mesh axes + shard_map + explicit
collectives — so the elastic scheduler above is untouched.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elasticdl_tpu.parallel.expert import (
    make_moe_fn,
    reference_moe,
    top1_gate,
)
from elasticdl_tpu.parallel.mesh import create_mesh
from elasticdl_tpu.parallel.pipeline import (
    make_pipeline_fn,
    reference_pipeline,
    stack_stage_params,
)

D = 16


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def _stage_params(n_stages, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": rng.standard_normal((D, D)).astype(np.float32) * 0.3,
            "b": rng.standard_normal((D,)).astype(np.float32) * 0.1,
        }
        for _ in range(n_stages)
    ]


def test_pipeline_matches_sequential():
    mesh = create_mesh({"pipe": 4}, axis_names=("pipe",))
    stages = _stage_params(4)
    rng = np.random.default_rng(1)
    micro = rng.standard_normal((6, 8, D)).astype(np.float32)

    pipe = make_pipeline_fn(mesh, _stage_fn)
    stacked = stack_stage_params(stages)
    with mesh:
        got = np.asarray(jax.jit(pipe)(stacked, micro))
    want = np.asarray(reference_pipeline(_stage_fn, stages, micro))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_pipeline_gradients_match_sequential():
    mesh = create_mesh({"pipe": 4}, axis_names=("pipe",))
    stages = _stage_params(4, seed=2)
    rng = np.random.default_rng(3)
    micro = rng.standard_normal((4, 8, D)).astype(np.float32)
    pipe = make_pipeline_fn(mesh, _stage_fn)

    def loss_ring(stacked):
        return (pipe(stacked, micro) ** 2).sum()

    def loss_seq(per_stage):
        out = reference_pipeline(_stage_fn, per_stage, micro)
        return (out ** 2).sum()

    with mesh:
        g_ring = jax.jit(jax.grad(loss_ring))(stack_stage_params(stages))
    g_seq = jax.grad(loss_seq)(stages)
    for s in range(4):
        np.testing.assert_allclose(
            np.asarray(g_ring["w"][s]),
            np.asarray(g_seq[s]["w"]),
            rtol=3e-4,
            atol=3e-5,
        )


def test_pipeline_composes_with_data_parallel():
    mesh = create_mesh(
        {"data": 2, "pipe": 4}, axis_names=("data", "pipe")
    )
    stages = _stage_params(4, seed=4)
    rng = np.random.default_rng(5)
    micro = rng.standard_normal((3, 8, D)).astype(np.float32)
    pipe = make_pipeline_fn(mesh, _stage_fn, batch_axis="data")
    with mesh:
        got = np.asarray(jax.jit(pipe)(stack_stage_params(stages), micro))
    want = np.asarray(reference_pipeline(_stage_fn, stages, micro))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def _expert_fn(params, x):
    return jax.nn.relu(x @ params["w"]) @ params["wo"]


def _expert_params(n_experts, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": rng.standard_normal((D, 32)).astype(np.float32) * 0.2,
            "wo": rng.standard_normal((32, D)).astype(np.float32) * 0.2,
        }
        for _ in range(n_experts)
    ]


def test_moe_matches_dense_when_under_capacity():
    mesh = create_mesh({"expert": 8}, axis_names=("expert",))
    experts = _expert_params(8)
    rng = np.random.default_rng(1)
    x = rng.standard_normal((64, D)).astype(np.float32)
    logits = rng.standard_normal((64, 8)).astype(np.float32)

    moe = make_moe_fn(mesh, _expert_fn, capacity_factor=8.0)  # no overflow
    stacked = stack_stage_params(experts)
    with mesh:
        got = np.asarray(jax.jit(moe)(stacked, x, logits))
    want = np.asarray(reference_moe(_expert_fn, experts, x, logits))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_moe_gradients_flow_to_experts_and_gate():
    mesh = create_mesh({"expert": 4}, axis_names=("expert",))
    experts = _expert_params(4, seed=2)
    rng = np.random.default_rng(3)
    x = rng.standard_normal((32, D)).astype(np.float32)
    logits = rng.standard_normal((32, 4)).astype(np.float32)
    moe = make_moe_fn(mesh, _expert_fn, capacity_factor=8.0)

    def loss_routed(stacked, logits):
        return (moe(stacked, x, logits) ** 2).sum()

    def loss_dense(per_expert, logits):
        return (
            reference_moe(_expert_fn, per_expert, x, logits) ** 2
        ).sum()

    with mesh:
        g_stack, g_gate = jax.jit(jax.grad(loss_routed, argnums=(0, 1)))(
            stack_stage_params(experts), logits
        )
    g_dense, g_gate_ref = jax.grad(loss_dense, argnums=(0, 1))(
        experts, logits
    )
    for e in range(4):
        np.testing.assert_allclose(
            np.asarray(g_stack["w"][e]),
            np.asarray(g_dense[e]["w"]),
            rtol=3e-4,
            atol=3e-5,
        )
    np.testing.assert_allclose(
        np.asarray(g_gate), np.asarray(g_gate_ref), rtol=3e-4, atol=3e-5
    )


def test_moe_overflow_tokens_bypass():
    """capacity 1 with all tokens gated to one expert: only the first
    token per shard-bucket is served, the rest contribute zero."""
    mesh = create_mesh({"expert": 4}, axis_names=("expert",))
    experts = _expert_params(4, seed=6)
    x = np.ones((8, D), np.float32)
    logits = np.zeros((8, 4), np.float32)
    logits[:, 2] = 5.0  # everyone wants expert 2

    moe = make_moe_fn(mesh, _expert_fn, capacity_factor=1e-9)  # cap -> 1
    with mesh:
        got = np.asarray(
            jax.jit(moe)(stack_stage_params(experts), x, logits)
        )
    nonzero = np.abs(got).sum(axis=1) > 0
    assert nonzero.sum() == 1  # one token served, overflow bypassed
    idx, gate = top1_gate(jnp.asarray(logits))
    assert int(idx[0]) == 2


def test_moe_composes_with_data_parallel():
    mesh = create_mesh(
        {"data": 2, "expert": 4}, axis_names=("data", "expert")
    )
    experts = _expert_params(4, seed=7)
    rng = np.random.default_rng(8)
    x = rng.standard_normal((32, D)).astype(np.float32)
    logits = rng.standard_normal((32, 4)).astype(np.float32)
    moe = make_moe_fn(
        mesh, _expert_fn, batch_axis="data", capacity_factor=8.0
    )
    with mesh:
        got = np.asarray(jax.jit(moe)(stack_stage_params(experts), x, logits))
    want = np.asarray(reference_moe(_expert_fn, experts, x, logits))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_moe_transformer_trains_on_expert_mesh():
    """The transformer family with num_experts: routed MoE blocks over a
    data x expert mesh, gradients flowing end to end."""
    import optax

    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import TrainState, make_train_step
    from model_zoo.transformer_lm import transformer_lm as zoo

    mesh = create_mesh(
        {"data": 2, "expert": 4}, axis_names=("data", "expert")
    )
    model = zoo.custom_model(
        vocab_size=64,
        num_layers=1,
        mesh=mesh,
        num_experts=4,
        use_flash=False,
    )
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(8, 16)).astype(np.int32)
    variables = init_variables(
        model, jax.random.PRNGKey(0), {"tokens": tokens}
    )
    params, state = split_variables(variables)
    # expert params carry the stacked (E, ...) leading dim
    moe = params["block_0"]["moe_mlp"]
    assert moe["experts_up"].shape[0] == 4
    opt = optax.sgd(0.05)
    ts = TrainState.create(params, state, opt)
    step = make_train_step(model, zoo.loss, opt)
    with mesh:
        losses = []
        for i in range(3):
            ts, loss = step(
                ts, {"tokens": tokens}, tokens, jax.random.PRNGKey(i)
            )
            losses.append(float(loss))
    assert all(np.isfinite(losses))
    # experts received gradient (params moved)
    moved = np.abs(
        np.asarray(ts.params["block_0"]["moe_mlp"]["experts_up"])
        - np.asarray(moe["experts_up"])
    ).max()
    assert moved > 0


def test_moe_transformer_dense_fallback_matches_routed():
    """Same model, mesh vs no mesh: with generous capacity the routed
    forward equals the dense fallback."""
    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from model_zoo.transformer_lm import transformer_lm as zoo

    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 64, size=(4, 8)).astype(np.int32)

    dense_model = zoo.custom_model(
        vocab_size=64, num_layers=1, num_experts=4, use_flash=False
    )
    variables = init_variables(
        dense_model, jax.random.PRNGKey(0), {"tokens": tokens}
    )
    params, state = split_variables(variables)
    dense_out = dense_model.apply({"params": params, **state}, {"tokens": tokens})

    for shape, names in (
        ({"expert": 4}, ("expert",)),
        ({"data": 2, "expert": 4}, ("data", "expert")),
    ):
        mesh = create_mesh(shape, axis_names=names)
        routed_model = zoo.custom_model(
            vocab_size=64, num_layers=1, mesh=mesh, num_experts=4,
            use_flash=False, moe_capacity_factor=8.0,  # equality: no overflow
        )
        with mesh:
            routed_out = routed_model.apply(
                {"params": params, **state}, {"tokens": tokens}
            )
        np.testing.assert_allclose(
            np.asarray(dense_out),
            np.asarray(routed_out),
            rtol=2e-4,
            atol=2e-4,
            err_msg=str(shape),
        )


def test_topk_gate_renormalizes():
    from elasticdl_tpu.parallel.expert import topk_gate

    logits = np.array([[2.0, 1.0, 0.0, -1.0]], np.float32)
    idx, gate = topk_gate(jnp.asarray(logits), 2)
    assert idx.shape == (1, 2) and gate.shape == (1, 2)
    assert list(np.asarray(idx[0])) == [0, 1]
    np.testing.assert_allclose(float(gate.sum()), 1.0, rtol=1e-6)
    # relative odds of the two selected experts preserved
    np.testing.assert_allclose(
        float(gate[0, 0] / gate[0, 1]), np.e, rtol=1e-4
    )


def test_moe_top2_matches_dense_top2():
    mesh = create_mesh({"expert": 8}, axis_names=("expert",))
    experts = _expert_params(8, seed=5)
    rng = np.random.default_rng(6)
    x = rng.standard_normal((64, D)).astype(np.float32)
    logits = rng.standard_normal((64, 8)).astype(np.float32)

    moe = make_moe_fn(
        mesh, _expert_fn, capacity_factor=8.0, num_selected=2
    )
    stacked = stack_stage_params(experts)
    with mesh:
        got = np.asarray(jax.jit(moe)(stacked, x, logits))
    want = np.asarray(
        reference_moe(_expert_fn, experts, x, logits, num_selected=2)
    )
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_load_balancing_loss_calibration():
    from elasticdl_tpu.parallel.expert import load_balancing_loss

    e = 8
    # perfectly balanced: token i hard-routes to expert i%e, so BOTH the
    # f (top-1 fraction) and P (mean prob) terms are exercised at 1/e
    logits = np.tile(np.eye(e, dtype=np.float32) * 20.0, (4, 1))
    balanced = float(load_balancing_loss(jnp.asarray(logits)))
    np.testing.assert_allclose(balanced, 1.0, rtol=1e-4)
    # collapsed: every token hard-routes to expert 0
    collapsed = np.zeros((32, e), np.float32)
    collapsed[:, 0] = 20.0
    assert float(load_balancing_loss(jnp.asarray(collapsed))) > e - 1e-3


def test_moe_aux_loss_enters_train_step():
    """The train step's loss must include the sown aux_loss collection
    (gradients reach the router even when the task loss plateaus)."""
    import optax

    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.training.step import (
        TrainState,
        aux_loss_total,
        make_train_step,
    )
    from model_zoo.transformer_lm import transformer_lm as zoo

    model = zoo.custom_model(
        vocab_size=32,
        num_layers=1,
        num_experts=4,
        moe_num_selected=2,
        moe_aux_loss_coef=0.1,
        use_flash=False,
    )
    tokens = np.random.default_rng(0).integers(
        0, 32, size=(2, 16)
    ).astype(np.int32)
    variables = init_variables(
        model, jax.random.PRNGKey(0), {"tokens": tokens}
    )
    params, state = split_variables(variables)
    assert "aux_loss" in state
    opt = optax.sgd(0.01)
    ts = TrainState.create(params, state, opt)
    step = make_train_step(model, zoo.loss, opt)
    ts, loss = step(ts, {"tokens": tokens}, tokens, jax.random.PRNGKey(1))

    # manual forward: task loss + aux == step loss
    from elasticdl_tpu.nn.model_api import apply_model

    output, new_state = apply_model(
        model,
        ts.params,
        ts.state,
        {"tokens": tokens},
        training=True,
        rng=jax.random.PRNGKey(2),
    )
    aux = float(aux_loss_total(new_state))
    assert aux > 0.0  # coef 0.1 * lb-loss(>=1.0)
    step2 = make_train_step(model, zoo.loss, opt)
    _, loss2 = step2(ts, {"tokens": tokens}, tokens, jax.random.PRNGKey(2))
    manual = float(zoo.loss(output, tokens)) + aux
    np.testing.assert_allclose(float(loss2), manual, rtol=1e-4)


# -- the pipeline JOB PATH (PipelinedStack -> trainer -> worker) -------------


def _plain_to_staged(plain_params, num_layers, n_stages):
    """Transplant a plain TransformerLM's params into the pipelined
    model's structure (stacked stage subtree), so both models compute
    with identical values."""
    per = num_layers // n_stages
    stages = []
    for s in range(n_stages):
        stage = {}
        for i in range(per):
            stage["block_%d" % i] = plain_params["block_%d" % (s * per + i)]
        stages.append(stage)
    from elasticdl_tpu.parallel.pipeline import stack_stage_params

    return {
        "embed": plain_params["embed"],
        "RMSNorm_0": plain_params["RMSNorm_0"],
        "pipe": {"stages": stack_stage_params(stages)},
    }


def test_pipelined_transformer_matches_plain():
    """Forward logits and a 4-step dp x pp training run must match the
    plain (single-stage) model exactly (same params transplanted)."""
    import optax

    from elasticdl_tpu.nn.model_api import init_variables, split_variables
    from elasticdl_tpu.parallel.trainer import AllReduceTrainer
    from model_zoo.transformer_lm import transformer_lm as zoo

    cfg = dict(
        vocab_size=64, num_layers=2, num_heads=2, head_dim=8,
        embed_dim=32, mlp_dim=64,
    )
    b, l = 8, 16
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(b, l)).astype(np.int32)
    batch = {"tokens": tokens}

    plain = zoo.custom_model(**cfg)
    variables = init_variables(
        plain, jax.random.PRNGKey(0), {"tokens": tokens[:1]}
    )
    plain_params, _ = split_variables(variables)

    mesh = create_mesh(
        {"data": 4, "pipe": 2}, axis_names=("data", "pipe")
    )
    piped = zoo.build_distributed_model(
        mesh=mesh, pipeline_stages=2, **cfg
    )
    staged_params = _plain_to_staged(plain_params, cfg["num_layers"], 2)

    out_plain = plain.apply({"params": plain_params}, batch)
    out_piped = piped.apply({"params": staged_params}, batch)
    np.testing.assert_allclose(
        np.asarray(out_piped), np.asarray(out_plain), rtol=2e-4, atol=2e-4
    )

    # ragged batch (eval tail): pads internally, slices back
    ragged = {"tokens": tokens[:5]}
    np.testing.assert_allclose(
        np.asarray(piped.apply({"params": staged_params}, ragged)),
        np.asarray(plain.apply({"params": plain_params}, ragged)),
        rtol=2e-4,
        atol=2e-4,
    )

    # training: same curves through the ALLREDUCE trainer
    param_specs = zoo.param_shardings(mesh, pipeline_stages=2)
    t_plain = AllReduceTrainer(plain, zoo.loss, optax.sgd(0.05), seed=1)
    t_piped = AllReduceTrainer(
        piped, zoo.loss, optax.sgd(0.05), mesh=mesh,
        param_specs=param_specs, seed=1,
    )
    from elasticdl_tpu.training.step import TrainState

    def host_clone(tree):
        # donated steps delete input buffers; each trainer needs its own
        return jax.tree_util.tree_map(lambda a: np.array(a), tree)

    t_plain.load_state(
        TrainState.create(host_clone(plain_params), {}, optax.sgd(0.05))
    )
    t_piped.load_state(
        TrainState.create(host_clone(staged_params), {}, optax.sgd(0.05))
    )
    for step in range(4):
        l_plain = float(t_plain.train_step(batch, tokens))
        l_piped = float(t_piped.train_step(batch, tokens))
        np.testing.assert_allclose(l_piped, l_plain, rtol=2e-4)
    # stage params actually sharded over the pipe axis
    leaf = t_piped.train_state.params["pipe"]["stages"]
    first = jax.tree_util.tree_leaves(leaf)[0]
    assert "pipe" in str(first.sharding.spec)


def test_pipeline_job_path_through_worker(tmp_path):
    """The VERDICT done-criterion: a zoo config trains through the job
    path with stages > 1 — master task dispatch, the single-process
    ALLREDUCE worker (the CLI local-mode engine), pipelined model."""
    from elasticdl_tpu.common.constants import JobType
    from elasticdl_tpu.data.example import encode_example
    from elasticdl_tpu.data.recordio import RecordIOWriter
    from elasticdl_tpu.master.checkpoint_service import CheckpointService
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.worker.allreduce_worker import AllReduceWorker
    from tests.in_process_master import InProcessMaster
    from tests.test_utils import MODEL_ZOO_PATH

    rng = np.random.default_rng(0)
    path = str(tmp_path / "tokens.edlr")
    with RecordIOWriter(path) as f:
        for _ in range(64):
            f.write(
                encode_example(
                    {
                        "tokens": rng.integers(
                            0, 64, size=(64,), dtype=np.int64
                        )
                    }
                )
            )
    task_d = TaskDispatcher({path: (0, 64)}, {}, {}, 32, 1)
    master = MasterServicer(
        1, 16, None, task_d,
        checkpoint_service=CheckpointService("", 0, 0, False),
        use_async=True,
    )
    worker = AllReduceWorker(
        worker_id=0,
        job_type=JobType.TRAINING_ONLY,
        minibatch_size=16,
        model_zoo=MODEL_ZOO_PATH,
        model_def="transformer_lm.transformer_lm.custom_model",
        model_params=(
            "pipeline_stages=2,vocab_size=64,num_layers=2,num_heads=2,"
            "head_dim=8,embed_dim=32,mlp_dim=64"
        ),
        stub=InProcessMaster(master),
    )
    # the worker built the pipelined form over a data x pipe mesh
    assert worker.trainer.mesh.shape.get("pipe") == 2
    losses = worker.run()
    assert task_d.finished()
    assert worker.trainer.version == 4  # 64 records / batch 16
    assert all(np.isfinite(losses))
