"""Client API / CLI local-mode test.

Parity: reference scripts/client_test.sh rung-3 semantics (submit a job,
wait for success) executed in local mode: master in-process + inline
worker, deferred SAVE_MODEL export, checkpointing.
"""

import glob
import os

from elasticdl_tpu.api import cli_main
from tests.test_utils import MODEL_ZOO_PATH, DatasetName, create_recordio_file


def test_cli_train_local_single_process(tmp_path):
    create_recordio_file(
        128, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(tmp_path)
    )
    export_dir = str(tmp_path / "export")
    ckpt_dir = str(tmp_path / "ckpt")
    rc = cli_main(
        [
            "train",
            "--job_name", "cli-test",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", "mnist_subclass.mnist_subclass.CustomModel",
            "--minibatch_size", "16",
            "--num_epochs", "1",
            "--training_data", str(tmp_path),
            "--num_ps_pods", "0",
            "--use_async", "true",
            "--checkpoint_steps", "4",
            "--checkpoint_dir", ckpt_dir,
            "--output", export_dir,
        ]
    )
    assert rc == 0
    exported = glob.glob(os.path.join(export_dir, "*", "model.chkpt"))
    assert exported, "SAVE_MODEL export missing"
    assert glob.glob(os.path.join(ckpt_dir, "model_v*.chkpt"))


def test_cli_allreduce_train_then_evaluate_then_predict(tmp_path):
    """The full serving story through cli_main in local allreduce mode:
    train writes sharded checkpoints, evaluate and predict score them —
    no collective, one process (the hand-driven round-3 CLI drives,
    locked as a regression test)."""
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        64, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(data_dir)
    )
    ckpt_dir = str(tmp_path / "ckpt")
    common = [
        "--model_zoo", MODEL_ZOO_PATH,
        "--model_def", "mnist_subclass.mnist_subclass.CustomModel",
        "--minibatch_size", "16",
        "--num_workers", "0",
        "--num_ps_pods", "0",
        "--distribution_strategy", "AllreduceStrategy",
    ]
    rc = cli_main(
        ["train", "--job_name", "ar-train", "--num_epochs", "1",
         "--training_data", str(data_dir),
         "--checkpoint_dir", ckpt_dir, "--checkpoint_steps", "2"]
        + common
    )
    assert rc == 0
    assert glob.glob(os.path.join(ckpt_dir, "ckpt_v*")), "no sharded ckpts"

    rc = cli_main(
        ["evaluate", "--job_name", "ar-eval",
         "--validation_data", str(data_dir),
         "--checkpoint_dir", ckpt_dir]
        + common
    )
    assert rc == 0

    rc = cli_main(
        ["predict", "--job_name", "ar-pred",
         "--prediction_data", str(data_dir),
         "--checkpoint_dir", ckpt_dir]
        + common
    )
    assert rc == 0

    # a serving job without any model source is refused at the CLI gate
    rc = cli_main(
        ["evaluate", "--job_name", "no-src",
         "--validation_data", str(data_dir)]
        + common
    )
    assert rc == 2
    # and --checkpoint_dir alone is NOT accepted for the PS strategy,
    # whose master only initializes from a checkpoint file
    rc = cli_main(
        ["predict", "--job_name", "ps-no-src",
         "--prediction_data", str(data_dir),
         "--checkpoint_dir", ckpt_dir,
         "--model_zoo", MODEL_ZOO_PATH,
         "--model_def", "mnist_subclass.mnist_subclass.CustomModel",
         "--minibatch_size", "16", "--num_workers", "0",
         "--num_ps_pods", "0"]
    )
    assert rc == 2
