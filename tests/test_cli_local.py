"""Client API / CLI local-mode test.

Parity: reference scripts/client_test.sh rung-3 semantics (submit a job,
wait for success) executed in local mode: master in-process + inline
worker, deferred SAVE_MODEL export, checkpointing.
"""

import glob
import os

from elasticdl_tpu.api import cli_main
from tests.test_utils import MODEL_ZOO_PATH, DatasetName, create_recordio_file


def test_cli_train_local_single_process(tmp_path):
    create_recordio_file(
        128, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(tmp_path)
    )
    export_dir = str(tmp_path / "export")
    ckpt_dir = str(tmp_path / "ckpt")
    rc = cli_main(
        [
            "train",
            "--job_name", "cli-test",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", "mnist_subclass.mnist_subclass.CustomModel",
            "--minibatch_size", "16",
            "--num_epochs", "1",
            "--training_data", str(tmp_path),
            "--num_ps_pods", "0",
            "--use_async", "true",
            "--checkpoint_steps", "4",
            "--checkpoint_dir", ckpt_dir,
            "--output", export_dir,
        ]
    )
    assert rc == 0
    exported = glob.glob(os.path.join(export_dir, "*", "model.chkpt"))
    assert exported, "SAVE_MODEL export missing"
    assert glob.glob(os.path.join(ckpt_dir, "model_v*.chkpt"))
