"""Client API / CLI local-mode test.

Parity: reference scripts/client_test.sh rung-3 semantics (submit a job,
wait for success) executed in local mode: master in-process + inline
worker, deferred SAVE_MODEL export, checkpointing.
"""

import glob
import os

from elasticdl_tpu.api import cli_main
from tests.test_utils import MODEL_ZOO_PATH, DatasetName, create_recordio_file


def test_cli_train_local_single_process(tmp_path):
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        128, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(data_dir)
    )
    export_dir = str(tmp_path / "export")
    ckpt_dir = str(tmp_path / "ckpt")
    rc = cli_main(
        [
            "train",
            "--job_name", "cli-test",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", "mnist_subclass.mnist_subclass.CustomModel",
            "--minibatch_size", "16",
            "--num_epochs", "1",
            "--training_data", str(data_dir),
            "--num_ps_pods", "0",
            "--use_async", "true",
            "--checkpoint_steps", "4",
            "--checkpoint_dir", ckpt_dir,
            "--output", export_dir,
        ]
    )
    assert rc == 0
    exported = glob.glob(os.path.join(export_dir, "*", "model.chkpt"))
    assert exported, "SAVE_MODEL export missing"
    assert glob.glob(os.path.join(ckpt_dir, "model_v*.chkpt"))

    # the export is the standard artifact (docs/export.md): manifest +
    # orbax params + serialized serving forward for this dense model
    from elasticdl_tpu.common.export import is_export_dir, load_export

    artifact_dir = os.path.dirname(exported[0])
    assert is_export_dir(artifact_dir)
    loaded = load_export(artifact_dir)
    assert loaded.has_serving_fn(), "dense model should ship serving fn"
    assert loaded.metadata["model_def"].endswith("CustomModel")
    import numpy as np

    # the serving signature is the dataset_fn's PREDICTION feature
    # structure (here {"image": (b, 28, 28)})
    out = np.asarray(
        loaded.serve({"image": np.zeros((3, 28, 28), np.float32)})
    )
    assert out.shape == (3, 10) and np.isfinite(out).all()

    # and the artifact DIRECTORY feeds a serving job directly
    rc = cli_main(
        [
            "predict",
            "--job_name", "cli-pred-export",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", "mnist_subclass.mnist_subclass.CustomModel",
            "--minibatch_size", "16",
            "--prediction_data", str(data_dir),
            "--num_ps_pods", "0",
            "--checkpoint_filename_for_init", artifact_dir,
        ]
    )
    assert rc == 0


def test_cli_allreduce_train_then_evaluate_then_predict(tmp_path):
    """The full serving story through cli_main in local allreduce mode:
    train writes sharded checkpoints, evaluate and predict score them —
    no collective, one process (the hand-driven round-3 CLI drives,
    locked as a regression test)."""
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        64, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(data_dir)
    )
    ckpt_dir = str(tmp_path / "ckpt")
    common = [
        "--model_zoo", MODEL_ZOO_PATH,
        "--model_def", "mnist_subclass.mnist_subclass.CustomModel",
        "--minibatch_size", "16",
        "--num_workers", "0",
        "--num_ps_pods", "0",
        "--distribution_strategy", "AllreduceStrategy",
    ]
    rc = cli_main(
        ["train", "--job_name", "ar-train", "--num_epochs", "1",
         "--training_data", str(data_dir),
         "--checkpoint_dir", ckpt_dir, "--checkpoint_steps", "2",
         "--output", str(tmp_path / "export")]
        + common
    )
    assert rc == 0
    assert glob.glob(os.path.join(ckpt_dir, "ckpt_v*")), "no sharded ckpts"
    from elasticdl_tpu.common.export import is_export_dir

    exports = glob.glob(os.path.join(str(tmp_path / "export"), "*"))
    assert exports and is_export_dir(exports[0]), "allreduce export missing"

    rc = cli_main(
        ["evaluate", "--job_name", "ar-eval",
         "--validation_data", str(data_dir),
         "--checkpoint_dir", ckpt_dir]
        + common
    )
    assert rc == 0

    rc = cli_main(
        ["predict", "--job_name", "ar-pred",
         "--prediction_data", str(data_dir),
         "--checkpoint_dir", ckpt_dir]
        + common
    )
    assert rc == 0

    # a serving job without any model source is refused at the CLI gate
    rc = cli_main(
        ["evaluate", "--job_name", "no-src",
         "--validation_data", str(data_dir)]
        + common
    )
    assert rc == 2
    # and --checkpoint_dir alone is NOT accepted for the PS strategy,
    # whose master only initializes from a checkpoint file
    rc = cli_main(
        ["predict", "--job_name", "ps-no-src",
         "--prediction_data", str(data_dir),
         "--checkpoint_dir", ckpt_dir,
         "--model_zoo", MODEL_ZOO_PATH,
         "--model_def", "mnist_subclass.mnist_subclass.CustomModel",
         "--minibatch_size", "16", "--num_workers", "0",
         "--num_ps_pods", "0"]
    )
    assert rc == 2


def test_cli_local_default_ps_pods_actually_trains(tmp_path):
    """Local mode with the cluster-oriented default --num_ps_pods=1 must
    still train: the master holds the optimizer (a drive caught dense
    gradients being silently dropped — versions advanced, weights
    never moved, and sparse jobs crashed on the missing applier)."""
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    create_recordio_file(
        128, DatasetName.IMAGE_DEFAULT, (28, 28), temp_dir=str(data_dir)
    )
    ckpt_dir = str(tmp_path / "ckpt")
    rc = cli_main(
        [
            "train",
            "--job_name", "cli-default-ps",
            "--model_zoo", MODEL_ZOO_PATH,
            "--model_def", "mnist_subclass.mnist_subclass.CustomModel",
            "--minibatch_size", "16",
            "--num_epochs", "1",
            "--training_data", str(data_dir),
            # note: NO --num_ps_pods (defaults to 1)
            "--use_async", "true",
            "--checkpoint_steps", "4",
            "--checkpoint_dir", ckpt_dir,
        ]
    )
    assert rc == 0
    from elasticdl_tpu.common.model_utils import (
        load_from_checkpoint_file,
    )

    ckpts = sorted(glob.glob(os.path.join(ckpt_dir, "model_v*.chkpt")))
    assert len(ckpts) >= 2
    _, first = load_from_checkpoint_file(ckpts[0])
    _, last = load_from_checkpoint_file(ckpts[-1])
    import numpy as np

    moved = any(
        not np.array_equal(first[k], last[k]) for k in first
    )
    assert moved, "weights identical across checkpoints: not training"
