"""Partition hashing tests (parity: reference tests/hash_utils_test.py)."""

import unittest

import numpy as np

from elasticdl_tpu.common.hash_utils import (
    int_to_id,
    scatter_embedding_vector,
    string_to_id,
)


class HashUtilsTest(unittest.TestCase):
    def test_string_to_id_stable_and_bounded(self):
        for name in ("dense/kernel", "dense/bias", "emb"):
            sid = string_to_id(name, 4)
            self.assertEqual(sid, string_to_id(name, 4))
            self.assertTrue(0 <= sid < 4)

    def test_int_to_id(self):
        self.assertEqual(int_to_id(10, 4), 2)
        self.assertEqual(int_to_id(3, 4), 3)

    def test_scatter_embedding_vector(self):
        values = np.arange(12, dtype=np.float32).reshape(6, 2)
        ids = np.array([0, 1, 2, 3, 4, 8])
        groups = scatter_embedding_vector(values, ids, 4)
        np.testing.assert_array_equal(groups[0][1], [0, 4, 8])
        np.testing.assert_array_equal(groups[1][1], [1])
        np.testing.assert_array_equal(
            groups[0][0], values[np.array([0, 4, 5])]
        )


if __name__ == "__main__":
    unittest.main()
