"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; all sharding/collective tests run
against ``--xla_force_host_platform_device_count=8`` CPU devices, mirroring
the reference's "fake the cluster in one process" test strategy
(reference tests/in_process_master.py).

Env vars alone are not enough here: a sitecustomize may pre-register an
accelerator PJRT plugin and pin ``jax_platforms`` via jax.config at
interpreter startup, so we override through jax.config and drop any
already-initialized backends before the first test touches a device.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends
except ImportError:
    clear_backends = getattr(jax, "clear_backends", None)
if clear_backends is not None:
    clear_backends()

# Fail fast (not deep inside a sharding test) if the virtual mesh did not
# come up — e.g. a CPU client predating this file already latched XLA_FLAGS.
_n = len(jax.devices())
if _n < 8:
    raise RuntimeError(
        "test bootstrap expected >=8 virtual CPU devices, got %d; a JAX "
        "backend was initialized before conftest could apply XLA_FLAGS" % _n
    )
