"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; all sharding/collective tests run
against ``--xla_force_host_platform_device_count=8`` CPU devices, mirroring
the reference's "fake the cluster in one process" test strategy
(reference tests/in_process_master.py).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
