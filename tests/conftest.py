"""Test bootstrap: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is unavailable in CI; all sharding/collective tests run
against ``--xla_force_host_platform_device_count=8`` CPU devices, mirroring
the reference's "fake the cluster in one process" test strategy
(reference tests/in_process_master.py).

Env vars alone are not enough here: a sitecustomize may pre-register an
accelerator PJRT plugin and pin ``jax_platforms`` via jax.config at
interpreter startup, so we override through jax.config and drop any
already-initialized backends before the first test touches a device.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
try:
    from jax.extend.backend import clear_backends
except ImportError:
    clear_backends = getattr(jax, "clear_backends", None)
if clear_backends is not None:
    clear_backends()

# Fail fast (not deep inside a sharding test) if the virtual mesh did not
# come up — e.g. a CPU client predating this file already latched XLA_FLAGS.
_n = len(jax.devices())
if _n < 8:
    raise RuntimeError(
        "test bootstrap expected >=8 virtual CPU devices, got %d; a JAX "
        "backend was initialized before conftest could apply XLA_FLAGS" % _n
    )


# ---------------------------------------------------------------------------
# EDL_LOCKTRACE=1: runtime lock-order sanitizer + thread-leak guard
# ---------------------------------------------------------------------------
# The data-plane suites opt into the lockdep-style sanitizer
# (elasticdl_tpu/tools/locktrace.py): every threading.Lock/RLock their
# code creates joins a global acquisition graph and an ABBA inversion
# raises LockOrderError at acquire time instead of deadlocking the run.
# Additionally, EVERY test in a locktraced run asserts that no
# non-daemon thread it started is still alive at teardown — the
# leaked-helper-thread class edlint R4 polices statically.
# scripts/check.sh runs the data-plane suites this way as one gate.

import threading as _conftest_threading

import pytest

_LOCKTRACE_SUITES = {
    "test_input_pipeline",
    "test_ps_overlap",
    "test_async_concurrency",
    "test_elastic_pipeline",
    "test_compile_plane",
    "test_locktrace",
    "test_telemetry",
    "test_tracing",
    "test_wire",
    "test_dense_sharding",
    "test_comm_plane",
    "test_ps_snapshot",
    "test_ps_device_parity",
    "test_tiered_store",
    "test_chaos",
    "test_master_journal",
    "test_serving",
    "test_serving_batcher",
    "test_layout_solver",
}


@pytest.fixture(autouse=True)
def _edl_locktrace_and_thread_leak_guard(request):
    if os.environ.get("EDL_LOCKTRACE") != "1":
        yield
        return
    from elasticdl_tpu.tools import locktrace

    module = request.module.__name__.rsplit(".", 1)[-1]
    traced = module in _LOCKTRACE_SUITES
    if traced:
        locktrace.install()
    before = set(_conftest_threading.enumerate())
    try:
        yield
    finally:
        if traced:
            export_path = os.environ.get("EDL_LOCKTRACE_EXPORT")
            if export_path:
                # the witnessed-edge graph dies with the tracer; dump it
                # first so edlint --lock-coverage can cross-check the
                # static lock-order graph against what the suite saw
                locktrace.export(export_path)
            locktrace.uninstall()
        leaked = [
            t
            for t in _conftest_threading.enumerate()
            if t not in before and t.is_alive() and not t.daemon
        ]
        for t in leaked:
            t.join(timeout=2.0)
        leaked = [t.name for t in leaked if t.is_alive()]
        assert not leaked, (
            "non-daemon thread(s) leaked out of this test: %s "
            "(daemonize, join, or shut the owner down — edlint R4)"
            % ", ".join(leaked)
        )
