"""Tier-1 wiring + self-tests for the edlint analyzer
(elasticdl_tpu/tools/edlint, docs/static_analysis.md).

Three layers:

- the tree gate: ``python -m elasticdl_tpu.tools.edlint`` must exit 0
  over this repo with ALL seven rules active, and every allowlist
  ratchet entry must carry a reason (the acceptance bar);
- known-bad fixtures per rule R1–R7, each paired with the safe idiom
  the rule must NOT flag — the R4/R5/R6 bad fixtures are the REAL
  pre-fix violations this PR fixed (k8s_client's stop-less watcher,
  task_data_service's ack RPC reached through two calls under the
  ledger lock, worker/main's silent leave_comm_world swallow),
  pinned so the rules keep catching regressions of exactly those
  shapes;
- engine mechanics: the ratchet counts per (rule, file) and the
  ``--stale`` only-shrinks check.
"""

import os
import subprocess
import sys

from elasticdl_tpu.tools.edlint.core import (
    apply_ratchet,
    run,
    scan,
    stale_entries,
)
from elasticdl_tpu.tools.edlint.ratchet import ALLOW

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)


_case = [0]


def _lint(tmp_path, source, relpath="elasticdl_tpu/fixture.py"):
    """Rule ids found in ``source`` planted at ``relpath`` of a FRESH
    scratch tree (one per call, so fixtures never see each other; the
    ratchet keys on repo paths, so scratch files never hit allowlist
    budgets)."""
    _case[0] += 1
    root = tmp_path / ("case%d" % _case[0])
    target = root / relpath
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(source)
    findings, broken = scan(str(root))
    assert not broken, broken
    violations, _, _ = apply_ratchet(findings)
    return violations


def _rules_of(violations):
    return sorted({v.rule for v in violations})


# ---------------------------------------------------------------------------
# the tree gate
# ---------------------------------------------------------------------------


def test_tree_is_clean_under_all_seven_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "elasticdl_tpu.tools.edlint", "--stale"],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=ROOT,
    )
    assert proc.returncode == 0, (
        "edlint tripped on the tree:\n" + proc.stdout + proc.stderr
    )


def test_every_ratchet_entry_carries_a_reason():
    assert ALLOW, "ratchet exists"
    for rule_id, files in ALLOW.items():
        for path, entry in files.items():
            assert entry.get("max", 0) > 0, (rule_id, path)
            reason = entry.get("reason", "")
            assert isinstance(reason, str) and len(reason) > 20, (
                "allowlist entry without a substantive reason: "
                "%s %s" % (rule_id, path)
            )


def test_greps_guard_shim_message_compat(tmp_path):
    """The retired regex guard's report vocabulary survives in R1/R2
    (tests/test_greps_guard.py pins the subprocess contract)."""
    violations = _lint(
        tmp_path,
        "import jax\nimport queue\n"
        "def probe():\n"
        "    return jax.devices()\n"
        "def feed(q, item):\n"
        "    q.put(item)\n",
    )
    messages = "\n".join(v.message for v in violations)
    assert "jax.devices() outside escapable_call" in messages
    assert "queue put without timeout+cancel" in messages


# ---------------------------------------------------------------------------
# R1 — device probe
# ---------------------------------------------------------------------------


def test_r1_flags_calls_but_not_the_escapable_passthrough(tmp_path):
    bad = _lint(
        tmp_path,
        "import jax\n"
        "def probe():\n"
        "    return len(jax.devices())\n",
    )
    assert _rules_of(bad) == ["R1"]
    good = _lint(
        tmp_path,
        "import jax\n"
        "from elasticdl_tpu.common.escapable import escapable_call\n"
        "def probe():\n"
        "    # jax.devices passes UNCALLED: the safe idiom the old\n"
        "    # regex needed a backtick heuristic to avoid flagging\n"
        "    return escapable_call(jax.devices, timeout=30)\n",
    )
    assert not good


# ---------------------------------------------------------------------------
# R2 — queue put discipline
# ---------------------------------------------------------------------------


def test_r2_receiver_typing_and_boundedness(tmp_path):
    bad = _lint(
        tmp_path,
        "import queue\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._jobs = queue.Queue(maxsize=4)\n"
        "    def feed(self, item):\n"
        "        self._jobs.put(item)\n",
    )
    assert _rules_of(bad) == ["R2"], bad
    good = _lint(
        tmp_path,
        "import queue\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        # unbounded: put never blocks — safe BY CONSTRUCTION,\n"
        "        # no allowlist entry needed (the regex guard had to\n"
        "        # ratchet exactly this shape by hand)\n"
        "        self._jobs = queue.Queue()\n"
        "    def feed(self, item, cancel, q):\n"
        "        self._jobs.put(item)\n"
        "        while not cancel.is_set():\n"
        "            try:\n"
        "                q.put(item, timeout=0.5)\n"
        "                return True\n"
        "            except queue.Full:\n"
        "                continue\n"
        "        return False\n"
        "    def cache_fill(self, cache, k, v):\n"
        "        cache.put(k, v)\n",
    )
    assert not good


# ---------------------------------------------------------------------------
# R3 — data-plane queue get discipline
# ---------------------------------------------------------------------------


def test_r3_scoped_to_data_plane_with_receiver_typing(tmp_path):
    src = (
        "import queue\n"
        "def consume(opts):\n"
        "    q = queue.Queue(maxsize=1)\n"
        "    item = q.get()\n"
        "    mode = opts.get('mode')\n"  # dict .get: not a queue
        "    return item, mode\n"
    )
    bad = _lint(tmp_path, src, relpath="elasticdl_tpu/data/fixture.py")
    assert _rules_of(bad) == ["R3"], bad
    assert len(bad) == 1  # the dict .get did not count
    # identical code OUTSIDE the data plane is out of R3's scope
    assert not _lint(
        tmp_path, src, relpath="elasticdl_tpu/master/fixture.py"
    )
    good = _lint(
        tmp_path,
        "import queue\n"
        "def consume(cancel):\n"
        "    q = queue.Queue(maxsize=1)\n"
        "    while not cancel.is_set():\n"
        "        try:\n"
        "            return q.get(timeout=0.2)\n"
        "        except queue.Empty:\n"
        "            continue\n"
        "    return q.get_nowait()\n",
        relpath="elasticdl_tpu/data/fixture.py",
    )
    assert not good


# ---------------------------------------------------------------------------
# R4 — thread lifecycle (real pre-fix violation: k8s_client's watcher)
# ---------------------------------------------------------------------------

R4_PREFIX_VIOLATION = """
import threading

class Client:
    # pre-fix common/k8s_client.py: fire-and-forget daemon watcher,
    # no stop/close path anywhere on the owning class — the stream
    # thread could only be abandoned, never collected
    def __init__(self, event_cb):
        self._event_cb = event_cb
        threading.Thread(
            target=self._watch, name="event_watcher", daemon=True
        ).start()

    def _watch(self):
        while True:
            self._event_cb()
"""

R4_FIXED = """
import threading

class Client:
    # the fix that shipped: the thread is held, and close() gives the
    # class a shutdown path
    def __init__(self, event_cb):
        self._event_cb = event_cb
        self._watch_thread = threading.Thread(
            target=self._watch, name="event_watcher", daemon=True
        )
        self._watch_thread.start()

    def _watch(self):
        while True:
            self._event_cb()

    def close(self):
        self._watch_thread.join(timeout=5.0)
"""


def test_r4_pins_the_prefix_k8s_watcher_violation(tmp_path):
    assert _rules_of(_lint(tmp_path, R4_PREFIX_VIOLATION)) == ["R4"]
    assert not _lint(tmp_path, R4_FIXED)


def test_r4_non_daemon_thread_must_be_joined(tmp_path):
    bad = _lint(
        tmp_path,
        "import threading\n"
        "def fire(fn):\n"
        "    threading.Thread(target=fn).start()\n",
    )
    assert _rules_of(bad) == ["R4"]
    good = _lint(
        tmp_path,
        "import threading\n"
        "def run(fn):\n"
        "    t = threading.Thread(target=fn)\n"
        "    t.start()\n"
        "    t.join()\n",
    )
    assert not good


def test_r4_cancel_event_counts_as_shutdown_path(tmp_path):
    # the Dataset.prefetch idiom: generator finally sets the
    # producer's cancel event — a cancel path without a method name
    good = _lint(
        tmp_path,
        "import threading\n"
        "class D:\n"
        "    def stream(self):\n"
        "        cancel = threading.Event()\n"
        "        def produce():\n"
        "            while not cancel.is_set():\n"
        "                pass\n"
        "        t = threading.Thread(target=produce, daemon=True)\n"
        "        t.start()\n"
        "        try:\n"
        "            yield 1\n"
        "        finally:\n"
        "            cancel.set()\n",
    )
    assert not good


def test_r4_executor_must_be_shut_down(tmp_path):
    bad = _lint(
        tmp_path,
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._pool = ThreadPoolExecutor(max_workers=2)\n",
    )
    assert _rules_of(bad) == ["R4"]
    good = _lint(
        tmp_path,
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class P:\n"
        "    def __init__(self):\n"
        "        self._pool = ThreadPoolExecutor(max_workers=2)\n"
        "    def close(self):\n"
        "        self._pool.shutdown(wait=True)\n",
    )
    assert not good


# ---------------------------------------------------------------------------
# R5 — blocking under lock (real pre-fix violation: the ack RPC chain)
# ---------------------------------------------------------------------------

R5_PREFIX_VIOLATION = """
import threading

class TaskDataService:
    # pre-fix worker/task_data_service.py: report_record_done held the
    # ledger lock across _drain_acknowledged -> _acknowledge -> the
    # report_task_result MASTER RPC — a full round trip serializing
    # the fetcher's round checks and any concurrent spare-park requeue.
    # Lexically the RPC is two calls deep: only the transitive pass
    # sees it.
    def __init__(self, worker):
        self._worker = worker
        self._ledger_lock = threading.Lock()
        self._inflight = []

    def report_record_done(self, count):
        with self._ledger_lock:
            self._drain_acknowledged()

    def _drain_acknowledged(self):
        while self._inflight:
            self._acknowledge(self._inflight.pop())

    def _acknowledge(self, task):
        self._worker.report_task_result(task, "")
"""

R5_FIXED = """
import threading

class TaskDataService:
    # the fix that shipped: snapshot under the lock, send after release
    def __init__(self, worker):
        self._worker = worker
        self._ledger_lock = threading.Lock()
        self._inflight = []

    def report_record_done(self, count):
        outbox = []
        with self._ledger_lock:
            self._drain_acknowledged(outbox)
        for task in outbox:
            self._worker.report_task_result(task, "")

    def _drain_acknowledged(self, outbox):
        while self._inflight:
            outbox.append(self._inflight.pop())
"""


def test_r5_pins_the_prefix_ack_rpc_chain(tmp_path):
    bad = _lint(tmp_path, R5_PREFIX_VIOLATION)
    assert _rules_of(bad) == ["R5"]
    assert "report_task_result" in bad[0].message  # names the sink
    assert not _lint(tmp_path, R5_FIXED)


def test_r5_direct_blocking_forms(tmp_path):
    bad = _lint(
        tmp_path,
        "import threading, time\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def step(self):\n"
        "        with self._lock:\n"
        "            time.sleep(0.5)\n",
    )
    assert _rules_of(bad) == ["R5"]


def test_r5_sees_acquire_try_finally_release_regions(tmp_path):
    bad = _lint(
        tmp_path,
        "import threading, time\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def step(self):\n"
        "        self._lock.acquire()\n"
        "        try:\n"
        "            time.sleep(0.5)\n"
        "        finally:\n"
        "            self._lock.release()\n",
    )
    assert _rules_of(bad) == ["R5"]


def test_r5_condition_wait_under_its_own_lock_is_fine(tmp_path):
    good = _lint(
        tmp_path,
        "import threading\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._cond = threading.Condition()\n"
        "    def step(self):\n"
        "        with self._cond:\n"
        "            self._cond.wait(timeout=1.0)\n",
    )
    assert not good


# ---------------------------------------------------------------------------
# R6 — silent broad except (real pre-fix violation: worker/main's
# swallowed leave announcement)
# ---------------------------------------------------------------------------

R6_PREFIX_VIOLATION = """
def announce_leave(stub, worker_id):
    # pre-fix worker/main.py: a missed leave announcement vanished —
    # nothing in any log tied a later spurious reform to this miss
    try:
        if stub is not None:
            stub.leave_comm_world(worker_id)
    except Exception:
        pass
"""

R6_FIXED = """
import logging
logger = logging.getLogger(__name__)

def announce_leave(stub, worker_id):
    try:
        if stub is not None:
            stub.leave_comm_world(worker_id)
    except Exception:
        logger.debug("leave announcement missed", exc_info=True)
"""


def test_r6_pins_the_prefix_silent_swallow(tmp_path):
    assert _rules_of(_lint(tmp_path, R6_PREFIX_VIOLATION)) == ["R6"]
    assert not _lint(tmp_path, R6_FIXED)


def test_r6_narrowed_types_pass(tmp_path):
    good = _lint(
        tmp_path,
        "def load_native():\n"
        "    try:\n"
        "        import ctypes\n"
        "        return ctypes\n"
        "    except (ImportError, OSError):\n"
        "        pass\n"
        "    return None\n",
    )
    assert not good


def test_r6_reraise_and_real_work_pass(tmp_path):
    good = _lint(
        tmp_path,
        "def f(x):\n"
        "    try:\n"
        "        return 1 / x\n"
        "    except Exception:\n"
        "        raise RuntimeError('bad x') from None\n"
        "def g(x, fallback):\n"
        "    try:\n"
        "        return 1 / x\n"
        "    except Exception:\n"
        "        return fallback(x)\n",
    )
    assert not good


# ---------------------------------------------------------------------------
# R7 — jit purity
# ---------------------------------------------------------------------------

R7_BAD = """
import jax

class Trainer:
    def make_step(self, opt):
        def step(ts, batch):
            # host side effects inside traced code: the print fires
            # once per TRACE (then silently never again), and the
            # self-mutation records only the tracer's abstract value
            print("step", ts.version)
            self.last_batch = batch
            return opt.update(ts, batch)
        return jax.jit(step, donate_argnums=(0,))
"""

R7_GOOD = """
import jax
import jax.numpy as jnp

def make_step(opt):
    def step(ts, batch):
        jax.debug.print("step {v}", v=ts.version)  # trace-aware: fine
        loss = jnp.sum(batch)
        return opt.update(ts, batch), loss
    return jax.jit(step, donate_argnums=(0,))

@jax.jit
def fwd(params, x):
    return params @ x
"""


def test_r7_flags_host_effects_in_traced_functions(tmp_path):
    bad = _lint(tmp_path, R7_BAD)
    assert _rules_of(bad) == ["R7"]
    assert not _lint(tmp_path, R7_GOOD)


def test_r7_flags_telemetry_registry_calls_in_traced_code(tmp_path):
    bad = _lint(
        tmp_path,
        "import jax\n"
        "from elasticdl_tpu.utils import profiling\n"
        "def step(ts, batch):\n"
        "    profiling.counters.inc('step/hits')\n"
        "    return ts\n"
        "jax.jit(step)\n",
    )
    assert _rules_of(bad) == ["R7"]
    assert "records telemetry" in bad[0].message
    # the same call OUTSIDE traced code is the intended idiom
    good = _lint(
        tmp_path,
        "import jax\n"
        "from elasticdl_tpu.utils import profiling\n"
        "def step(ts, batch):\n"
        "    return ts\n"
        "def drive(ts, batch):\n"
        "    profiling.counters.inc('step/hits')\n"
        "    profiling.events.emit('resize_begin')\n"
        "    return jax.jit(step)(ts, batch)\n",
    )
    assert not good


def test_r7_sees_decorator_and_shard_map_forms(tmp_path):
    bad = _lint(
        tmp_path,
        "import jax, functools, logging\n"
        "logger = logging.getLogger(__name__)\n"
        "@functools.partial(jax.jit, donate_argnums=(0,))\n"
        "def step(ts, batch):\n"
        "    logger.info('stepping %s', ts)\n"
        "    return ts\n"
        "def build(mesh, shard_map):\n"
        "    def body(tree):\n"
        "        global _seen\n"
        "        _seen = tree\n"
        "        return tree\n"
        "    return jax.jit(shard_map(body, mesh=mesh))\n",
    )
    assert _rules_of(bad) == ["R7"]
    assert len(bad) == 2


# ---------------------------------------------------------------------------
# engine mechanics
# ---------------------------------------------------------------------------


def test_ratchet_counts_per_rule_and_file(tmp_path):
    (tmp_path / "elasticdl_tpu").mkdir()
    (tmp_path / "elasticdl_tpu" / "two.py").write_text(
        "import jax\n"
        "def a():\n"
        "    return jax.devices()\n"
        "def b():\n"
        "    return jax.devices()\n"
    )
    findings, _ = scan(str(tmp_path))
    allow = {
        "R1": {
            "elasticdl_tpu/two.py": {"max": 1, "reason": "test budget"}
        }
    }
    violations, counts, allowed = apply_ratchet(findings, allow=allow)
    assert counts[("R1", "elasticdl_tpu/two.py")] == 2
    assert len(allowed) == 1 and len(violations) == 1
    # the ratchet suppresses in line order: the SECOND site is the
    # violation, so a new site past the budget always surfaces
    assert violations[0].lineno > allowed[0].lineno


def test_stale_entries_enforce_only_shrinks(tmp_path):
    (tmp_path / "elasticdl_tpu").mkdir()
    (tmp_path / "elasticdl_tpu" / "one.py").write_text(
        "import jax\n"
        "def a():\n"
        "    return jax.devices()\n"
    )
    allow = {
        "R1": {
            "elasticdl_tpu/one.py": {"max": 3, "reason": "too wide"},
            "elasticdl_tpu/gone.py": {"max": 1, "reason": "deleted"},
        }
    }
    _, counts, _ = run(str(tmp_path), allow=allow)
    stale = stale_entries(counts, allow=allow)
    assert ("R1", "elasticdl_tpu/one.py", 1, 3) in stale
    assert ("R1", "elasticdl_tpu/gone.py", 0, 1) in stale
